"""Fault-tolerant multi-host build (parallel/multihost_build.py; docs/21).

Two layers, mirroring docs/21's failure-mode matrix:

  - the **WorkClaims protocol** (lifecycle/lease.py), over BOTH LogStore
    backends: done records are final; an expired claim is reclaimed by
    exactly one racer (the CAS, not luck, picks the winner); a fenced
    zombie's renew/complete lose deterministically and land journal
    ``fence`` records; torn claim writes read as absent and are
    reclaimed over the burned generation; and a holder whose measured
    store RTT ate its margin stands down BEFORE wall-clock expiry.
  - the **end-to-end build**: two subprocess hosts produce a per-bucket
    byte-identical index to the single-process build, and a SIGKILLed
    host mid-route costs one claim TTL, not the build — the survivor
    completes the same bytes and the journal proves exactly ONE
    ``claim.commit`` per build.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
from hyperspace_tpu.io.parquet import bucket_id_of_file
from hyperspace_tpu.lifecycle import journal as lifecycle_journal
from hyperspace_tpu.lifecycle.lease import WorkClaims
from hyperspace_tpu.parallel import multihost_build
from hyperspace_tpu.telemetry.perf_ledger import store_for

BOTH_STORES = ["hyperspace_tpu.io.log_store.PosixLogStore",
               "hyperspace_tpu.io.log_store.EmulatedObjectStore"]


def _session(tmp_path, store_class=BOTH_STORES[0]):
    s = HyperspaceSession(system_path=str(tmp_path / "ix"))
    s.conf.set("hyperspace.index.logStoreClass", store_class)
    return s


def _claims(s, owner, ttl_s=0.5):
    store = store_for(s.conf, os.path.join(str(s.conf.system_path),
                                           "_claims_test"))
    return WorkClaims(store, s.conf, owner=owner, ttl_s=ttl_s)


def _claim_events(conf):
    return [r for r in lifecycle_journal.records(conf)
            if r.get("decision") == "claim"]


# ---------------------------------------------------------------------------
# WorkClaims protocol (in-process, both backends)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("store_class", BOTH_STORES)
class TestWorkClaims:
    def test_claim_complete_is_final(self, tmp_path, store_class):
        s = _session(tmp_path, store_class)
        a = _claims(s, "a", ttl_s=5.0)
        b = _claims(s, "b", ttl_s=5.0)
        claim = a.try_claim("chunk-00000")
        assert claim is not None and claim["epoch"] == 1
        assert b.try_claim("chunk-00000") is None      # live holder
        assert a.renew(claim)                          # extends, bumps gen
        assert a.complete(claim, {"rows": 7})
        assert a.result("chunk-00000") == {"rows": 7}
        assert b.try_claim("chunk-00000") is None      # done is FINAL
        assert b.pending(["chunk-00000", "chunk-00001"]) == ["chunk-00001"]
        modes = [e["mode"] for e in _claim_events(s.conf)]
        assert "acquire" in modes and "complete" in modes

    def test_expired_reclaim_fences_zombie(self, tmp_path, store_class):
        s = _session(tmp_path, store_class)
        a = _claims(s, "a", ttl_s=0.3)
        b = _claims(s, "b", ttl_s=5.0)
        stale = a.try_claim("group-000")
        assert stale is not None
        time.sleep(0.4)                                # a's TTL runs out
        taken = b.try_claim("group-000")
        assert taken is not None and taken["epoch"] == 2
        # The zombie wakes: both its renew and its complete lose the CAS.
        assert a.renew(stale) is False
        assert a.complete(stale, {"rows": 1}) is False
        assert b.complete(taken, {"rows": 2})
        assert b.result("group-000") == {"rows": 2}    # the winner's bytes
        modes = [e["mode"] for e in _claim_events(s.conf)]
        assert "reclaim" in modes and modes.count("fence") == 2

    def test_double_reclaim_single_winner(self, tmp_path, store_class):
        """Two racers both observe the SAME expired generation; the CAS
        lets exactly one through — the loser gets None, not a claim."""
        s = _session(tmp_path, store_class)
        a = _claims(s, "a", ttl_s=0.2)
        b = _claims(s, "b", ttl_s=5.0)
        c = _claims(s, "c", ttl_s=5.0)
        assert a.try_claim("chunk-00003") is not None
        time.sleep(0.3)
        stale_read = c.get("chunk-00003")              # c reads FIRST ...
        won = b.try_claim("chunk-00003")               # ... then b commits
        assert won is not None and won["epoch"] == 2
        c.get = lambda item: stale_read                # c acts on its read
        assert c.try_claim("chunk-00003") is None      # CAS loss, no claim
        rec, _g = b.get("chunk-00003")
        assert rec["holder"] == "b"

    def test_torn_claim_reads_absent_then_reclaimed(self, tmp_path,
                                                    store_class):
        s = _session(tmp_path, store_class)
        a = _claims(s, "a", ttl_s=5.0)
        # A torn put burned a real generation with unparseable bytes.
        assert a.store.put_if_generation_match(
            WorkClaims.PREFIX + "chunk-00001", b"\x00torn not json", 0)
        rec, gen = a.get("chunk-00001")
        assert rec is None and gen >= 1                # absent, gen burned
        claim = a.try_claim("chunk-00001")
        assert claim is not None
        assert claim["epoch"] > gen                    # monotonic past it
        assert a.complete(claim, {})
        modes = [e["mode"] for e in _claim_events(s.conf)]
        assert "reclaim" in modes                      # takeover, not fresh

    def test_rtt_margin_stands_down_before_expiry(self, tmp_path,
                                                  store_class):
        """Clock-skew / slow-store stand-down: when the measured store
        RTT eats the safety margin, ``holds`` goes False while the wall
        clock still shows a live claim — the holder renews (or
        discards) instead of committing into a possible takeover."""
        s = _session(tmp_path, store_class)
        a = _claims(s, "a", ttl_s=0.9)
        b = _claims(s, "b", ttl_s=5.0)
        claim = a.try_claim("group-001")
        assert claim is not None
        a._lat_ewma_s = 10.0                           # degraded store link
        assert a.margin_s() == pytest.approx(0.3)      # clamped to TTL/3
        time.sleep(0.65)                               # inside the margin
        assert time.time() < claim["expires_at"]       # NOT yet expired...
        assert not a.holds(claim)                      # ...but stands down
        assert b.try_claim("group-001") is None        # successor waits
        assert a.renew(claim)                          # CAS still ours
        assert a.holds(claim)                          # fresh TTL again


# ---------------------------------------------------------------------------
# End-to-end: N subprocess hosts, one index
# ---------------------------------------------------------------------------
N_ROWS = 24000


@pytest.fixture(scope="module")
def mh_source(tmp_path_factory):
    root = tmp_path_factory.mktemp("mh_src")
    rng = np.random.default_rng(7)
    t = pa.table({
        "k": pa.array(rng.integers(0, 500, size=N_ROWS), type=pa.int64()),
        "g": pa.array(rng.integers(0, 7, size=N_ROWS), type=pa.int64()),
        "v": pa.array(rng.integers(0, 1000, size=N_ROWS), type=pa.int64()),
    })
    step = -(-N_ROWS // 3)
    for f in range(3):
        pq.write_table(t.slice(f * step, step),
                       os.path.join(str(root), f"part-{f:05d}.parquet"))
    return str(root)


def _mh_session(tmp_path, src, hosts):
    s = HyperspaceSession(system_path=str(tmp_path / f"ix_h{hosts}"))
    s.conf.num_buckets = 8
    s.conf.device_batch_rows = 4096
    s.conf.device_build_min_rows = 0       # host route path on every host
    s.conf.multihost_build_hosts = hosts
    s.conf.multihost_build_claim_ttl_s = 1.5
    s.conf.multihost_build_poll_s = 0.02
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(src), IndexConfig("mh", ["k"], ["g", "v"]))
    return s, hs


def _bucket_digests(s):
    entry = s.index_collection_manager.get_index("mh")
    out = {}
    for fi in entry.content.file_infos():
        with open(fi.name, "rb") as fh:
            out.setdefault(bucket_id_of_file(fi.name), []).append(
                hashlib.sha256(fh.read()).hexdigest())
    return {b: sorted(v) for b, v in out.items()}


@pytest.fixture(scope="module")
def single_host_digests(mh_source, tmp_path_factory):
    s, _hs = _mh_session(tmp_path_factory.mktemp("mh_single"), mh_source, 0)
    return _bucket_digests(s)


def test_two_host_build_bit_equal(tmp_path, mh_source, single_host_digests):
    s, hs = _mh_session(tmp_path, mh_source, 2)
    assert _bucket_digests(s) == single_host_digests
    props = hs.last_build_report().properties
    assert props["multihost_hosts"] == 2
    assert props["multihost_chunks"] >= 2
    assert props["multihost_groups"] >= 2
    assert props["multihost_route_wall_s"] > 0
    # Exactly one commit record for the whole build.
    commits = [e for e in _claim_events(s.conf) if e["mode"] == "commit"]
    assert len(commits) == 1
    # Scratch is gone; no claims left behind for the doctor to grade.
    assert multihost_build.scan_build_claims(s.conf) == []


def test_sigkill_mid_route_survivor_completes(tmp_path, mh_source,
                                              single_host_digests,
                                              monkeypatch):
    """SIGKILL one host once routing is underway: the survivor reclaims
    the victim's expired claims and lands the byte-identical index,
    with exactly one journalled commit."""
    killed = {}
    orig_spawn = multihost_build.spawn_hosts

    def spawn_and_kill(conf, build_id, n):
        procs = orig_spawn(conf, build_id, n)
        store = multihost_build._store(conf, build_id)
        watch = WorkClaims(store, conf, owner="watcher", ttl_s=1.0)

        def watcher():
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not killed:
                done = sum(
                    1 for key in store.list_keys(WorkClaims.PREFIX)
                    if (rec := watch.get(key[len(WorkClaims.PREFIX):])[0])
                    and rec.get("done")
                    and rec["item"].startswith("chunk-"))
                if done >= 1 and procs[0].poll() is None:
                    os.kill(procs[0].pid, signal.SIGKILL)
                    killed["after_chunks"] = done
                    return
                time.sleep(0.02)

        threading.Thread(target=watcher, daemon=True).start()
        return procs

    monkeypatch.setattr(multihost_build, "spawn_hosts", spawn_and_kill)
    s, _hs = _mh_session(tmp_path, mh_source, 2)
    assert killed, "watcher never fired; the drill proved nothing"
    assert _bucket_digests(s) == single_host_digests
    events = _claim_events(s.conf)
    commits = [e for e in events if e["mode"] == "commit"]
    assert len(commits) == 1               # exactly-once, journal-proven
    # Every item's done record exists exactly once (the claim table is
    # the ledger; one done record per item is what made commit safe).
    done_items = [e["item"] for e in events if e["mode"] == "complete"]
    assert len(done_items) == len(set(done_items))
    assert multihost_build.scan_build_claims(s.conf) == []
