"""Distributed request tracing + the flight recorder
(telemetry/flight_recorder.py, interop trace context; docs/16, docs/07).

The contract under test: a trace id minted on the CLIENT names the
request end to end — the server adopts it (malformed ids are replaced,
never rejected), every response echoes it, the flight recorder keeps the
interesting tail under it (slow/error/deadline/shed always, healthy
sampled, ring bounded with healthy evicted first), and a drain persists
the ring as a diagnostics bundle readable after restart over BOTH
LogStore backends."""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, col
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.interop.query import (
    mint_trace_id,
    pop_trace_context,
    valid_trace_id,
)
from hyperspace_tpu.interop.server import (
    QueryClient,
    QueryFailedError,
    QueryServer,
    parse_wire_error,
)
from hyperspace_tpu.telemetry import flight_recorder, metrics, trace
from hyperspace_tpu.telemetry.flight_recorder import FlightRecorder

BOTH_STORES = ("hyperspace_tpu.io.log_store.PosixLogStore",
               "hyperspace_tpu.io.log_store.EmulatedObjectStore")


@pytest.fixture(autouse=True)
def _fresh_ring():
    flight_recorder.reset()
    yield
    flight_recorder.reset()


@pytest.fixture(scope="module")
def big_dir(tmp_path_factory):
    """A table big enough that a group-by takes real wall time — the
    deadline of the end-to-end demo must expire SERVER-SIDE, mid-query."""
    d = str(tmp_path_factory.mktemp("flight") / "big")
    os.makedirs(d)
    rng = np.random.default_rng(13)
    n = 4_000_000
    pq.write_table(pa.table({
        "g": pa.array(rng.integers(0, 1_000_000, n), type=pa.int64()),
        "x": pa.array(rng.random(n)),
    }), os.path.join(d, "p.parquet"))
    return d


@pytest.fixture()
def env(tmp_path):
    data = str(tmp_path / "data")
    os.makedirs(data)
    n = 1000
    pq.write_table(pa.table({
        "k": pa.array(np.arange(n, dtype=np.int64)),
        "v": pa.array((np.arange(n) % 5).astype(np.int64)),
    }), os.path.join(data, "f.parquet"))
    s = HyperspaceSession(system_path=str(tmp_path / "ix"))
    s.conf.num_buckets = 4
    return s, data


def _point_spec(data, k):
    return {"source": {"format": "parquet", "path": data},
            "filter": {"op": "==", "col": "k", "value": int(k)},
            "select": ["k", "v"]}


def _slow_spec(big_dir):
    return {"source": {"format": "parquet", "path": big_dir},
            "group_by": ["g"], "aggs": {"t": ["x", "sum"]},
            "sort": [["t", False]], "limit": 5}


def _wait_for_record(trace_id, timeout_s=30.0):
    deadline_at = time.monotonic() + timeout_s
    while time.monotonic() < deadline_at:
        rec = flight_recorder.recorder().find(trace_id)
        if rec is not None:
            return rec
        time.sleep(0.02)
    return None


# ---------------------------------------------------------------------------
# Trace-context parsing: malformed ids must never reject a request
# ---------------------------------------------------------------------------
class TestTraceContextParsing:
    def test_mint_shape(self):
        tid = mint_trace_id()
        assert valid_trace_id(tid)
        assert len(tid) == 16
        assert mint_trace_id() != tid  # 8 random bytes, not a counter

    def test_valid_ids_adopted_and_popped(self):
        spec = {"trace_id": "00ff00ff00ff00ff",
                "request_id": "1234567890abcdef", "sql": "x"}
        tid, rid, adopted = pop_trace_context(spec)
        assert adopted
        assert tid == "00ff00ff00ff00ff" and rid == "1234567890abcdef"
        assert "trace_id" not in spec and "request_id" not in spec

    def test_uppercase_normalizes(self):
        tid, _rid, adopted = pop_trace_context(
            {"trace_id": "00FF00FF00FF00FF"})
        assert adopted and tid == "00ff00ff00ff00ff"

    @pytest.mark.parametrize("bad", [
        "short",                      # wrong length (too short)
        "00ff00ff00ff00ff00",         # wrong length (too long)
        "zzzzzzzzzzzzzzzz",           # non-hex, right length
        "00ff00ff00ff00f ",           # embedded space
        "",                           # empty string
        1234567890123456,             # not a string
        12.5,
        None,
        True,
        ["00ff00ff00ff00ff"],         # list-wrapped
        {"id": "00ff00ff00ff00ff"},   # dict-wrapped
    ])
    def test_malformed_ids_fall_back_to_minted(self, bad):
        spec = {"trace_id": bad, "request_id": bad, "source": {}}
        tid, rid, adopted = pop_trace_context(spec)
        assert not adopted
        assert valid_trace_id(tid) and valid_trace_id(rid)
        assert "trace_id" not in spec and "request_id" not in spec

    def test_missing_ids_minted_independently(self):
        tid, rid, adopted = pop_trace_context({})
        assert not adopted and valid_trace_id(tid) and valid_trace_id(rid)
        # valid trace_id + garbage request_id: trace adopted, request
        # minted — the fields degrade independently.
        tid2, rid2, adopted2 = pop_trace_context(
            {"trace_id": "a" * 16, "request_id": "nope"})
        assert adopted2 and tid2 == "a" * 16 and valid_trace_id(rid2)

    def test_malformed_id_never_rejects_the_request(self, env):
        """End to end: a garbage trace_id still answers OK, under a
        server-minted id."""
        s, data = env
        with QueryServer(s) as server:
            with QueryClient(server.address) as qc:
                out = qc.query({**_point_spec(data, 3),
                                "trace_id": "!!not-hex-at-all!!",
                                "request_id": 42})
                assert out.num_rows == 1
                assert valid_trace_id(qc.last_trace_id)
                assert qc.last_trace_id != "!!not-hex-at-all!!"


# ---------------------------------------------------------------------------
# Wire echo + parse_wire_error
# ---------------------------------------------------------------------------
class TestWireEcho:
    def test_ok_echoes_adopted_id(self, env):
        s, data = env
        tid = mint_trace_id()
        with QueryServer(s) as server:
            with QueryClient(server.address) as qc:
                qc.query({**_point_spec(data, 1), "trace_id": tid})
                assert qc.last_trace_id == tid

    def test_error_carries_trace_id(self, env):
        s, data = env
        spec = {"source": {"format": "parquet", "path": data},
                "filter": {"op": "==", "col": "no_such", "value": 1}}
        with QueryServer(s) as server:
            with QueryClient(server.address) as qc:
                with pytest.raises(QueryFailedError) as ei:
                    qc.query(spec)
        assert ei.value.code == "FAILED"
        assert valid_trace_id(ei.value.trace_id)
        assert ei.value.trace_id == qc.last_trace_id
        # The echo is a trailing token, not part of the message.
        assert "trace=" not in ei.value.message

    def test_parse_wire_error_trace_forms(self):
        e = parse_wire_error("ERR BUSY queue full trace=00ff00ff00ff00ff")
        assert e.code == "BUSY" and e.trace_id == "00ff00ff00ff00ff"
        assert e.message == "queue full"
        # Pre-trace server: no token, trace_id None — old wire accepted.
        e = parse_wire_error("ERR BUSY queue full")
        assert e.code == "BUSY" and e.trace_id is None
        # Bare pre-taxonomy form with an echo still parses.
        e = parse_wire_error("ERR something broke trace=aaaaaaaaaaaaaaaa")
        assert e.code == "FAILED" and e.trace_id == "a" * 16
        assert e.message == "something broke"
        # A message that merely CONTAINS trace= mid-sentence is left alone.
        e = parse_wire_error("ERR FAILED trace=zz is not an id")
        assert e.trace_id is None and "trace=zz" in e.message

    def test_badreq_on_unparseable_line_still_echoes(self, env):
        """Even a request that fails JSON parsing gets a (server-minted)
        trace id on its ERR line."""
        import socket as socketlib

        s, _data = env
        with QueryServer(s) as server:
            with socketlib.create_connection(server.address) as sock:
                sock.sendall(b"this is not json\n")
                line = sock.makefile("rb").readline().decode()
        assert line.startswith("ERR BADREQ")
        err = parse_wire_error(line.rstrip("\n"))
        assert valid_trace_id(err.trace_id)


# ---------------------------------------------------------------------------
# Retention policy
# ---------------------------------------------------------------------------
def _conf(**over):
    c = HyperspaceConf()
    for k, v in over.items():
        setattr(c, k, v)
    return c


def _rec(recorder, conf, outcome, latency_ms=1.0, tid=None):
    return recorder.record(
        conf, kind="spec", outcome=outcome, latency_ms=latency_ms,
        trace_id=tid or mint_trace_id(), request_id=mint_trace_id())


class TestRetention:
    def test_interesting_outcomes_always_retained(self):
        r = FlightRecorder()
        conf = _conf(flight_recorder_healthy_sample_n=0)
        for outcome in ("FAILED", "DEADLINE", "BUSY", "BADREQ",
                        "error", "degraded"):
            assert _rec(r, conf, outcome)
        assert not _rec(r, conf, "OK")  # healthy, sampling off
        assert {x["outcome"] for x in r.records()} == {
            "FAILED", "DEADLINE", "BUSY", "BADREQ", "error", "degraded"}
        assert all(x["reason"] == "error" for x in r.records())

    def test_slow_threshold_retains(self):
        r = FlightRecorder()
        conf = _conf(flight_recorder_slow_ms=50.0,
                     flight_recorder_healthy_sample_n=0)
        assert not _rec(r, conf, "OK", latency_ms=49.0)
        assert _rec(r, conf, "OK", latency_ms=51.0)
        (rec,) = r.records()
        assert rec["slow"] and rec["reason"] == "slow"

    def test_healthy_sampling_one_in_n(self):
        r = FlightRecorder()
        conf = _conf(flight_recorder_healthy_sample_n=4)
        kept = sum(_rec(r, conf, "OK") for _ in range(16))
        assert kept == 4

    def test_disabled_keeps_nothing(self):
        r = FlightRecorder()
        conf = _conf(flight_recorder_enabled=False)
        assert not _rec(r, conf, "FAILED")
        assert r.records() == []

    def test_healthy_evicted_before_interesting(self):
        r = FlightRecorder()
        conf = _conf(flight_recorder_max_records=16,
                     flight_recorder_healthy_sample_n=1)
        for _ in range(12):
            assert _rec(r, conf, "OK")
        error_ids = [mint_trace_id() for _ in range(8)]
        for tid in error_ids:
            assert _rec(r, conf, "DEADLINE", tid=tid)
        recs = r.records()
        assert len(recs) == 16
        kept = {x["trace_id"] for x in recs}
        assert set(error_ids) <= kept  # every DEADLINE survived
        assert sum(1 for x in recs if x["outcome"] == "OK") == 8

    def test_ring_bound_under_threaded_storm(self):
        """8 threads hammer mixed outcomes: the bound holds at every
        point, nothing raises, and the survivors are the interesting
        tail (healthy evicted first)."""
        r = FlightRecorder()
        conf = _conf(flight_recorder_max_records=32,
                     flight_recorder_healthy_sample_n=1)
        errors: list = []

        def storm(seed: int) -> None:
            try:
                for i in range(200):
                    outcome = ("FAILED", "DEADLINE", "BUSY", "OK")[
                        (seed + i) % 4]
                    _rec(r, conf, outcome, latency_ms=float(i % 7))
                    if i % 50 == 0:
                        assert len(r.records()) <= 32
            except Exception as e:  # noqa: BLE001 — reported below
                errors.append(e)

        threads = [threading.Thread(target=storm, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors
        recs = r.records()
        assert len(recs) == 32
        # 1200 interesting offers vs 400 healthy: the ring's tail end
        # state is all-interesting, healthy records were evicted first.
        assert all(x["outcome"] != "OK" for x in recs)

    def test_record_never_raises_on_broken_input(self):
        """A report object whose to_dict() explodes must not fail the
        request being recorded."""
        r = FlightRecorder()

        class Broken:
            decisions = ()

            def to_dict(self):
                raise RuntimeError("boom")

        assert not r.record(_conf(), kind="spec", outcome="FAILED",
                            latency_ms=1.0, trace_id=mint_trace_id(),
                            request_id=mint_trace_id(), report=Broken())
        assert r.records() == []


# ---------------------------------------------------------------------------
# Local collect feed + slow_queries()
# ---------------------------------------------------------------------------
class TestLocalFeed:
    def test_slow_local_query_lands_in_slow_queries(self, env):
        s, data = env
        s.conf.flight_recorder_slow_ms = 0.0001  # everything is "slow"
        hs = Hyperspace(s)
        s.read.parquet(data).filter(col("k") == 5).collect()
        t = hs.slow_queries()
        assert t.num_rows == 1
        assert t.column("kind")[0].as_py() == "local"
        assert t.column("outcome")[0].as_py() == "ok"
        tid = t.column("traceId")[0].as_py()
        assert valid_trace_id(tid)
        assert hs.trace(tid)["trace_id"] == tid

    def test_failed_local_query_retained_with_error_outcome(self, env):
        s, data = env
        hs = Hyperspace(s)
        with pytest.raises(Exception):
            s.read.parquet(data).filter(col("nope") == 1).collect()
        t = hs.slow_queries()
        assert t.num_rows == 1
        assert t.column("outcome")[0].as_py() == "error"

    def test_request_scope_suppresses_local_feed(self, env):
        """Inside a serve request scope the HANDLER records; collect must
        not double-record."""
        s, data = env
        s.conf.flight_recorder_slow_ms = 0.0001
        with trace.request_scope(mint_trace_id(), mint_trace_id()):
            s.read.parquet(data).filter(col("k") == 5).collect()
        assert flight_recorder.recorder().records() == []


# ---------------------------------------------------------------------------
# Metrics surfacing: HELP lines + exemplars
# ---------------------------------------------------------------------------
class TestMetricsSurfacing:
    def test_help_lines_from_docs16_catalog(self):
        reg = metrics.MetricsRegistry()
        reg.inc("serve.requests")
        reg.inc("rule.filter.applied")  # placeholder row <slug>
        text = reg.render_prometheus()
        assert "# HELP hyperspace_serve_requests " in text
        assert "# HELP hyperspace_rule_filter_applied " in text
        assert "# TYPE hyperspace_serve_requests counter" in text
        # An uncataloged name renders without HELP, never fails.
        reg.inc("not.in.catalog")
        assert "# HELP hyperspace_not_in_catalog" \
            not in reg.render_prometheus()

    def test_exemplar_links_bucket_to_trace_id(self):
        reg = metrics.MetricsRegistry()
        tid = mint_trace_id()
        reg.observe("serve.latency_ms", 12.0, exemplar=tid)
        reg.observe("serve.latency_ms", 700.0)  # no exemplar
        text = reg.render_prometheus()
        assert f'# {{trace_id="{tid}"}} 12' in text
        # Only the bucket the exemplar landed in carries it.
        assert text.count("trace_id=") == 1
        # Snapshot shape is unchanged (no exemplar leakage).
        snap = reg.snapshot()["serve.latency_ms"]
        assert set(snap) == {"count", "sum", "min", "max", "mean",
                             "buckets"}

    def test_served_slow_request_exemplar_in_metrics_text(self, env):
        s, data = env
        s.conf.flight_recorder_slow_ms = 0.0001
        metrics.reset()
        hs = Hyperspace(s)
        with QueryServer(s) as server:
            with QueryClient(server.address) as qc:
                qc.query(_point_spec(data, 2))
                tid = qc.last_trace_id
                rec = _wait_for_record(tid)
        assert rec is not None
        assert f'trace_id="{tid}"' in hs.metrics_text()


# ---------------------------------------------------------------------------
# Trace-sink rotation
# ---------------------------------------------------------------------------
class TestSinkRotation:
    def test_rotation_bounds_the_sink_file(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        sink = trace.JsonlTraceSink(path, max_bytes=400)
        for i in range(50):
            sp = trace.Span(f"span.{i:03d}.{'x' * 40}", {})
            sink.emit(sp)
        assert os.path.exists(path)
        assert os.path.exists(path + ".1")
        # Current file stays inside the bound (+ one line of slack).
        assert os.path.getsize(path) <= 400 + 120
        # Rotation replaced, not accumulated: no .2 and the total on
        # disk is ~2x the bound, not 50 lines.
        assert not os.path.exists(path + ".2")

    def test_unbounded_never_rotates(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        sink = trace.JsonlTraceSink(path, max_bytes=0)
        for _ in range(20):
            sink.emit(trace.Span("s" * 60, {}))
        assert not os.path.exists(path + ".1")

    def test_conf_installs_and_updates_max_bytes(self, tmp_path):
        conf = _conf(telemetry_trace_sink=str(tmp_path / "t.jsonl"),
                     telemetry_trace_max_bytes=123)
        trace.configure_from_conf(conf)
        try:
            sinks = [x for x in trace._sinks
                     if isinstance(x, trace.JsonlTraceSink)]
            assert len(sinks) == 1 and sinks[0].max_bytes == 123
            conf.telemetry_trace_max_bytes = 456
            trace.configure_from_conf(conf)  # idempotent, updates bound
            sinks2 = [x for x in trace._sinks
                      if isinstance(x, trace.JsonlTraceSink)]
            assert sinks2 == sinks and sinks[0].max_bytes == 456
        finally:
            trace.clear_sinks()


# ---------------------------------------------------------------------------
# Diagnostics bundles: both backends, restart, bounds, fault isolation
# ---------------------------------------------------------------------------
class TestBundles:
    @pytest.mark.parametrize("store_cls", BOTH_STORES)
    def test_bundle_survives_restart_over_backend(self, tmp_path,
                                                  store_cls):
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        s.conf.log_store_class = store_cls
        tid = mint_trace_id()
        assert flight_recorder.record(
            s.conf, kind="spec", outcome="DEADLINE", latency_ms=42.0,
            trace_id=tid, request_id=mint_trace_id(),
            error="deadline expired")
        key = flight_recorder.dump_diagnostics(s.conf)
        assert key is not None
        # "Restart": a fresh session + conf over the same system path,
        # and a wiped in-memory ring — only the store can answer now.
        flight_recorder.reset()
        s2 = HyperspaceSession(system_path=str(tmp_path / "ix"))
        s2.conf.log_store_class = store_cls
        got = Hyperspace(s2).diagnostics_bundles()
        assert [b["key"] for b in got] == [key]
        bundle = got[0]
        assert bundle["v"] == flight_recorder.BUNDLE_VERSION
        recs = [r for r in bundle["records"] if r["trace_id"] == tid]
        assert recs and recs[0]["outcome"] == "DEADLINE"
        assert "metrics" in bundle and "perf_tail" in bundle

    @pytest.mark.parametrize("store_cls", BOTH_STORES)
    def test_bundles_bounded_oldest_pruned(self, tmp_path, store_cls):
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        s.conf.log_store_class = store_cls
        s.conf.flight_recorder_max_bundles = 2
        keys = [flight_recorder.dump_diagnostics(s.conf)
                for _ in range(4)]
        assert all(keys)
        got = flight_recorder.bundles(s.conf)
        assert [b["key"] for b in got] == sorted(keys)[-2:]

    def test_dump_never_consumes_fault_budget(self, tmp_path):
        """Diagnostics IO must be invisible to an armed fault plan
        (faults.quiet): the dump succeeds AND the counter stays."""
        from hyperspace_tpu.io import faults

        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        plan = faults.FaultPlan(site="store.put", kind="eio", at=1,
                                count=1)
        faults.install(plan)
        try:
            assert flight_recorder.dump_diagnostics(s.conf) is not None
            assert plan._calls == 0
        finally:
            faults.clear()

    def test_dump_failure_swallowed(self, tmp_path):
        """An unwritable store must cost nothing but a counter."""
        s = HyperspaceSession(system_path="/proc/definitely/not/writable")
        err0 = metrics.registry().counter("flight.dump.errors")
        assert flight_recorder.dump_diagnostics(s.conf) is None
        assert metrics.registry().counter("flight.dump.errors") > err0

    def test_disabled_recorder_skips_dump(self, tmp_path):
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        s.conf.flight_recorder_enabled = False
        assert flight_recorder.dump_diagnostics(s.conf) is None

    def test_index_listing_ignores_diagnostics_dir(self, env):
        s, data = env
        from hyperspace_tpu import IndexConfig

        hs = Hyperspace(s)
        hs.create_index(s.read.parquet(data),
                        IndexConfig("ix", ["k"], ["v"]))
        assert flight_recorder.dump_diagnostics(s.conf) is not None
        assert os.path.isdir(os.path.join(s.conf.system_path,
                                          flight_recorder.FLIGHT_DIR))
        assert hs.indexes().num_rows == 1  # underscore dir skipped


# ---------------------------------------------------------------------------
# The new verbs
# ---------------------------------------------------------------------------
class TestVerbs:
    def test_slow_queries_verb_matches_api(self, env):
        s, data = env
        s.conf.flight_recorder_slow_ms = 0.0001
        with QueryServer(s) as server:
            with QueryClient(server.address) as qc:
                qc.query(_point_spec(data, 7))
                tid = qc.last_trace_id
                assert _wait_for_record(tid) is not None
                t = qc.query({"verb": "slow_queries"})
        assert tid in t.column("traceId").to_pylist()
        assert "recordJson" in t.column_names

    def test_trace_verb_unknown_id_is_badreq(self, env):
        s, _data = env
        with QueryServer(s) as server:
            with QueryClient(server.address) as qc:
                with pytest.raises(QueryFailedError,
                                   match="no retained") as ei:
                    qc.query({"verb": "trace", "id": "f" * 16})
            assert ei.value.code == "BADREQ"
            with QueryClient(server.address) as qc:
                with pytest.raises(QueryFailedError, match="needs"):
                    qc.query({"verb": "trace"})

    def test_shed_request_recorded(self, env, big_dir):
        """A queue-full shed never reaches a worker — the handler's
        record still lands, outcome BUSY, under the client's trace id."""
        from hyperspace_tpu.interop.server import ServerBusyError

        s, _data = env
        s.conf.serving_workers = 1
        s.conf.serving_queue_depth = 1
        with QueryServer(s) as server:
            clients = [QueryClient(server.address) for _ in range(8)]
            try:
                busy_ids: list = []

                def run(c):
                    try:
                        c.query(_slow_spec(big_dir))
                    except ServerBusyError:
                        busy_ids.append(c.last_trace_id)
                    except Exception:  # noqa: BLE001 — not the point here
                        pass

                threads = [threading.Thread(target=run, args=(c,))
                           for c in clients]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=120)
                assert busy_ids, "8 clients vs 1 worker shed nothing"
                rec = _wait_for_record(busy_ids[0])
                assert rec is not None and rec["outcome"] == "BUSY"
            finally:
                for c in clients:
                    c.close()


# ---------------------------------------------------------------------------
# The end-to-end demo (ISSUE 9 acceptance)
# ---------------------------------------------------------------------------
class TestEndToEndDemo:
    def test_deadline_trace_record_survives_restart(self, tmp_path,
                                                    big_dir):
        """Client sends a query whose deadline expires server-side →
        the client error carries the trace id → slow_queries()/the trace
        verb return the full record (serve.request → query.collect span
        tree, run report, DEADLINE outcome) → after drain (the SIGTERM
        path) + restart the same record is readable from the persisted
        diagnostics bundle."""
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        s.conf.telemetry_tracing_enabled = True
        hs = Hyperspace(s)
        server = QueryServer(s).start()
        try:
            with QueryClient(server.address) as qc:
                with pytest.raises(QueryFailedError) as ei:
                    qc.query(_slow_spec(big_dir), deadline_ms=40)
            assert ei.value.code == "DEADLINE" and ei.value.retryable
            tid = ei.value.trace_id
            assert valid_trace_id(tid)
            # The worker aborts at its next phase boundary and records
            # the abandoned job with its span tree — poll for it.
            rec = _wait_for_record(tid)
            assert rec is not None, "DEADLINE record never retained"
            assert rec["outcome"] == "DEADLINE"
            assert rec["kind"] == "spec"
            assert rec["queue_wait_ms"] is not None
            # Span tree spans the serve boundary: serve.request roots
            # query.collect.
            assert rec["spans"]["name"] == "serve.request"
            assert rec["spans"]["tags"]["trace_id"] == tid

            def names(d):
                yield d["name"]
                for c in d.get("children", ()) or ():
                    yield from names(c)

            assert "query.collect" in set(names(rec["spans"]))
            # The run report rode along (the query died mid-execution).
            assert rec["report"] is not None
            assert rec["report"]["outcome"] == "error"
            # Surfacing: the API and the wire agree.
            assert hs.trace(tid)["trace_id"] == tid
            assert tid in hs.slow_queries().column("traceId").to_pylist()
            with QueryClient(server.address) as qc2:
                verb = qc2.query({"verb": "trace", "id": tid})
            assert json.loads(
                verb.column("record_json")[0].as_py())["trace_id"] == tid
        finally:
            # drain() is what the SIGTERM handler runs: it persists the
            # diagnostics bundle after in-flight work settles.
            assert server.drain(grace_s=60.0)
        # "Restart": fresh session over the same system path, in-memory
        # ring wiped — the persisted bundle must still answer.
        flight_recorder.reset()
        s2 = HyperspaceSession(system_path=str(tmp_path / "ix"))
        got = Hyperspace(s2).diagnostics_bundles()
        assert got, "drain did not persist a diagnostics bundle"
        recs = [r for b in got for r in b["records"]
                if r["trace_id"] == tid]
        assert recs and recs[0]["outcome"] == "DEADLINE"
        assert recs[0]["spans"]["name"] == "serve.request"

    def test_plan_fingerprint_recorded_for_served_queries(self, env):
        s, data = env
        s.conf.flight_recorder_slow_ms = 0.0001
        with QueryServer(s) as server:
            with QueryClient(server.address) as qc:
                qc.query(_point_spec(data, 1))
                first = qc.last_trace_id
                qc.query(_point_spec(data, 1))
                second = qc.last_trace_id
        rec1, rec2 = _wait_for_record(first), _wait_for_record(second)
        assert rec1 is not None and rec2 is not None
        # Same query shape + literals → same plan fingerprint, and the
        # repeat was a plan-cache hit.
        assert rec1["plan_fingerprint"]
        assert rec1["plan_fingerprint"] == rec2["plan_fingerprint"]
        hits = [d for d in rec2["report"]["decisions"]
                if d["kind"] == "plan_cache"]
        assert hits and hits[-1]["hit"] is True
