"""Wire-level chaos: deterministic net-fault injection at the interop
socket seams (interop/netfaults.py), the front door's circuit breakers,
hedged requests and single deadline budget, stale-pool eviction, the
SIGSTOP gray-failure drill, and the lease's store-latency margin +
epoch fencing (docs/20-fleet-serving.md)."""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession
from hyperspace_tpu.interop import (
    FleetQueryClient,
    QueryClient,
    QueryServer,
)
from hyperspace_tpu.interop import netfaults
from hyperspace_tpu.interop.server import _Endpoint
from hyperspace_tpu.io import faults
from hyperspace_tpu.lifecycle import journal as lifecycle_journal
from hyperspace_tpu.lifecycle import lease as lease_mod
from hyperspace_tpu.telemetry import metrics


def _counter(name):
    return metrics.registry().counter(name)


@pytest.fixture()
def env(tmp_path):
    data = str(tmp_path / "data")
    os.makedirs(data)
    n = 500
    pq.write_table(pa.table({
        "k": pa.array(np.arange(n, dtype=np.int64)),
        "v": pa.array(np.arange(n, dtype=np.int64) * 3),
    }), os.path.join(data, "f.parquet"))
    s = HyperspaceSession(system_path=str(tmp_path / "ix"))
    s.conf.num_buckets = 4
    return s, data


def _point_spec(data, k):
    return {"source": {"format": "parquet", "path": data},
            "filter": {"op": "==", "col": "k", "value": int(k)},
            "select": ["k", "v"]}


@pytest.fixture(autouse=True)
def _clear_net_state():
    yield
    faults.clear()
    netfaults.clear_parked()


# ---------------------------------------------------------------------------
# The plan: net kinds, net sites, channel gating
# ---------------------------------------------------------------------------
class TestNetFaultPlan:
    def test_net_sites_registered(self):
        for site in ("net.connect", "net.send", "net.recv", "net.accept"):
            assert site in faults.SITES

    def test_net_kind_requires_net_site(self):
        with pytest.raises(ValueError, match="net"):
            faults.FaultPlan(site="store.put", kind="reset")

    def test_storage_kind_rejected_at_net_site(self):
        with pytest.raises(ValueError, match="net"):
            faults.FaultPlan(site="net.send", kind="eio")

    def test_net_checkpoint_fires_only_net_channel(self):
        faults.install(faults.FaultPlan(site="net.send", kind="reset",
                                        at=1, count=-1))
        # The storage checkpoints never see a net plan...
        assert not faults.FaultPlan(
            site="net.send", kind="reset")._should_fire("net.send")
        # ...and the net checkpoint arbitrates site + order as usual.
        assert faults.net("net.recv") is None
        assert faults.net("net.send") is not None

    def test_quiet_suppresses_net_faults(self):
        faults.install(faults.FaultPlan(site="net.send", kind="reset",
                                        at=1, count=-1))
        with faults.quiet():
            assert faults.net("net.send") is None
        assert faults.net("net.send") is not None

    def test_at_count_window(self):
        faults.install(faults.FaultPlan(site="net.connect", kind="refused",
                                        at=2, count=1))
        assert faults.net("net.connect") is None      # call 1: before at
        assert faults.net("net.connect") is not None  # call 2: fires
        assert faults.net("net.connect") is None      # call 3: spent

    def test_conf_arming_carries_shaping(self, tmp_path):
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        s.conf.set("hyperspace.system.faultInjection.enabled", True)
        s.conf.set("hyperspace.system.faultInjection.site", "net.recv")
        s.conf.set("hyperspace.system.faultInjection.kind", "slow")
        s.conf.set("hyperspace.system.faultInjection.latencyMs", 7.5)
        s.conf.set("hyperspace.system.faultInjection.hangS", 0.125)
        faults.install_from_conf(s.conf)
        plan = faults.active()
        assert plan is not None and plan.kind == "slow"
        assert plan.latency_ms == 7.5 and plan.hang_s == 0.125


# ---------------------------------------------------------------------------
# The seams, against raw TCP sockets
# ---------------------------------------------------------------------------
def _tcp_pair():
    listener = socket.create_server(("127.0.0.1", 0))
    client = socket.create_connection(listener.getsockname())
    server, _ = listener.accept()
    listener.close()
    return client, server


class TestNetSeams:
    def test_connect_refused(self):
        faults.install(faults.FaultPlan(site="net.connect", kind="refused"))
        with pytest.raises(ConnectionRefusedError, match="injected"):
            netfaults.connect(("127.0.0.1", 1))

    def test_connect_black_hole_hangs_then_times_out(self):
        faults.install(faults.FaultPlan(site="net.connect",
                                        kind="black-hole", hang_s=0.08))
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="black-hole"):
            netfaults.connect(("127.0.0.1", 1))
        assert time.monotonic() - t0 >= 0.08

    def test_connect_slow_still_dials(self):
        listener = socket.create_server(("127.0.0.1", 0))
        faults.install(faults.FaultPlan(site="net.connect", kind="slow",
                                        latency_ms=60.0))
        t0 = time.monotonic()
        sock = netfaults.connect(listener.getsockname())
        assert time.monotonic() - t0 >= 0.06
        sock.close()
        listener.close()

    def test_send_torn_frame_delivers_half_then_reset(self):
        client, server = _tcp_pair()
        faults.install(faults.FaultPlan(site="net.send",
                                        kind="torn-frame"))
        payload = b"x" * 4096
        with pytest.raises(ConnectionResetError, match="torn frame"):
            netfaults.send_all(client, payload)
        got = b""
        server.settimeout(2.0)
        try:
            while True:
                chunk = server.recv(65536)
                if not chunk:
                    break
                got += chunk
        except OSError:
            pass  # RST close surfaces as ECONNRESET — equally torn
        assert 0 < len(got) < len(payload)
        server.close()

    def test_send_disarmed_passes_through(self):
        client, server = _tcp_pair()
        netfaults.send_all(client, b"hello")
        server.settimeout(2.0)
        assert server.recv(64) == b"hello"
        client.close()
        server.close()

    def test_before_recv_black_hole(self):
        faults.install(faults.FaultPlan(site="net.recv", kind="black-hole",
                                        hang_s=0.05))
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            netfaults.before_recv()
        assert time.monotonic() - t0 >= 0.05

    def test_on_accept_reset_consumes_connection(self):
        client, server = _tcp_pair()
        faults.install(faults.FaultPlan(site="net.accept", kind="reset"))
        assert netfaults.on_accept(server) is False
        client.settimeout(2.0)
        with pytest.raises(OSError):
            if client.recv(1) == b"":       # FIN still counts as dead
                raise ConnectionResetError
        client.close()

    def test_on_accept_black_hole_parks_open(self):
        client, server = _tcp_pair()
        faults.install(faults.FaultPlan(site="net.accept",
                                        kind="black-hole"))
        assert netfaults.on_accept(server) is False
        # Parked: the peer sees neither data nor FIN.
        client.settimeout(0.2)
        with pytest.raises(socket.timeout):
            client.recv(1)
        netfaults.clear_parked()
        client.close()

    def test_on_accept_disarmed_and_slow_pass_through(self):
        client, server = _tcp_pair()
        assert netfaults.on_accept(server) is True
        faults.install(faults.FaultPlan(site="net.accept", kind="slow"))
        assert netfaults.on_accept(server) is True
        client.close()
        server.close()


# ---------------------------------------------------------------------------
# The seams, through the real client/server wire path
# ---------------------------------------------------------------------------
class TestWirePathFaults:
    def test_connect_refused_fails_over(self, env):
        s, data = env
        retry0 = _counter("client.retry")
        with QueryServer(s) as server:
            with FleetQueryClient([server.address, server.address]) as fc:
                faults.install(faults.FaultPlan(
                    site="net.connect", kind="refused", at=1, count=1))
                assert fc.query(_point_spec(data, 7)) \
                    .column("v").to_pylist() == [21]
        assert _counter("client.retry") - retry0 >= 1

    def test_torn_response_frame_is_retryable(self, env):
        """An armed torn-frame on the server's response: the client
        must surface a retryable ConnectionError (never a raw Arrow
        decode error), and the front door must recover bit-equal."""
        s, data = env
        with QueryServer(s) as server:
            # Seam order: client request send = 1, server response
            # send = 2 — tear the response.
            faults.install(faults.FaultPlan(
                site="net.send", kind="torn-frame", at=2, count=1))
            with QueryClient(server.address) as c:
                with pytest.raises(ConnectionError):
                    c.query(_point_spec(data, 3))
            faults.install(faults.FaultPlan(
                site="net.send", kind="torn-frame", at=2, count=1))
            with FleetQueryClient([server.address, server.address]) as fc:
                assert fc.query(_point_spec(data, 3)) \
                    .column("v").to_pylist() == [9]

    def test_recv_reset_fails_over(self, env):
        s, data = env
        with QueryServer(s) as server:
            faults.install(faults.FaultPlan(
                site="net.recv", kind="reset", at=1, count=1))
            with FleetQueryClient([server.address, server.address]) as fc:
                assert fc.query(_point_spec(data, 4)) \
                    .column("v").to_pylist() == [12]

    def test_accept_reset_fails_over(self, env):
        s, data = env
        with QueryServer(s) as server:
            faults.install(faults.FaultPlan(
                site="net.accept", kind="reset", at=1, count=1))
            with FleetQueryClient([server.address, server.address]) as fc:
                assert fc.query(_point_spec(data, 5)) \
                    .column("v").to_pylist() == [15]

    def test_slow_recv_shapes_latency_only(self, env):
        s, data = env
        with QueryServer(s) as server:
            with QueryClient(server.address) as c:
                c.query(_point_spec(data, 1))  # warm (dataset open)
                faults.install(faults.FaultPlan(
                    site="net.recv", kind="slow", at=1, count=1,
                    latency_ms=120.0))
                t0 = time.monotonic()
                assert c.query(_point_spec(data, 6)) \
                    .column("v").to_pylist() == [18]
                assert time.monotonic() - t0 >= 0.12


# ---------------------------------------------------------------------------
# Satellite 1: pooled-connection validation / stale-socket eviction
# ---------------------------------------------------------------------------
class TestStalePoolEviction:
    def test_bounced_server_socket_evicted_without_retry(self, tmp_path):
        """SIGKILL + same-port restart leaves half-open TCP in the
        client's pool; checkout validation must eat it silently — a
        fresh dial, not a reset charged to retry accounting."""
        data = str(tmp_path / "data")
        os.makedirs(data)
        pq.write_table(pa.table({
            "k": pa.array(np.arange(100, dtype=np.int64)),
            "v": pa.array(np.arange(100, dtype=np.int64) * 3),
        }), os.path.join(data, "f.parquet"))
        env_vars = dict(os.environ, JAX_PLATFORMS="cpu")

        def _spawn(port=0):
            p = subprocess.Popen(
                [sys.executable, "-c", _SERVER_CHILD,
                 str(tmp_path / "ix"), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env_vars)
            line = p.stdout.readline()
            assert line, p.stderr.read()
            return p, json.loads(line)["port"]

        proc, port = _spawn()
        fc = FleetQueryClient([("127.0.0.1", port)])
        try:
            assert fc.query(_point_spec(data, 2)) \
                .column("v").to_pylist() == [6]
            assert fc._endpoints[0].idle  # the connection was pooled
            proc.kill()
            proc.wait(timeout=30)
            proc, _ = _spawn(port)       # bounce: same port, new pid
            retry0 = _counter("client.retry")
            evict0 = _counter("client.pool.evicted")
            assert fc.query(_point_spec(data, 8)) \
                .column("v").to_pylist() == [24]
            # The stale socket was caught at CHECKOUT — a fresh dial,
            # not a failed request turned into a retry.
            assert _counter("client.pool.evicted") - evict0 >= 1
            assert _counter("client.retry") - retry0 == 0
        finally:
            fc.close()
            proc.kill()
            proc.wait(timeout=30)

    def test_healthy_pooled_socket_not_evicted(self, env):
        s, data = env
        with QueryServer(s) as server:
            with FleetQueryClient([server.address]) as fc:
                evict0 = _counter("client.pool.evicted")
                for k in range(5):
                    fc.query(_point_spec(data, k))
                assert _counter("client.pool.evicted") - evict0 == 0


# ---------------------------------------------------------------------------
# Satellite 2: ONE deadline budget across every failover attempt
# ---------------------------------------------------------------------------
class _BusyEndpoint:
    """Answers every request line with retryable ``ERR BUSY`` + a
    retry-after hint, then closes (mirrors test_fleet_serving)."""

    def __init__(self, retry_after_ms=300):
        self._listener = socket.create_server(("127.0.0.1", 0))
        self.address = self._listener.getsockname()
        self._hint = retry_after_ms
        self.hits = 0
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                f = conn.makefile("rb")
                if f.readline():
                    self.hits += 1
                    conn.sendall(
                        f"ERR BUSY admission queue full; retry later "
                        f"retry-after-ms={self._hint}\n".encode())
                conn.close()
            except OSError:
                pass

    def close(self):
        self._stop = True
        self._listener.close()


class _SilentEndpoint:
    """Accepts and reads, never answers — a gray server."""

    def __init__(self):
        self._listener = socket.create_server(("127.0.0.1", 0))
        self.address = self._listener.getsockname()
        self._stop = False
        self._conns = []
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)  # hold open; never reply

    def close(self):
        self._stop = True
        self._listener.close()
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass


class TestDeadlineBudget:
    def test_busy_retries_spend_one_budget(self, env):
        s, data = env
        busy = [_BusyEndpoint(retry_after_ms=300) for _ in range(2)]
        try:
            with FleetQueryClient([b.address for b in busy],
                                  max_attempts=10) as fc:
                t0 = time.monotonic()
                with pytest.raises(Exception):
                    fc.query(_point_spec(data, 1), deadline_ms=700)
                elapsed = time.monotonic() - t0
            # 10 attempts x 300 ms hinted backoff would be ~3 s; ONE
            # 700 ms budget caps the whole call.
            assert elapsed < 1.8, elapsed
            assert sum(b.hits for b in busy) >= 2  # it did retry
        finally:
            for b in busy:
                b.close()

    def test_gray_endpoint_timeout_leaves_failover_budget(self, env):
        """The per-attempt socket timeout spreads the budget: a silent
        endpoint costs a slice of the deadline, not all of it, so the
        next attempt still has budget to succeed."""
        s, data = env
        silent = _SilentEndpoint()
        try:
            with QueryServer(s) as server:
                with FleetQueryClient([silent.address, server.address],
                                      max_attempts=4) as fc:
                    answers = []
                    t0 = time.monotonic()
                    for k in range(4):
                        answers.append(
                            fc.query(_point_spec(data, k),
                                     deadline_ms=4000)
                            .column("v").to_pylist())
                    elapsed = time.monotonic() - t0
            assert answers == [[0], [3], [6], [9]]
            assert elapsed < 16.0
        finally:
            silent.close()

    def test_deadline_exhausted_raises_timeout(self, env):
        s, data = env
        silent = _SilentEndpoint()
        try:
            with FleetQueryClient([silent.address],
                                  max_attempts=3) as fc:
                t0 = time.monotonic()
                with pytest.raises(OSError):
                    fc.query(_point_spec(data, 1), deadline_ms=600)
                elapsed = time.monotonic() - t0
            assert elapsed < 2.5, elapsed
        finally:
            silent.close()


# ---------------------------------------------------------------------------
# Tentpole: per-endpoint circuit breakers
# ---------------------------------------------------------------------------
class TestCircuitBreaker:
    def test_transitions(self):
        ep = _Endpoint(("127.0.0.1", 1))
        now = time.monotonic()
        assert not ep.breaker_blocked(now)
        assert not ep.breaker_failure(3, 10.0)
        assert not ep.breaker_failure(3, 10.0)
        assert ep.breaker_failure(3, 10.0)        # third opens
        assert ep.breaker_state == "open"
        assert ep.breaker_blocked(time.monotonic())
        assert not ep.breaker_on_pick(time.monotonic())  # still cooling
        ep.breaker_until = time.monotonic() - 0.01       # cooldown over
        assert ep.breaker_on_pick(time.monotonic())      # -> half-open
        assert ep.breaker_state == "half-open"
        assert ep.breaker_blocked(time.monotonic())      # probe in flight
        assert ep.breaker_failure(3, 10.0)        # probe failed: re-open
        assert ep.breaker_state == "open"
        ep.breaker_until = time.monotonic() - 0.01
        assert ep.breaker_on_pick(time.monotonic())
        assert ep.breaker_success()               # probe served: closed
        assert ep.breaker_state == "closed"
        assert not ep.breaker_blocked(time.monotonic())

    def test_success_resets_failure_streak(self):
        ep = _Endpoint(("127.0.0.1", 1))
        ep.breaker_failure(3, 10.0)
        ep.breaker_failure(3, 10.0)
        assert not ep.breaker_success()  # closed stays closed
        assert ep.breaker_fails == 0
        assert not ep.breaker_failure(3, 10.0)  # streak restarted

    def test_open_breaker_routes_away_until_probe(self, env):
        s, data = env
        busy = _BusyEndpoint(retry_after_ms=50)
        open0 = _counter("client.breaker.open")
        close0 = _counter("client.breaker.close")
        try:
            with QueryServer(s) as server:
                with FleetQueryClient(
                        [busy.address, server.address],
                        breaker_enabled=True, breaker_failures=1,
                        breaker_cooldown_ms=60_000.0) as fc:
                    for k in range(8):
                        assert fc.query(_point_spec(data, k)) \
                            .column("v").to_pylist() == [3 * k]
                    # The busy endpoint tripped its breaker on the
                    # first failure and was never routed to again
                    # (the cooldown outlives the test).
                    assert busy.hits == 1
                    assert fc._endpoints[0].breaker_state == "open"
                    assert metrics.snapshot()[
                        "client.breaker.open_now"] >= 1.0
        finally:
            busy.close()
        assert _counter("client.breaker.open") - open0 >= 1
        assert _counter("client.breaker.close") - close0 == 0

    def test_half_open_probe_closes_on_recovery(self, env):
        s, data = env
        with QueryServer(s) as server:
            with FleetQueryClient(
                    [server.address, server.address],
                    breaker_enabled=True, breaker_failures=1,
                    breaker_cooldown_ms=50.0) as fc:
                # Manufacture an open breaker on endpoint 0, as if it
                # had failed — the server itself is healthy, so the
                # probe after the cooldown succeeds and closes it.
                fc._endpoints[0].breaker_failure(1, 0.05)
                close0 = _counter("client.breaker.close")
                time.sleep(0.08)  # cooldown elapses
                for k in range(6):
                    fc.query(_point_spec(data, k))
                assert fc._endpoints[0].breaker_state == "closed"
                assert _counter("client.breaker.close") - close0 >= 1
                assert metrics.snapshot()[
                    "client.breaker.open_now"] == 0.0

    def test_all_breakers_open_still_serves(self, env):
        """Breakers shape routing; they never refuse work outright."""
        s, data = env
        with QueryServer(s) as server:
            with FleetQueryClient([server.address],
                                  breaker_enabled=True,
                                  breaker_failures=1) as fc:
                fc._endpoints[0].breaker_failure(1, 60.0)
                assert fc.query(_point_spec(data, 9)) \
                    .column("v").to_pylist() == [27]


# ---------------------------------------------------------------------------
# Tentpole: hedged requests
# ---------------------------------------------------------------------------
class TestHedging:
    def test_hedge_beats_slow_primary(self, env):
        """Arm a one-shot slow ``net.recv`` per query: the PRIMARY's
        read (first through the seam) stalls 400 ms, the hedge fires at
        40 ms against the other endpoint, reads clean, and wins."""
        s, data = env
        with QueryServer(s) as s1, QueryServer(s) as s2:
            with FleetQueryClient([s1.address, s2.address],
                                  hedge_enabled=True, hedge_delay_ms=40.0,
                                  max_attempts=2) as fc:
                for k in range(4):  # warm both endpoints, no faults
                    fc.query(_point_spec(data, k))
                sent0 = _counter("client.hedge.sent")
                wins0 = _counter("client.hedge.wins")
                for k in range(3):
                    faults.install(faults.FaultPlan(
                        site="net.recv", kind="slow", at=1, count=1,
                        latency_ms=400.0))
                    assert fc.query(_point_spec(data, k),
                                    deadline_ms=8000) \
                        .column("v").to_pylist() == [3 * k]
                    faults.clear()
        sent = _counter("client.hedge.sent") - sent0
        wins = _counter("client.hedge.wins") - wins0
        assert sent == 3
        assert 1 <= wins <= sent

    def test_no_hedge_when_primary_fast(self, env):
        s, data = env
        with QueryServer(s) as server:
            with FleetQueryClient(
                    [server.address, server.address],
                    hedge_enabled=True, hedge_delay_ms=2000.0) as fc:
                fc.query(_point_spec(data, 0))  # warm
                sent0 = _counter("client.hedge.sent")
                for k in range(5):
                    fc.query(_point_spec(data, k))
                assert _counter("client.hedge.sent") - sent0 == 0

    def test_loser_response_never_cross_wires(self, env):
        """After a hedge wins, the slow primary still finishes reading
        its OWN late response, which is discarded by request_id —
        follow-up queries on the same pooled connections stay
        bit-equal (no frame from the loser leaks into a later
        answer)."""
        s, data = env
        with QueryServer(s) as s1, QueryServer(s) as s2:
            with FleetQueryClient([s1.address, s2.address],
                                  hedge_enabled=True, hedge_delay_ms=30.0,
                                  max_attempts=2) as fc:
                for k in range(4):
                    fc.query(_point_spec(data, k))
                faults.install(faults.FaultPlan(
                    site="net.recv", kind="slow", at=1, count=1,
                    latency_ms=300.0))
                fc.query(_point_spec(data, 10), deadline_ms=6000)
                faults.clear()
                time.sleep(0.5)  # let the loser finish its late read
                for k in range(20, 30):
                    assert fc.query(_point_spec(data, k),
                                    deadline_ms=6000) \
                        .column("v").to_pylist() == [3 * k]

    def test_single_endpoint_never_hedges(self, env):
        s, data = env
        with QueryServer(s) as server:
            with FleetQueryClient([server.address],
                                  hedge_enabled=True,
                                  hedge_delay_ms=1.0) as fc:
                sent0 = _counter("client.hedge.sent")
                # Even a slow-looking first attempt has nowhere else
                # to go with one endpoint.
                for k in range(3):
                    fc.query(_point_spec(data, k))
                assert _counter("client.hedge.sent") - sent0 == 0

    def test_adaptive_delay_tracks_ewma(self, env):
        s, data = env
        with QueryServer(s) as server:
            with FleetQueryClient([server.address],
                                  hedge_enabled=True) as fc:
                assert fc._hedge_delay_s() == 0.050  # no history yet
                for k in range(5):
                    fc.query(_point_spec(data, k))
                assert fc._lat_ewma_ms > 0.0
                assert 0.010 <= fc._hedge_delay_s() <= 0.500


# ---------------------------------------------------------------------------
# Satellite 3: SIGSTOP gray failure through a real subprocess fleet
# ---------------------------------------------------------------------------
_SERVER_CHILD = r"""
import json, os, sys
from hyperspace_tpu import HyperspaceSession
from hyperspace_tpu.interop import QueryServer
s = HyperspaceSession(system_path=sys.argv[1])
port = int(sys.argv[2]) if len(sys.argv) > 2 else 0
server = QueryServer(s, port=port, handle_sigterm=True).start()
print(json.dumps({"port": server.address[1], "pid": os.getpid()}),
      flush=True)
server.drained.wait()
sys.exit(0)
"""


class TestSigstopGrayFailure:
    def test_stopped_server_times_out_and_fails_over(self, tmp_path):
        data = str(tmp_path / "data")
        os.makedirs(data)
        n = 200
        pq.write_table(pa.table({
            "k": pa.array(np.arange(n, dtype=np.int64)),
            "v": pa.array(np.arange(n, dtype=np.int64) * 5),
        }), os.path.join(data, "f.parquet"))
        env_vars = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = [subprocess.Popen(
            [sys.executable, "-c", _SERVER_CHILD, str(tmp_path / "ix")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env_vars) for _ in range(2)]
        stopped_pid = None
        try:
            children = []
            for p in procs:
                line = p.stdout.readline()
                assert line, p.stderr.read()
                children.append(json.loads(line))
            endpoints = [("127.0.0.1", c["port"]) for c in children]
            spec = {"source": {"format": "parquet", "path": data},
                    "filter": {"op": "==", "col": "k", "value": 0},
                    "select": ["k", "v"]}
            retry0 = _counter("client.retry")
            fail0 = _counter("client.failover")
            hedge0 = _counter("client.hedge.sent")
            with FleetQueryClient(endpoints, max_attempts=4) as fc:
                for k in range(4):  # warm both servers
                    spec["filter"]["value"] = k
                    assert fc.query(dict(spec)) \
                        .column("v").to_pylist() == [5 * k]
                stopped_pid = children[0]["pid"]
                os.kill(stopped_pid, signal.SIGSTOP)  # alive, serves nothing
                answered = []
                for k in range(6):
                    spec["filter"]["value"] = k
                    answered.append(fc.query(dict(spec), deadline_ms=3000)
                                    .column("v").to_pylist())
                os.kill(stopped_pid, signal.SIGCONT)
                stopped_pid = None
                # Late responses from the woken server died with their
                # discarded connections — follow-ups stay bit-equal.
                for k in range(6):
                    spec["filter"]["value"] = k
                    assert fc.query(dict(spec), deadline_ms=3000) \
                        .column("v").to_pylist() == [5 * k]
            # ZERO lost: every request answered, bit-equal.
            assert answered == [[5 * k] for k in range(6)]
            retries = _counter("client.retry") - retry0
            failovers = _counter("client.failover") - fail0
            assert retries >= 1       # the gray timeouts surfaced
            assert 1 <= failovers <= retries  # and routed away; no
            # double-count: each retry is one failover at most, and
            # hedging (off) never fired.
            assert _counter("client.hedge.sent") - hedge0 == 0
        finally:
            if stopped_pid is not None:
                try:
                    os.kill(stopped_pid, signal.SIGCONT)
                except OSError:
                    pass
            for p in procs:
                p.kill()
                p.wait(timeout=30)


# ---------------------------------------------------------------------------
# Tentpole: lease store-latency margin + epoch fencing
# ---------------------------------------------------------------------------
class TestLeaseMarginFencing:
    def _conf(self, tmp_path, ttl=1.0):
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        s.conf.set("hyperspace.lifecycle.lease.enabled", True)
        s.conf.set("hyperspace.lifecycle.lease.ttlS", ttl)
        return s.conf

    def test_margin_scales_with_measured_latency(self, tmp_path):
        conf = self._conf(tmp_path, ttl=1.0)
        lease = lease_mod.MaintenanceLease(conf, owner="m")
        assert lease.margin_s() == pytest.approx(0.02)  # cold floor
        lease._lat_ewma_s = 0.05
        assert lease.margin_s() == pytest.approx(0.10)  # 2 round-trips
        lease._lat_ewma_s = 10.0
        assert lease.margin_s() == pytest.approx(1.0 / 3.0)  # clamped

    def test_acquire_measures_store_latency(self, tmp_path):
        conf = self._conf(tmp_path)
        lease = lease_mod.MaintenanceLease(conf, owner="a")
        assert lease.try_acquire()
        assert lease._lat_ewma_s > 0.0

    def test_holder_stops_early_by_margin(self, tmp_path):
        conf = self._conf(tmp_path, ttl=1.0)
        lease = lease_mod.MaintenanceLease(conf, owner="a")
        assert lease.try_acquire()
        # A degraded store (slow CAS round-trips) widens the margin:
        # the holder stands down BEFORE its wall-clock expiry.
        lease._lat_ewma_s = 0.2          # margin = 0.333 (ttl/3 clamp)
        lease._expires_at = time.time() + 0.3
        assert not lease.holds()         # inside the margin: stop acting
        lease._lat_ewma_s = 0.001        # healthy store: margin = 0.02
        assert lease.holds()

    def test_zombie_renew_is_fenced_after_takeover(self, tmp_path):
        """The partition drill: holder A's renew is black-holed past
        the TTL (modeled as the CAS arriving late), B takes over with
        a bumped epoch, and A's late CAS loses — A is fenced, stands
        down, and the journal carries the whole story."""
        conf = self._conf(tmp_path, ttl=0.5)
        a = lease_mod.MaintenanceLease(conf, owner="zombie")
        b = lease_mod.MaintenanceLease(conf, owner="successor")
        fenced0 = _counter("lease.fenced")
        assert a.ensure()
        assert a.epoch == 1
        assert not b.ensure()            # live holder: B idles
        time.sleep(0.6)                  # A's renews black-hole past TTL
        assert not a.holds()             # wall clock already stopped A
        assert b.ensure()                # expired: B takes over
        assert b.epoch == 2
        # A's delayed CAS finally lands — at a stale generation.
        assert not a.renew()
        assert not a._held
        assert _counter("lease.fenced") - fenced0 == 1
        status = lease_mod.status(conf)
        assert status["holder"] == "successor"
        assert status["epoch"] == 2
        recs = lifecycle_journal.records(conf)
        modes = [r.get("mode") for r in recs
                 if r.get("decision") == "lease"]
        assert "takeover" in modes and "fence" in modes
        # Exactly one holder may execute: A re-competes as an ordinary
        # candidate and loses while B's lease is fresh.
        assert not a.ensure()
        assert b.ensure()                # renew

    def test_journal_proves_exactly_once_under_contention(self, tmp_path):
        """Two processes' worth of lease handles racing ensure():
        every round has at most ONE winner."""
        conf = self._conf(tmp_path, ttl=5.0)
        holders = [lease_mod.MaintenanceLease(conf, owner=f"h{i}")
                   for i in range(3)]
        for _ in range(4):
            winners = [h for h in holders if h.ensure()]
            assert len(winners) == 1
            assert winners[0].owner == holders[0].owner  # stable holder


# ---------------------------------------------------------------------------
# Doctor: the client check
# ---------------------------------------------------------------------------
class TestDoctorClientCheck:
    def test_warns_while_breaker_open(self, env):
        s, _data = env
        hs = Hyperspace(s)
        metrics.set_gauge("client.breaker.open_now", 2.0)
        try:
            check = hs.doctor().check("client")
            assert check.status == "warn"
            assert "breaker" in check.summary
        finally:
            metrics.set_gauge("client.breaker.open_now", 0.0)

    def test_ok_with_closed_breakers(self, env):
        s, _data = env
        metrics.set_gauge("client.breaker.open_now", 0.0)
        check = Hyperspace(s).doctor().check("client")
        assert check.status == "ok"
