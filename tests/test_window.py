"""Window functions (round-3 verdict item 5).

The reference's plan-stability corpus uses rank()/row_number()/sum() OVER
(PARTITION BY ... ORDER BY ...) throughout (TPC-DS q36, q44, q47, q49,
q57 under /root/reference/src/test/resources/tpcds/queries/); this engine
owns the Window plan node (host sort + segmented scan).  Correctness is
checked against pandas, plan goldens pin three TPC-DS shapes, and a fuzz
sweep runs random window specs against their pandas equivalents.
"""

from __future__ import annotations

import math
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

# Optional test dep: environments without hypothesis skip the module
# instead of erroring at collection (the fuzz nets are additive coverage).
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from tests.test_plan_stability import _simplify, _write

APPROVED_DIR = os.path.join(os.path.dirname(__file__), "resources",
                            "approved-plans-window")
GENERATE = os.environ.get("HS_GENERATE_GOLDEN_FILES") == "1"


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("window"))
    data = os.path.join(root, "sales")
    os.makedirs(data)
    rng = np.random.default_rng(13)
    n = 4000
    t = pa.table({
        "grp": pa.array((np.arange(n) % 23).astype(np.int64)),
        "cls": pa.array([("a", "b", "c")[i % 3] for i in range(n)]),
        # Few distinct revenue values: tie groups are common.
        "rev": pa.array(np.round(rng.uniform(0, 50, n), 0)),
        "qty": pa.array(rng.integers(1, 20, n), type=pa.int64()),
        "rid": pa.array(np.arange(n, dtype=np.int64)),
    })
    pq.write_table(t, os.path.join(data, "p.parquet"))
    s = HyperspaceSession(system_path=os.path.join(root, "ix"))
    s.conf.num_buckets = 4
    return s, data, t.to_pandas()


def _pd_rank(df, part, order_cols, ascending, method):
    key = df.sort_values(order_cols, ascending=ascending, kind="stable")
    r = key.groupby(part)[order_cols[0] if len(order_cols) == 1
                          else order_cols].apply(lambda x: x)
    # pandas' own rank handles this directly:
    by = df[order_cols[0]] if len(order_cols) == 1 else None
    return by


class TestCorrectness:
    def test_row_number_and_ranks_match_pandas(self, env):
        s, data, df = env
        out = (s.read.parquet(data)
               .with_window("rn", "row_number", partition_by=["grp"],
                            order_by=[("rev", False), "rid"])
               .with_window("rk", "rank", partition_by=["grp"],
                            order_by=[("rev", False)])
               .with_window("dr", "dense_rank", partition_by=["grp"],
                            order_by=[("rev", False)])
               .collect().to_pandas().sort_values("rid"))
        g = df.sort_values("rid").groupby("grp")["rev"]
        want_rk = g.rank(method="min", ascending=False).astype(int)
        want_dr = g.rank(method="dense", ascending=False).astype(int)
        np.testing.assert_array_equal(out["rk"], want_rk)
        np.testing.assert_array_equal(out["dr"], want_dr)
        # row_number with the rid tiebreak is a permutation of 1..size.
        sizes = df.groupby("grp")["rid"].transform("size")
        assert (out.groupby("grp")["rn"].max().to_numpy()
                == df.groupby("grp")["rid"].count().to_numpy()).all()
        assert out["rn"].dtype == np.int32

    def test_partition_aggregate_no_order(self, env):
        s, data, df = env
        out = (s.read.parquet(data)
               .with_window("total", "sum", partition_by=["grp"],
                            value="qty")
               .with_window("m", "mean", partition_by=["grp"], value="rev")
               .with_window("n", "count", partition_by=["grp"])
               .collect().to_pandas().sort_values("rid"))
        base = df.sort_values("rid")
        np.testing.assert_array_equal(
            out["total"], base.groupby("grp")["qty"].transform("sum"))
        np.testing.assert_allclose(
            out["m"], base.groupby("grp")["rev"].transform("mean"))
        np.testing.assert_array_equal(
            out["n"], base.groupby("grp")["rid"].transform("size"))

    def test_running_sum_range_frame_shares_ties(self, env):
        """Spark's default RANGE frame: rows tied on the order key get
        the tie group's full (last) cumulative value."""
        s, data, df = env
        out = (s.read.parquet(data)
               .with_window("run", "sum", partition_by=["grp"],
                            order_by=["rev"], value="qty")
               .collect().to_pandas())
        # Pandas equivalent: cumsum over sorted rows, then max within
        # (grp, rev) tie groups.
        sdf = df.sort_values(["grp", "rev"], kind="stable")
        cs = sdf.groupby("grp")["qty"].cumsum()
        want = cs.groupby([sdf["grp"], sdf["rev"]]).transform("max")
        merged = out.set_index("rid")["run"]
        np.testing.assert_array_equal(
            merged.loc[sdf["rid"]].to_numpy(), want.to_numpy())

    def test_running_min_max_and_global_window(self, env):
        s, data, df = env
        out = (s.read.parquet(data)
               .with_window("lo", "min", order_by=["rid"], value="rev")
               .with_window("hi", "max", order_by=["rid"], value="rev")
               .collect().to_pandas().sort_values("rid"))
        np.testing.assert_allclose(out["lo"], df["rev"].cummin())
        np.testing.assert_allclose(out["hi"], df["rev"].cummax())

    def test_nulls_in_value_and_keys(self, tmp_path):
        d = str(tmp_path / "nv")
        os.makedirs(d)
        pq.write_table(pa.table({
            "g": pa.array([1, 1, 1, None, None], type=pa.int64()),
            "o": pa.array([1, 2, 3, 1, 2], type=pa.int64()),
            "v": pa.array([None, 4.0, None, None, 2.0]),
        }), os.path.join(d, "p.parquet"))
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        out = (s.read.parquet(d)
               .with_window("rs", "sum", partition_by=["g"],
                            order_by=["o"], value="v")
               .with_window("n", "count", partition_by=["g"], value="v")
               .sort("g", "o").collect())
        # Null partition keys form their own group (Spark groups nulls).
        assert out.column("rs").to_pylist() == [None, 2.0, None, 4.0, 4.0]
        assert out.column("n").to_pylist() == [1, 1, 1, 1, 1]

    def test_rank_requires_order_by(self, env):
        s, data, _df = env
        with pytest.raises(ValueError, match="ORDER BY"):
            s.read.parquet(data).with_window("r", "rank",
                                             partition_by=["grp"])

    def test_window_over_spec(self, env):
        s, data, df = env
        from hyperspace_tpu.interop.query import dataset_from_spec

        out = dataset_from_spec(s, {
            "source": {"format": "parquet", "path": data},
            "window": [{"name": "rk", "func": "rank",
                        "partition_by": ["grp"],
                        "order_by": [["rev", False]]}],
            "qualify": {"op": "<=", "col": "rk", "value": 1},
        }).collect()
        want = int((df.groupby("grp")["rev"].transform("max")
                    == df["rev"]).sum())
        assert out.num_rows == want


# ---- TPC-DS-shaped plan goldens (q36 / q44 / q47 shapes) ---------------

def _window_queries(session, paths):
    read = session.read
    sales = read.parquet(paths)
    return {
        # q36 shape: rank() over a margin within a class partition, keep
        # the top ranks.
        "w36_rank_within_class": sales
            .group_by("cls", "grp")
            .agg(margin=(col("rev") * col("qty"), "sum"))
            .with_window("rk", "rank", partition_by=["cls"],
                         order_by=[("margin", False)])
            .filter(col("rk") <= 3)
            .sort("cls", "rk"),
        # q44 shape: best and worst performers by row_number over avg.
        "w44_best_worst": sales
            .group_by("grp")
            .agg(avg_rev=("rev", "mean"))
            .with_window("best", "row_number",
                         order_by=[("avg_rev", False), "grp"])
            .with_window("worst", "row_number",
                         order_by=[("avg_rev", True), "grp"])
            .filter((col("best") <= 5) | (col("worst") <= 5))
            .sort("best"),
        # q47 shape: per-partition mean alongside each row (the
        # avg-over-partition + deviation filter).
        "w47_deviation_from_mean": sales
            .group_by("grp", "cls")
            .agg(s=("qty", "sum"))
            .with_window("avg_s", "mean", partition_by=["grp"], value="s")
            .filter((col("avg_s") > 0) & ((col("s") - col("avg_s"))
                                          / col("avg_s") > 0.05))
            .sort("grp", "cls"),
    }


WINDOW_GOLDENS = sorted(["w36", "w44", "w47"])


@pytest.mark.parametrize("prefix", WINDOW_GOLDENS)
def test_window_plan_stability(env, prefix):
    session, data, _df = env
    queries = _window_queries(session, data)
    name = [k for k in queries if k.startswith(prefix)][0]
    session.enable_hyperspace()
    try:
        plan = queries[name].optimized_plan()
    finally:
        session.disable_hyperspace()
    simplified = _simplify(plan.tree_string(), {"sales": data})
    approved_path = os.path.join(APPROVED_DIR, name, "simplified.txt")
    if GENERATE:
        os.makedirs(os.path.dirname(approved_path), exist_ok=True)
        with open(approved_path, "w", encoding="utf-8") as f:
            f.write(simplified)
        return
    assert os.path.isfile(approved_path), (
        f"No approved plan for {name}; run with HS_GENERATE_GOLDEN_FILES=1")
    with open(approved_path, "r", encoding="utf-8") as f:
        approved = f.read()
    assert simplified == approved, (
        f"Plan for {name} changed.\n--- approved ---\n{approved}\n"
        f"--- current ---\n{simplified}")


@pytest.mark.parametrize("prefix", WINDOW_GOLDENS)
def test_window_answers_match_pandas(env, prefix):
    session, data, df = env
    queries = _window_queries(session, data)
    name = [k for k in queries if k.startswith(prefix)][0]
    got = queries[name].collect().to_pandas()
    if name.startswith("w36"):
        base = (df.assign(margin=df["rev"] * df["qty"])
                .groupby(["cls", "grp"])["margin"].sum().reset_index())
        base["rk"] = base.groupby("cls")["margin"] \
            .rank(method="min", ascending=False).astype(int)
        want = base[base["rk"] <= 3]
        assert len(got) == len(want)
        np.testing.assert_array_equal(
            got.sort_values(["cls", "rk", "grp"])["grp"].to_numpy(),
            want.sort_values(["cls", "rk", "grp"])["grp"].to_numpy())
    elif name.startswith("w44"):
        base = df.groupby("grp")["rev"].mean().reset_index(name="avg_rev")
        order = base.sort_values(["avg_rev", "grp"],
                                 ascending=[False, True], kind="stable")
        best = set(order.head(5)["grp"])
        worst = set(base.sort_values(["avg_rev", "grp"], kind="stable")
                    .head(5)["grp"])
        assert set(got["grp"]) == best | worst
    else:
        base = (df.groupby(["grp", "cls"])["qty"].sum()
                .reset_index(name="s"))
        base["avg_s"] = base.groupby("grp")["s"].transform("mean")
        want = base[(base["avg_s"] > 0)
                    & ((base["s"] - base["avg_s"]) / base["avg_s"] > 0.05)]
        assert len(got) == len(want)


# ---- fuzz: random window specs vs pandas -------------------------------

@settings(max_examples=int(os.environ.get("HS_FUZZ_EXAMPLES", "60")) // 3,
          deadline=None,
          suppress_health_check=[HealthCheck.function_scoped_fixture])
@given(func=st.sampled_from(["row_number", "rank", "dense_rank", "sum",
                             "count", "min", "max", "mean"]),
       part=st.sampled_from([(), ("grp",), ("cls",), ("grp", "cls")]),
       asc=st.booleans(), with_order=st.booleans())
def test_window_fuzz_matches_pandas(env, func, part, asc, with_order):
    s, data, df = env
    ranking = func in ("row_number", "rank", "dense_rank")
    if ranking:
        with_order = True
    order = [("rev", asc), ("rid", True)] if func == "row_number" \
        else ([("rev", asc)] if with_order else [])
    value = None if func in ("row_number", "rank", "dense_rank", "count") \
        else "qty"
    ds = s.read.parquet(data).with_window(
        "w", func, partition_by=list(part), order_by=order, value=value)
    got = ds.collect().to_pandas().sort_values("rid")["w"].to_numpy()

    pdf = df.sort_values("rid").reset_index(drop=True)
    grouper = list(part) if part else (lambda _x: 0)
    gb = pdf.groupby(grouper if part else np.zeros(len(pdf), dtype=int))
    if func == "row_number":
        key = pdf.sort_values(["rev", "rid"], ascending=[asc, True],
                              kind="stable")
        want = key.groupby(list(part) if part
                           else np.zeros(len(key), dtype=int)) \
            .cumcount().sort_index().to_numpy() + 1
    elif func in ("rank", "dense_rank"):
        want = gb["rev"].rank(
            method="min" if func == "rank" else "dense",
            ascending=asc).to_numpy().astype(int)
    elif not with_order:
        if func == "count":
            want = gb["rid"].transform("size").to_numpy()
        else:
            want = gb["qty"].transform(func).to_numpy()
    else:
        sdf = pdf.sort_values(["rev"], ascending=asc, kind="stable")
        part_key = [sdf[c] for c in part] if part \
            else [pd.Series(np.zeros(len(sdf), dtype=int), index=sdf.index)]
        if func == "count":
            cum = part_key[0].groupby(part_key).cumcount() + 1 \
                if False else sdf.assign(one=1).groupby(
                    [k for k in part_key])["one"].cumsum()
        elif func == "mean":
            csum = sdf.groupby([k for k in part_key])["qty"].cumsum()
            ccnt = sdf.assign(one=1).groupby(
                [k for k in part_key])["one"].cumsum()
            cum = csum / ccnt
        else:
            cum = getattr(sdf.groupby([k for k in part_key])["qty"],
                          f"cum{func}" if func in ("min", "max")
                          else "cumsum")()
        tie_key = [k for k in part_key] + [sdf["rev"]]
        shared = cum.groupby(tie_key).transform("last")
        want = shared.sort_index().to_numpy()
    if func in ("mean",):
        np.testing.assert_allclose(got, want)
    else:
        np.testing.assert_array_equal(got, want)


def test_running_min_on_strings_raises_clearly(tmp_path):
    d = str(tmp_path / "str")
    os.makedirs(d)
    pq.write_table(pa.table({
        "g": pa.array([1, 1], type=pa.int64()),
        "o": pa.array([1, 2], type=pa.int64()),
        "s": pa.array(["b", "a"]),
    }), os.path.join(d, "p.parquet"))
    s = HyperspaceSession(system_path=str(tmp_path / "ix"))
    with pytest.raises(ValueError, match="Running window min"):
        (s.read.parquet(d)
         .with_window("m", "min", partition_by=["g"], order_by=["o"],
                      value="s").collect())
    # Whole-partition min over strings still works.
    out = (s.read.parquet(d)
           .with_window("m", "min", partition_by=["g"], value="s")
           .collect())
    assert out.column("m").to_pylist() == ["a", "a"]


def test_window_sum_type_stable_on_empty_input(env):
    s, data, _df = env
    t32 = (s.read.parquet(data)
           .with_column("q32", col("qty").cast("int32")))
    full = t32.with_window("sm", "sum", partition_by=["grp"],
                           value="q32").collect()
    empty = (t32.filter(col("rid") < 0)
             .with_window("sm", "sum", partition_by=["grp"], value="q32")
             .collect())
    assert full.schema.field("sm").type == empty.schema.field("sm").type \
        == pa.int64()


class TestLagLead:
    def test_lag_lead_match_pandas(self, env):
        s, data, df = env
        out = (s.read.parquet(data)
               .with_window("prev", "lag", partition_by=["grp"],
                            order_by=["rid"], value="qty")
               .with_window("nxt", "lead", partition_by=["grp"],
                            order_by=["rid"], value="qty")
               .with_window("prev2", "lag", partition_by=["grp"],
                            order_by=["rid"], value="qty", offset=2)
               .collect().to_pandas().sort_values("rid"))
        base = df.sort_values("rid")
        g = base.groupby("grp")["qty"]
        pd.testing.assert_series_equal(
            out["prev"].reset_index(drop=True),
            g.shift(1).reset_index(drop=True), check_names=False)
        pd.testing.assert_series_equal(
            out["nxt"].reset_index(drop=True),
            g.shift(-1).reset_index(drop=True), check_names=False)
        pd.testing.assert_series_equal(
            out["prev2"].reset_index(drop=True),
            g.shift(2).reset_index(drop=True), check_names=False)
        # Type preserved: qty is int64, shifted column stays int64
        # (nulls at partition edges).
        tbl = (s.read.parquet(data)
               .with_window("p", "lag", partition_by=["grp"],
                            order_by=["rid"], value="qty").collect())
        assert tbl.schema.field("p").type == pa.int64()

    def test_lag_from_sql_q47_shape(self, env):
        """TPC-DS q47's prev-period comparison from SQL text."""
        s, data, df = env
        from hyperspace_tpu.sql import sql

        ds = sql(s, """
            SELECT grp, rid, qty,
                   lag(qty, 1) OVER (PARTITION BY grp ORDER BY rid)
                       AS prev_qty
            FROM sales
        """, tables={"sales": s.read.parquet(data)})
        out = ds.collect().to_pandas().sort_values("rid")
        want = df.sort_values("rid").groupby("grp")["qty"].shift(1)
        pd.testing.assert_series_equal(
            out["prev_qty"].reset_index(drop=True),
            want.reset_index(drop=True), check_names=False)

    def test_lag_requires_order_by(self, env):
        s, data, _df = env
        with pytest.raises(ValueError, match="ORDER BY"):
            s.read.parquet(data).with_window(
                "p", "lag", partition_by=["grp"], value="qty")


def test_lag_preserves_int64_exactly(tmp_path):
    """No pandas float round-trip: values above 2^53 survive lag/lead
    bit-for-bit (review finding)."""
    d = str(tmp_path / "big")
    os.makedirs(d)
    big = 2**53 + 1
    pq.write_table(pa.table({
        "g": pa.array([1, 1], type=pa.int64()),
        "o": pa.array([1, 2], type=pa.int64()),
        "v": pa.array([big, 7], type=pa.int64()),
    }), os.path.join(d, "p.parquet"))
    s = HyperspaceSession(system_path=str(tmp_path / "ix"))
    out = (s.read.parquet(d)
           .with_window("p", "lag", partition_by=["g"], order_by=["o"],
                        value="v")
           .with_window("nx", "lead", partition_by=["g"], order_by=["o"],
                        value="v")
           .sort("o").collect())
    assert out.column("p").to_pylist() == [None, big]
    assert out.column("nx").to_pylist() == [7, None]
    assert out.schema.field("p").type == pa.int64()
