"""Timeline profiler, kernel attribution, Perfetto export, and the
health doctor (docs/16-observability.md).

Covers the PR's acceptance loop:
  - busy/gap analysis math on hand-built intervals, then on a REAL
    spill-forced build (nonzero read-idle-while-spill fraction);
  - the background memory sampler and per-phase high-water marks;
  - block_until_ready-timed kernel attribution metrics and the
    flight-record ``device_ms`` discriminator;
  - Perfetto/Chrome trace-event export: schema validation, and
    reconstruction from a flight-recorder record and a perf-ledger
    entry;
  - the doctor matrix over BOTH LogStore backends: clean tree → ok,
    seeded quarantine → crit (and ok again after repair), stale
    index → warn;
  - ``perf_history`` index/section/limit filters (API + verb).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.telemetry import metrics, perf_ledger, timeline
from hyperspace_tpu.telemetry.doctor import doctor

BOTH_STORES = ("hyperspace_tpu.io.log_store.PosixLogStore",
               "hyperspace_tpu.io.log_store.EmulatedObjectStore")


@pytest.fixture(autouse=True)
def _timeline_cleanup():
    """The enable flag and the interval ring are process-global (like
    tracing): a test that enables the timeline must not leak it."""
    yield
    timeline.disable_timeline()
    timeline.reset()


def _write_source(path: str, n: int = 40_000, files: int = 4,
                  seed: int = 13) -> None:
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    t = pa.table({
        "k": pa.array(rng.integers(0, max(1, n // 8), n), type=pa.int64()),
        "v": rng.random(n),
    })
    step = -(-n // files)
    for i in range(files):
        pq.write_table(t.slice(i * step, step),
                       os.path.join(path, f"part-{i:05d}.parquet"))


def _session(tmp_path, name: str = "ix", **conf) -> HyperspaceSession:
    s = HyperspaceSession(system_path=str(tmp_path / name))
    s.conf.num_buckets = 4
    for k, v in conf.items():
        setattr(s.conf, k, v)
    return s


# ---------------------------------------------------------------------------
# Recorder + gap/overlap math
# ---------------------------------------------------------------------------
class TestRecorder:
    def test_disabled_is_a_noop(self):
        timeline.disable_timeline()
        timeline.reset()
        assert timeline.op_begin() is None
        assert timeline.kernel_begin() is None
        timeline.record_interval("a", "k", 0, 10)
        timeline.kernel_end("x", None, None)   # no sync, no record
        timeline.record_transfer("h2d", 1024)  # no counter
        assert timeline.recorder().intervals() == []
        assert "exec.transfer.h2d.bytes" not in metrics.snapshot()

    def test_enabled_records_and_bounds(self):
        timeline.enable_timeline()
        rec = timeline.recorder()
        rec.set_capacity(8)
        for i in range(20):
            timeline.record_interval("lane", "k", i, i + 1)
        ivs = rec.intervals()
        assert len(ivs) == 8
        assert ivs[0][2] == 12  # oldest 12 dropped
        assert metrics.snapshot().get("timeline.dropped", 0) >= 12
        rec.set_capacity(timeline._DEFAULT_MAX_INTERVALS)

    def test_lane_context_manager(self):
        timeline.enable_timeline()
        timeline.reset()
        with timeline.lane("read", "chunk"):
            pass
        ivs = timeline.recorder().intervals("read")
        assert len(ivs) == 1 and ivs[0][1] == "chunk"

    def test_busy_report_overlap_math(self):
        # A busy [0, 100); B busy [50, 150): window 150.
        report = timeline.busy_report([("A", "x", 0, 100),
                                       ("B", "x", 50, 150)])
        assert report["lanes"]["A"]["busy_fraction"] == pytest.approx(
            100 / 150, abs=1e-3)
        assert report["lanes"]["B"]["busy_fraction"] == pytest.approx(
            100 / 150, abs=1e-3)
        # B runs alone in [100, 150): A idle while B busy = 50/150.
        assert report["idle_while_busy"]["A"]["B"] == pytest.approx(
            50 / 150, abs=1e-3)
        assert report["idle_while_busy"]["B"]["A"] == pytest.approx(
            50 / 150, abs=1e-3)

    def test_busy_report_fully_serialized(self):
        # Strictly sequential lanes: each is idle for ALL of the other's
        # busy time — the shape a serialized build pipeline has.
        report = timeline.busy_report([("read", "x", 0, 100),
                                       ("spill", "x", 100, 200)])
        assert report["idle_while_busy"]["read"]["spill"] \
            == pytest.approx(0.5, abs=1e-3)
        assert report["idle_while_busy"]["spill"]["read"] \
            == pytest.approx(0.5, abs=1e-3)

    def test_busy_report_merges_overlapping_spans(self):
        # Two overlapping intervals on one lane count once.
        report = timeline.busy_report([("A", "x", 0, 60),
                                       ("A", "x", 40, 100)])
        assert report["lanes"]["A"]["busy_fraction"] == pytest.approx(1.0)

    def test_busy_report_empty(self):
        assert timeline.busy_report([]) == {
            "window_s": 0.0, "lanes": {}, "idle_while_busy": {}}


class TestMemorySampler:
    def test_sampler_feeds_sink_and_ring(self):
        timeline.enable_timeline()
        timeline.reset()

        class Sink:
            def __init__(self):
                self.samples = []

            def add_memory_sample(self, ts, rss, dev):
                self.samples.append((ts, rss, dev))

        sink = Sink()
        sampler = timeline.MemorySampler(cadence_ms=2.0, sink=sink)
        sampler.start()
        time.sleep(0.08)
        sampler.stop()
        assert sink.samples, "sampler produced nothing in 80 ms"
        assert timeline.recorder().memory_samples()
        ts, rss, dev = sink.samples[0]
        assert rss > 0  # /proc/self/statm works on this host
        assert dev >= 0

    def test_start_sampler_respects_gate(self, tmp_path):
        s = _session(tmp_path)
        timeline.disable_timeline()
        assert timeline.start_sampler(s.conf) is None
        timeline.enable_timeline()
        s.conf.timeline_memory_sample_ms = 0.0
        assert timeline.start_sampler(s.conf) is None
        s.conf.timeline_memory_sample_ms = 5.0
        sampler = timeline.start_sampler(s.conf)
        assert sampler is not None
        sampler.stop()


# ---------------------------------------------------------------------------
# The spill-forced build: lanes, matrix, per-phase memory
# ---------------------------------------------------------------------------
@pytest.fixture(scope="class")
def spill_build(tmp_path_factory):
    """One spill-forced build with the timeline + a fast sampler on:
    shared by the gap-analysis and export tests (class-scoped — the
    build is the expensive part)."""
    tmp_path = tmp_path_factory.mktemp("spill")
    src = str(tmp_path / "src")
    _write_source(src, n=120_000, files=6)
    session = _session(tmp_path, timeline_enabled=True,
                       timeline_memory_sample_ms=2.0)
    session.conf.device_batch_rows = 8192   # force the external build
    session.conf.parallel_build = "off"
    hs = Hyperspace(session)
    timeline.reset()
    hs.create_index(session.read.parquet(src),
                    IndexConfig("spix", ["k"], ["v"]))
    yield session, hs
    timeline.disable_timeline()
    timeline.reset()


class TestSpillBuildTimeline:
    def test_lanes_matrix_ring_and_live_export(self, spill_build,
                                               tmp_path):
        """First test in the class ON PURPOSE: the per-test cleanup
        wipes the process ring, so the ring/export assertions must run
        in the same test slot the class fixture built in.  Later tests
        read the (per-report) interval copy only."""
        _session_, hs = spill_build
        report = hs.last_build_report()
        assert report.spill_bytes > 0, "build did not spill"
        lanes = report.lane_report()
        for lane_name in ("read", "spill_route", "spill_finish"):
            assert lane_name in lanes["lanes"], sorted(lanes["lanes"])
        matrix = lanes["idle_while_busy"]
        # The acceptance number: reads are DONE before the per-bucket
        # finish pass runs, so the read lane must be measurably idle
        # while spill work is busy — the serialization ROADMAP item 2's
        # prefetch rewrite must reduce.
        read_idle_while_spill = max(matrix["read"]["spill_route"],
                                    matrix["read"]["spill_finish"])
        assert read_idle_while_spill > 0.0, matrix

        # Build-phase intervals reached the process ring...
        kinds = {iv[1] for iv in timeline.recorder().intervals()}
        assert "build.phase" in kinds
        # ...and the live-ring Perfetto export renders them plus the
        # sampler's memory counter track, schema-valid.
        path = str(tmp_path / "trace.json")
        hs.export_timeline(path)
        with open(path, "r", encoding="utf-8") as f:
            events = json.load(f)["traceEvents"]
        _validate_trace_events(events)
        names = {e["name"] for e in events}
        assert "build.phase" in names
        assert "memory" in names
        ring_lanes = {e["args"]["name"] for e in events
                      if e["ph"] == "M"}
        assert "read" in ring_lanes and "spill_route" in ring_lanes

    def test_memory_sampler_ran_and_phase_high_water(self, spill_build):
        _session_, hs = spill_build
        report = hs.last_build_report()
        assert report.memory_samples, "no background memory samples"
        peaks = report.phase_memory_mb()
        assert isinstance(peaks, dict)
        assert peaks, "no sample landed inside any phase interval"
        assert all(v > 0 for v in peaks.values()), peaks

    def test_to_dict_carries_lanes_and_peaks(self, spill_build):
        _session_, hs = spill_build
        d = hs.last_build_report().to_dict()
        assert "lanes" in d and "idle_while_busy" in d["lanes"]
        assert "phase_peak_rss_mb" in d

    def test_disabled_build_records_nothing(self, tmp_path):
        timeline.disable_timeline()
        timeline.reset()
        src = str(tmp_path / "src")
        _write_source(src, n=5_000, files=2)
        session = _session(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src),
                        IndexConfig("offix", ["k"], ["v"]))
        report = hs.last_build_report()
        assert report.intervals == []
        assert report.memory_samples == []
        assert "lanes" not in report.to_dict()


# ---------------------------------------------------------------------------
# Kernel attribution
# ---------------------------------------------------------------------------
class TestKernelAttribution:
    def test_device_filter_emits_kernel_metrics(self, tmp_path):
        src = str(tmp_path / "src")
        _write_source(src, n=10_000, files=2)
        session = _session(tmp_path, timeline_enabled=True)
        session.conf.device_filter_min_rows = 1  # force the device path
        metrics.reset()
        ds = session.read.parquet(src).filter(col("k") < 100)
        out = ds.collect()
        assert out.num_rows > 0
        snap = metrics.snapshot()
        hist = snap.get("exec.kernel.filter.device_ms")
        assert isinstance(hist, dict) and hist["count"] >= 1, sorted(snap)
        device_counters = [k for k in snap
                           if k.startswith("exec.device.")
                           and k.endswith(".kernel_ms")]
        assert device_counters, sorted(snap)
        assert snap.get("exec.transfer.d2h.bytes", 0) > 0
        # The kernel decision landed on the run report → device_ms
        # summary nonzero.
        rep = session.last_run_report_value
        kernels = [d for d in rep.decisions if d.get("kind") == "kernel"]
        assert kernels and kernels[0]["name"] == "filter"
        assert timeline.device_ms_summary(rep) > 0
        # ...and on a device:<id> timeline lane.
        lanes = {iv[0] for iv in timeline.recorder().intervals()}
        assert any(ln.startswith("device:") for ln in lanes), lanes

    def test_timeline_off_means_no_kernel_sync_or_metrics(self, tmp_path):
        timeline.disable_timeline()
        src = str(tmp_path / "src")
        _write_source(src, n=10_000, files=2)
        session = _session(tmp_path)
        session.conf.device_filter_min_rows = 1
        metrics.reset()
        session.read.parquet(src).filter(col("k") < 100).collect()
        assert "exec.kernel.filter.device_ms" not in metrics.snapshot()

    def test_flight_record_carries_device_ms(self, tmp_path):
        from hyperspace_tpu.telemetry import flight_recorder

        src = str(tmp_path / "src")
        _write_source(src, n=10_000, files=2)
        session = _session(tmp_path, timeline_enabled=True)
        session.conf.device_filter_min_rows = 1
        session.conf.flight_recorder_slow_ms = 0.001  # retain everything
        flight_recorder.reset()
        session.read.parquet(src).filter(col("k") < 100).collect()
        table = flight_recorder.slow_queries_table(session.conf)
        assert table.num_rows >= 1
        assert "deviceMs" in table.column_names
        assert max(table.column("deviceMs").to_pylist()) > 0
        rec = flight_recorder.recorder().records()[-1]
        assert rec["device_ms"] > 0

    def test_executor_operator_intervals(self, tmp_path):
        src = str(tmp_path / "src")
        _write_source(src, n=5_000, files=2)
        session = _session(tmp_path, timeline_enabled=True)
        timeline.reset()
        session.read.parquet(src).collect()
        kinds = {iv[1] for iv in timeline.recorder().intervals("exec")}
        assert "Scan" in kinds, kinds


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------
def _validate_trace_events(events) -> None:
    """Chrome trace-event schema: every event has ph/pid/ts-or-metadata;
    X events carry name + ts + dur; C events carry numeric args."""
    assert isinstance(events, list) and events
    for ev in events:
        assert isinstance(ev, dict)
        assert ev.get("ph") in ("X", "C", "M"), ev
        assert isinstance(ev.get("pid"), int)
        if ev["ph"] == "M":
            assert ev.get("name") == "thread_name"
            assert isinstance(ev["args"]["name"], str)
            continue
        assert isinstance(ev.get("name"), str) and ev["name"]
        assert isinstance(ev.get("ts"), (int, float))
        if ev["ph"] == "X":
            assert isinstance(ev.get("dur"), (int, float))
            assert ev["dur"] >= 0
        if ev["ph"] == "C":
            assert all(isinstance(v, (int, float))
                       for v in ev["args"].values()), ev


class TestPerfettoExport:
    def test_trace_event_builder_schema(self):
        events = timeline.to_trace_events(
            intervals=[("read", "build.phase", 1000, 5000),
                       ("spill_route", "build.phase", 2000, 9000)],
            memory_samples=[(1500, 123.4, 1 << 20)])
        _validate_trace_events(events)
        # One Perfetto thread per lane, named via metadata events.
        named = {e["args"]["name"] for e in events if e["ph"] == "M"}
        assert named == {"read", "spill_route"}
        x = [e for e in events if e["ph"] == "X"]
        assert {e["args"]["lane"] for e in x} == {"read", "spill_route"}
        # ns → µs conversion.
        assert min(e["ts"] for e in x) == pytest.approx(1.0)
        c = [e for e in events if e["ph"] == "C"]
        assert c and c[0]["args"]["host_rss_mb"] == pytest.approx(123.4)

    def test_roundtrip_from_flight_record(self, tmp_path):
        from hyperspace_tpu.telemetry import flight_recorder, trace

        src = str(tmp_path / "src")
        _write_source(src, n=5_000, files=2)
        session = _session(tmp_path, timeline_enabled=True)
        session.conf.flight_recorder_slow_ms = 0.001
        session.conf.telemetry_tracing_enabled = True
        flight_recorder.reset()
        try:
            hs = Hyperspace(session)
            session.read.parquet(src).collect()
        finally:
            trace.disable_tracing()
        rec = flight_recorder.recorder().records()[-1]
        assert rec["spans"], "tracing was on; the record must carry spans"
        path = str(tmp_path / "from_record.json")
        hs.export_timeline(path, trace_id=rec["trace_id"])
        with open(path, "r", encoding="utf-8") as f:
            events = json.load(f)["traceEvents"]
        _validate_trace_events(events)
        names = {e["name"] for e in events}
        assert "query.collect" in names, names

    def test_export_unknown_trace_id_raises(self, tmp_path):
        session = _session(tmp_path)
        hs = Hyperspace(session)
        with pytest.raises(ValueError, match="no retained flight record"):
            hs.export_timeline(str(tmp_path / "x.json"),
                               trace_id="deadbeefdeadbeef")

    def test_reconstruct_from_perf_ledger_entry(self, tmp_path):
        src = str(tmp_path / "src")
        _write_source(src, n=5_000, files=2)
        session = _session(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src),
                        IndexConfig("lx", ["k"], ["v"]))
        history = hs.perf_history(index="lx")
        assert history.num_rows >= 1
        key = history.column("key").to_pylist()[-1]
        path = str(tmp_path / "from_ledger.json")
        hs.export_timeline(path, ledger_key=key)
        with open(path, "r", encoding="utf-8") as f:
            events = json.load(f)["traceEvents"]
        _validate_trace_events(events)
        names = {e["name"] for e in events}
        assert any(n.startswith("phase.") for n in names), names

    def test_export_unknown_ledger_key_raises(self, tmp_path):
        session = _session(tmp_path)
        hs = Hyperspace(session)
        with pytest.raises(ValueError, match="no perf-ledger record"):
            hs.export_timeline(str(tmp_path / "x.json"),
                               ledger_key="r-0000000000000-0-00000")


# ---------------------------------------------------------------------------
# The doctor
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("store_cls", BOTH_STORES)
class TestDoctorMatrix:
    def _built(self, tmp_path, store_cls):
        src = str(tmp_path / "src")
        _write_source(src, n=8_000, files=2)
        session = _session(tmp_path, log_store_class=store_cls)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src),
                        IndexConfig("dix", ["k"], ["v"]))
        return session, hs, src

    def test_clean_tree_is_ok(self, tmp_path, store_cls):
        session, hs, _src = self._built(tmp_path, store_cls)
        metrics.reset()  # degraded counters are process-global
        report = hs.doctor()
        assert report.status == "ok", report.render()
        assert {c.name for c in report.checks} == {
            "integrity", "staleness", "cdc.merge_debt", "maintenance",
            "perf", "serving", "degraded", "lint", "device_skew",
            "client"}
        assert metrics.snapshot().get("health.status") == 0

    def test_seeded_quarantine_is_crit_and_repair_restores_ok(
            self, tmp_path, store_cls):
        session, hs, _src = self._built(tmp_path, store_cls)
        metrics.reset()
        manager = session.index_collection_manager
        entry = manager.get_index("dix")
        victim = entry.content.file_infos()[0].name
        qm = manager.quarantine_manager("dix")
        assert qm.add(victim, reason="test-seeded")
        report = hs.doctor()
        assert report.status == "crit", report.render()
        check = report.check("integrity")
        assert check.status == "crit"
        assert check.data["quarantined"] == {"dix": 1}
        assert metrics.snapshot().get("health.status") == 2
        # Repair rebuilds the quarantined bucket and clears the record:
        # the doctor must grade the tree ok again.
        hs.refresh_index("dix", mode="repair")
        metrics.reset()
        report = hs.doctor()
        assert report.status == "ok", report.render()
        assert metrics.snapshot().get("health.status") == 0

    def test_stale_index_is_warn(self, tmp_path, store_cls):
        session, hs, src = self._built(tmp_path, store_cls)
        metrics.reset()
        # Append a source file AFTER the build: the index is now behind.
        extra = pa.table({"k": pa.array([1, 2, 3], type=pa.int64()),
                          "v": [0.1, 0.2, 0.3]})
        pq.write_table(extra, os.path.join(src, "part-99999.parquet"))
        report = hs.doctor()
        assert report.status == "warn", report.render()
        check = report.check("staleness")
        assert check.status == "warn"
        assert check.data["stale"]["dix"]["appended"] == 1
        assert metrics.snapshot().get("health.status") == 1


class TestDoctorChecks:
    def test_serving_overload_grades_crit(self, tmp_path):
        session = _session(tmp_path)
        metrics.reset()
        metrics.inc("serve.requests", 100)
        metrics.inc("serve.shed", 50)  # 0.5 >= 5 * 0.05
        report = doctor(session)
        assert report.check("serving").status == "crit"
        assert report.status == "crit"

    def test_serving_slo_burn_grades_warn(self, tmp_path):
        session = _session(tmp_path)
        session.conf.doctor_latency_slo_ms = 100.0
        metrics.reset()
        metrics.inc("serve.requests", 10)
        for _ in range(8):
            metrics.observe("serve.latency_ms", 10.0)
        for _ in range(2):
            metrics.observe("serve.latency_ms", 5000.0)  # 20% over SLO
        report = doctor(session)
        check = report.check("serving")
        assert check.status == "warn", check.to_dict()
        assert check.data["slo_burn"] == pytest.approx(0.2)

    def test_perf_trend_regression_grades_warn(self, tmp_path):
        session = _session(tmp_path)
        metrics.reset()
        for wall in (1.0, 1.1, 0.9, 1.0, 10.0):  # latest 10x the median
            perf_ledger.append(session.conf, {
                "kind": "action", "name": "CreateAction(trendix)",
                "wall_s": wall, "outcome": "ok"})
        report = doctor(session)
        check = report.check("perf")
        assert check.status == "warn", check.to_dict()
        assert "CreateAction(trendix)" in check.data["regressions"]

    def test_degraded_counters_grade_warn(self, tmp_path):
        session = _session(tmp_path)
        metrics.reset()
        metrics.inc("degraded.fallbacks")
        report = doctor(session)
        assert report.check("degraded").status == "warn"
        assert report.status == "warn"

    def test_maintenance_backoff_grades_warn(self, tmp_path):
        from hyperspace_tpu.lifecycle.daemon import daemon_for

        session = _session(tmp_path)
        metrics.reset()
        d = daemon_for(session)
        d._backoff["dix"] = (3, time.monotonic() + 60.0)
        report = doctor(session)
        check = report.check("maintenance")
        assert check.status == "warn"
        assert check.data["backoffs"]["dix"]["failures"] == 3

    def test_blind_check_is_warn_not_crash(self, tmp_path, monkeypatch):
        """A check that raises must degrade to warn, never propagate.
        (``session.index_collection_manager`` is a property minting a
        fresh manager per access, so the CLASS method is patched.)"""
        from hyperspace_tpu.index.cache import (
            CachingIndexCollectionManager,
        )

        session = _session(tmp_path)
        metrics.reset()
        monkeypatch.setattr(CachingIndexCollectionManager, "get_indexes",
                            _boom)
        report = doctor(session)
        assert report.check("integrity").status == "warn"
        assert "check failed" in report.check("integrity").summary
        assert report.status == "warn"

    def test_report_render_and_table(self, tmp_path):
        session = _session(tmp_path)
        metrics.reset()
        report = doctor(session)
        assert report.status in ("ok", "warn", "crit")
        assert "Doctor:" in report.render()
        table = report.table()
        assert table.column("check").to_pylist()[0] == "overall"
        assert len(table.column("check").to_pylist()) \
            == len(report.checks) + 1

    def test_doctor_verb(self, tmp_path):
        from hyperspace_tpu.interop.server import _serve_verb

        session = _session(tmp_path)
        metrics.reset()
        table = _serve_verb(session, {"verb": "doctor"})
        checks = table.column("check").to_pylist()
        assert "overall" in checks and "integrity" in checks
        statuses = set(table.column("status").to_pylist())
        assert statuses <= {"ok", "warn", "crit"}


def _boom(*_a, **_k):
    raise RuntimeError("listing exploded")


# ---------------------------------------------------------------------------
# perf_history ergonomics
# ---------------------------------------------------------------------------
class TestPerfHistoryFilters:
    @pytest.fixture()
    def seeded(self, tmp_path):
        src = str(tmp_path / "src")
        _write_source(src, n=6_000, files=2)
        session = _session(tmp_path)
        hs = Hyperspace(session)
        ds = session.read.parquet(src)
        hs.create_index(ds, IndexConfig("aa", ["k"], ["v"]))
        hs.create_index(ds, IndexConfig("bb", ["k"], ["v"]))
        perf_ledger.append(session.conf, {
            "kind": "bench", "name": "sf1_queries", "outcome": "ok",
            "wall_s": 1.0})
        return session, hs

    def test_index_filter(self, seeded):
        _session_, hs = seeded
        table = hs.perf_history(index="aa")
        names = table.column("name").to_pylist()
        assert names and all(n.endswith("(aa)") for n in names)
        assert hs.perf_history(index="nope").num_rows == 0

    def test_section_filter(self, seeded):
        _session_, hs = seeded
        table = hs.perf_history(section="sf1_queries")
        assert table.num_rows == 1
        assert table.column("kind").to_pylist() == ["bench"]

    def test_limit_keeps_most_recent(self, seeded):
        _session_, hs = seeded
        full = hs.perf_history()
        assert full.num_rows >= 3
        table = hs.perf_history(limit=2)
        assert table.num_rows == 2
        assert table.column("key").to_pylist() \
            == full.column("key").to_pylist()[-2:]

    def test_verb_mirrors_filters(self, seeded):
        from hyperspace_tpu.interop.server import _serve_verb

        session, _hs = seeded
        table = _serve_verb(session, {"verb": "perf_history",
                                      "section": "sf1_queries"})
        assert table.num_rows == 1
        table = _serve_verb(session, {"verb": "perf_history", "limit": 1})
        assert table.num_rows == 1
        with pytest.raises(ValueError, match='"limit"'):
            _serve_verb(session, {"verb": "perf_history", "limit": -1})
        with pytest.raises(ValueError, match='"index"'):
            _serve_verb(session, {"verb": "perf_history", "index": 3})
