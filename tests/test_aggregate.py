"""Aggregation: group-by / global aggregates over the plan IR.

The reference delegates aggregation to Spark (its TPC-DS corpus keeps
Aggregates above the rewritten scans — PlanStabilitySuite.scala); this
engine owns its executor, so Aggregate is a first-class node: rules
rewrite the patterns BELOW it, column pruning pushes only the needed
inputs into the scans, and answers must match pandas exactly."""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.plan.nodes import Aggregate, Project, Scan


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(6)
    n = 2000
    data = str(tmp_path / "data")
    os.makedirs(data)
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 50, n), type=pa.int64()),
        "v": pa.array(rng.random(n)),
        "w": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        "pad": pa.array(rng.random(n)),
    }), os.path.join(data, "f.parquet"))
    s = HyperspaceSession(system_path=str(tmp_path / "ix"))
    s.conf.num_buckets = 4
    return s, data


def _pandas_groupby(data, keys, col_name, func):
    df = pq.read_table(os.path.join(data, "f.parquet")).to_pandas()
    return getattr(df.groupby(keys)[col_name], func)()


def test_group_by_matches_pandas(env):
    s, data = env
    out = (s.read.parquet(data).group_by("k")
           .agg(total=("v", "sum"), biggest=("w", "max"))
           .collect().to_pandas().set_index("k").sort_index())
    want_sum = _pandas_groupby(data, "k", "v", "sum")
    want_max = _pandas_groupby(data, "k", "w", "max")
    np.testing.assert_allclose(out["total"], want_sum.sort_index())
    np.testing.assert_array_equal(out["biggest"], want_max.sort_index())


def test_global_agg_and_count_nulls(env, tmp_path):
    s, _ = env
    d = str(tmp_path / "nulls")
    os.makedirs(d)
    pq.write_table(pa.table({"a": [1, None, 3], "b": [2.0, 4.0, None]}),
                   os.path.join(d, "f.parquet"))
    out = s.read.parquet(d).agg(n=("a", "count"), mx=("b", "max")).collect()
    assert out.to_pylist() == [{"n": 2, "mx": 4.0}]


def test_aggregate_over_indexed_filter_prunes_and_matches(env):
    """Rules rewrite the filter below the Aggregate; pruning pushes only
    group/agg inputs into the scan (pad never read)."""
    s, data = env
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(data), IndexConfig("ki", ["k"], ["v"]))
    s.enable_hyperspace()
    ds = (s.read.parquet(data).filter(col("k") == 7)
          .group_by("k").agg(total=("v", "sum")))
    plan = ds.optimized_plan()
    scans = [x for x in plan.leaf_relations() if x.relation.index_scan_of]
    assert scans, plan.tree_string()
    # The aggregate survives on top of the rewritten subtree.
    assert isinstance(plan, Aggregate), plan.tree_string()
    got = ds.collect()
    s.disable_hyperspace()
    assert got.equals(ds.collect())


def test_pruning_pushes_only_agg_inputs(env):
    s, data = env
    ds = s.read.parquet(data).group_by("k").agg(total=("v", "sum"))
    plan = ds.optimized_plan()

    def projected(node):
        if isinstance(node, Project) and isinstance(node.child, Scan):
            return set(node.columns)
        for c in node.children:
            r = projected(c)
            if r is not None:
                return r
        return None

    cols = projected(plan)
    assert cols == {"k", "v"}, plan.tree_string()


def test_agg_over_join_answer_parity(env, tmp_path):
    s, data = env
    d2 = str(tmp_path / "dim")
    os.makedirs(d2)
    pq.write_table(pa.table({
        "k2": pa.array(np.arange(50, dtype=np.int64)),
        "name": pa.array([f"g{i % 5}" for i in range(50)]),
    }), os.path.join(d2, "f.parquet"))
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(data), IndexConfig("ki", ["k"], ["v"]))
    hs.create_index(s.read.parquet(d2), IndexConfig("di", ["k2"], ["name"]))
    s.enable_hyperspace()
    ds = (s.read.parquet(data)
          .join(s.read.parquet(d2), col("k") == col("k2"))
          .group_by("name").agg(total=("v", "sum")))
    got = ds.collect().to_pandas().set_index("name").sort_index()
    s.disable_hyperspace()
    want = ds.collect().to_pandas().set_index("name").sort_index()
    np.testing.assert_allclose(got["total"], want["total"])


def test_distinct_matches_pandas(env, tmp_path):
    s, _ = env
    d = str(tmp_path / "dup")
    os.makedirs(d)
    pq.write_table(pa.table({
        "a": [1, 1, 2, 2, 2, None],
        "b": ["x", "x", "y", "y", "z", "x"],
    }), os.path.join(d, "f.parquet"))
    out = (s.read.parquet(d).distinct().collect().to_pylist())
    assert sorted(map(repr, out)) == sorted(map(repr, [
        {"a": 1, "b": "x"}, {"a": 2, "b": "y"}, {"a": 2, "b": "z"},
        {"a": None, "b": "x"}]))
    # distinct after a projection dedups the projected columns only.
    one = s.read.parquet(d).select("b").distinct().collect()
    assert sorted(one.column("b").to_pylist()) == ["x", "y", "z"]
    # Duplicate projected names fail before distinct (scan concat);
    # self-join duplicates are renamed by the executor — the executor's
    # own unique-name guard in Distinct is defense in depth.
    with pytest.raises(Exception, match="duplicate field names"):
        s.read.parquet(d).select("a", "a").distinct().collect()


def test_having_filter_above_aggregate(env):
    """SQL HAVING is just Filter above Aggregate in this IR; pruning and
    execution compose without special casing."""
    s, data = env
    ds = (s.read.parquet(data).group_by("k").agg(total=("v", "sum"))
          .filter(col("total") > 20.0).sort("k"))
    out = ds.collect().to_pandas()
    df = pq.read_table(os.path.join(data, "f.parquet")).to_pandas()
    want = df.groupby("k")["v"].sum()
    want = want[want > 20.0]
    np.testing.assert_array_equal(out["k"], want.index.sort_values())
    np.testing.assert_allclose(out.set_index("k")["total"],
                               want.sort_index())


def test_statistical_functions_match_pandas(env):
    s, data = env
    out = (s.read.parquet(data).group_by("k")
           .agg(nd=("w", "count_distinct"), sd=("v", "stddev"),
                var=("v", "variance"))
           .collect().to_pandas().set_index("k").sort_index())
    df = pq.read_table(os.path.join(data, "f.parquet")).to_pandas()
    g = df.groupby("k")
    np.testing.assert_array_equal(out["nd"], g["w"].nunique().sort_index())
    # Arrow stddev/variance are POPULATION (ddof=0).
    np.testing.assert_allclose(out["sd"], g["v"].std(ddof=0).sort_index())
    np.testing.assert_allclose(out["var"], g["v"].var(ddof=0).sort_index())


def test_bad_function_rejected(env):
    s, data = env
    with pytest.raises(ValueError, match="Unsupported aggregate"):
        s.read.parquet(data).group_by("k").agg(x=("v", "median"))


def test_duplicate_specs_both_materialize(env):
    """Two aggs over the same (column, func) must produce BOTH outputs —
    positional mapping, not name-keyed."""
    s, data = env
    out = (s.read.parquet(data).group_by("k")
           .agg(a=("v", "sum"), b=("v", "sum")).collect())
    assert set(out.column_names) == {"k", "a", "b"}
    assert out.column("a").to_pylist() == out.column("b").to_pylist()


def test_count_counts_rows_including_null_keys(env, tmp_path):
    """group_by(g).count() is count(*): a null group key's rows count."""
    s, _ = env
    d = str(tmp_path / "ng")
    os.makedirs(d)
    pq.write_table(pa.table({"g": [1, None, None]}), os.path.join(d, "f.parquet"))
    out = s.read.parquet(d).group_by("g").count().collect().to_pylist()
    assert sorted(out, key=lambda r: (r["g"] is None, r["g"])) == [
        {"g": 1, "count": 1}, {"g": None, "count": 2}]


def test_empty_group_count_raises_clearly(env):
    s, data = env
    with pytest.raises(ValueError, match="Dataset.count"):
        s.read.parquet(data).group_by().count()


class TestDeviceAggregate:
    """Device segment-reduction kernel parity with the arrow host path."""

    def _env(self, tmp_path, n=5000, seed=0):
        import os

        import pyarrow.parquet as pq

        from hyperspace_tpu import HyperspaceSession

        rng = np.random.default_rng(seed)
        d = str(tmp_path / "agg")
        os.makedirs(d)
        pq.write_table(pa.table({
            "g1": pa.array(rng.integers(-50, 50, n), type=pa.int64()),
            "g2": pa.array(rng.integers(0, 4, n), type=pa.int32()),
            "v_int": pa.array(rng.integers(-1000, 1000, n), type=pa.int64()),
            "v_float": pa.array(rng.random(n) * 100 - 50),
            "s": pa.array([f"t{i % 3}" for i in range(n)]),
        }), f"{d}/p.parquet")
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        return s, d

    def _collect_both(self, s, build):
        s.conf.device_agg_min_rows = 1
        dev = build().collect()
        from hyperspace_tpu.execution.executor import Executor

        ex_stats = s.last_execution_stats
        assert any(a["strategy"] == "device-segment"
                   for a in ex_stats.get("aggregates", [])), ex_stats
        s.conf.device_agg_min_rows = 1 << 60
        host = build().collect()
        assert not (s.last_execution_stats or {}).get("aggregates")
        return dev, host

    @staticmethod
    def _canon(t):
        cols = sorted(t.column_names)
        return (t.select(cols)
                .sort_by([(c, "ascending") for c in cols]).to_pydict())

    def test_single_key_all_ops(self, tmp_path):
        from hyperspace_tpu import col

        s, d = self._env(tmp_path)

        def build():
            return (s.read.parquet(d).group_by("g1")
                    .agg(total=("v_int", "sum"),
                         lo=("v_float", "min"),
                         hi=("v_float", "max"),
                         avg=("v_float", "mean"),
                         n=("v_int", "count"),
                         rows=("", "count_all")))

        dev, host = self._collect_both(s, build)
        a, b = self._canon(dev), self._canon(host)
        assert a.keys() == b.keys()
        for k in a:
            if k in ("avg", "total", "lo", "hi"):
                np.testing.assert_allclose(a[k], b[k], rtol=1e-12)
            else:
                assert a[k] == b[k], k

    def test_multi_key_and_expression_input(self, tmp_path):
        from hyperspace_tpu import col

        s, d = self._env(tmp_path, seed=3)

        def build():
            return (s.read.parquet(d).group_by("g1", "g2")
                    .agg(rev=(col("v_float") * (1 - col("v_float") / 500),
                              "sum"),
                         n=("v_int", "count")))

        dev, host = self._collect_both(s, build)
        a, b = self._canon(dev), self._canon(host)
        for k in a:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-12)

    def test_string_key_stays_on_host(self, tmp_path):
        s, d = self._env(tmp_path)
        s.conf.device_agg_min_rows = 1
        out = (s.read.parquet(d).group_by("s")
               .agg(total=("v_int", "sum")).collect())
        assert not (s.last_execution_stats or {}).get("aggregates")
        assert out.num_rows == 3

    def test_nullable_input_stays_on_host(self, tmp_path):
        import os

        import pyarrow.parquet as pq

        from hyperspace_tpu import HyperspaceSession

        d = str(tmp_path / "nulls")
        os.makedirs(d)
        pq.write_table(pa.table({
            "g": pa.array([1, 1, 2], type=pa.int64()),
            "v": pa.array([1, None, 3], type=pa.int64()),
        }), f"{d}/p.parquet")
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        s.conf.device_agg_min_rows = 1
        out = (s.read.parquet(d).group_by("g")
               .agg(n=("v", "count")).sort("g").collect())
        assert not (s.last_execution_stats or {}).get("aggregates")
        assert out.column("n").to_pylist() == [1, 1]

    def test_temporal_and_bool_inputs_stay_on_host(self, tmp_path):
        """Temporal/bool inputs must not flip behavior or output schema at
        the device_agg_min_rows threshold (review finding): min(date32)
        works identically, sum(date32) raises identically."""
        import os

        import pyarrow.parquet as pq
        import pytest as _pytest

        from hyperspace_tpu import HyperspaceSession

        d = str(tmp_path / "temporal")
        os.makedirs(d)
        import datetime

        pq.write_table(pa.table({
            "g": pa.array([1, 1, 2], type=pa.int64()),
            "d": pa.array([datetime.date(2024, 1, i + 1) for i in range(3)]),
            "b": pa.array([True, False, True]),
        }), f"{d}/p.parquet")
        s = HyperspaceSession(system_path=str(tmp_path / "ix"))
        s.conf.device_agg_min_rows = 1
        out = (s.read.parquet(d).group_by("g")
               .agg(m=("d", "min")).sort("g").collect())
        assert not (s.last_execution_stats or {}).get("aggregates")
        assert out.column("m").to_pylist() == [datetime.date(2024, 1, 1),
                                               datetime.date(2024, 1, 3)]
        # Bool sum keeps the host path (and its uint64 schema).
        out2 = (s.read.parquet(d).group_by("g")
                .agg(t=("b", "sum")).sort("g").collect())
        assert not (s.last_execution_stats or {}).get("aggregates")
        assert out2.column("t").to_pylist() == [1, 1]
        with _pytest.raises(pa.ArrowNotImplementedError):
            (s.read.parquet(d).group_by("g").agg(t=("d", "sum")).collect())
