"""Fleet telemetry federation (telemetry/fleet.py; docs/16).

Covers the acceptance loop of the fleet observability plane with REAL
subprocesses over one index tree and both LogStore backends: heartbeat
publish/CAS-refresh/prune, merge semantics (counters by sum, gauges
per-process, histograms by bucket-sum with exemplar carry), federated
slow-query/trace resolution (live snapshots + persisted bundles), the
cluster doctor (stale heartbeat crit within two publish intervals,
duplicate-daemon warn, aggregate overload, kernel-ms skew), the
single-process device-skew doctor check, the inline ``fleet_status``
verb, the fleet scrape mode, and the fault matrix proving the publisher
never consumes an armed fault budget or breaks a query.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession
from hyperspace_tpu.telemetry import fleet, flight_recorder, metrics

POSIX = "hyperspace_tpu.io.log_store.PosixLogStore"
EMULATED = "hyperspace_tpu.io.log_store.EmulatedObjectStore"
BACKENDS = [POSIX, EMULATED]

# Child process: mint a trace id, retain one interesting flight record,
# bump a test counter, publish — then either exit ("once") or keep the
# publisher heartbeating until killed ("hold").
_CHILD = r"""
import json, os, sys, time
from hyperspace_tpu import HyperspaceSession
from hyperspace_tpu.interop.query import mint_trace_id
from hyperspace_tpu.telemetry import fleet, flight_recorder, metrics

system_path, store_class, mode, counter, interval = sys.argv[1:6]
s = HyperspaceSession(system_path=system_path)
s.conf.set("hyperspace.index.logStoreClass", store_class)
s.conf.set("hyperspace.fleet.telemetry.enabled", True)
s.conf.set("hyperspace.fleet.telemetry.publishIntervalS", float(interval))
tid = mint_trace_id()
metrics.inc("fleet.test.queries", float(counter))
flight_recorder.record(
    s.conf, kind="spec", outcome="FAILED", latency_ms=12.5,
    trace_id=tid, request_id=mint_trace_id(), error="seeded in child")
if mode == "hold":
    fleet.publisher_for(s).start()
else:
    assert fleet.publish_once(s.conf)
print(json.dumps({"process": fleet.process_identity(), "trace": tid,
                  "pid": os.getpid()}), flush=True)
if mode == "hold":
    time.sleep(600)
"""


def _spawn(system_path, store_class, mode, counter, interval):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(system_path), store_class,
         mode, str(counter), str(interval)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env)


def _read_children(procs):
    out = []
    for p in procs:
        line = p.stdout.readline()
        assert line, p.stderr.read()
        out.append(json.loads(line))
    return out


def _session(tmp_path, store_class=EMULATED, interval=30.0):
    s = HyperspaceSession(system_path=str(tmp_path / "ix"))
    s.conf.set("hyperspace.index.logStoreClass", store_class)
    s.conf.set("hyperspace.fleet.telemetry.publishIntervalS", interval)
    return s


def _put_snapshot(conf, snap):
    """Plant a foreign snapshot directly (a process we don't spawn)."""
    from hyperspace_tpu.telemetry.perf_ledger import store_for

    store = store_for(conf, fleet.fleet_root(conf))
    key = "hb-" + snap["process"]
    payload = json.dumps(snap, default=str).encode("utf-8")
    assert store.put_if_generation_match(key, payload,
                                         store.generation(key))


def _foreign(process, ts=None, role="client", counters=None,
             gauges=None, histograms=None, records=None,
             device_kernel_ms=None):
    return {
        "v": 1, "ts": time.time() if ts is None else ts,
        "process": process, "host": "h", "pid": 1, "role": role,
        "health": None,
        "metrics": {"counters": counters or {}, "gauges": gauges or {},
                    "histograms": histograms or {}},
        "device_kernel_ms": device_kernel_ms or {},
        "records": records or [],
    }


# ---------------------------------------------------------------------------
# Merge semantics (pure)
# ---------------------------------------------------------------------------
class TestMergeSemantics:
    def test_counters_sum_and_gauges_per_process(self):
        merged = fleet.merge_metrics([
            _foreign("a", counters={"x": 2.0, "y": 1.0},
                     gauges={"g": 5.0}),
            _foreign("b", counters={"x": 3.0}, gauges={"g": 7.0}),
        ])
        assert merged["counters"]["x"] == 5.0
        assert merged["counters"]["y"] == 1.0
        assert merged["gauges"]["g"] == {"a": 5.0, "b": 7.0}
        assert merged["processes"] == ["a", "b"]

    def test_histograms_bucket_sum_with_exemplar_carry(self):
        h1 = {"count": 2, "sum": 30.0, "min": 10.0, "max": 20.0,
              "buckets": {"10.0": 1, "25.0": 1},
              "exemplars": {"3": ["aaaa000011112222", 10.0]}}
        h2 = {"count": 1, "sum": 5.0, "min": 5.0, "max": 5.0,
              "buckets": {"5.0": 1},
              "exemplars": {"2": ["bbbb000011112222", 5.0]}}
        merged = fleet.merge_metrics([
            _foreign("a", histograms={"lat": h1}),
            _foreign("b", histograms={"lat": h2}),
        ])["histograms"]["lat"]
        assert merged["count"] == 3
        assert merged["sum"] == 35.0
        assert merged["min"] == 5.0 and merged["max"] == 20.0
        assert merged["mean"] == pytest.approx(35.0 / 3)
        assert merged["buckets"] == {"10.0": 1, "25.0": 1, "5.0": 1}
        assert merged["exemplars"]["3"] == ["aaaa000011112222", 10.0]
        assert merged["exemplars"]["2"] == ["bbbb000011112222", 5.0]

    def test_typed_snapshot_round_trips_through_json(self):
        metrics.reset()
        metrics.inc("c", 2.0)
        metrics.set_gauge("g", 1.5)
        metrics.observe("h", 3.0, exemplar="cccc000011112222")
        typed = json.loads(json.dumps(
            metrics.registry().typed_snapshot()))
        merged = fleet.merge_metrics([
            {"process": "p", "metrics": typed}])
        assert merged["counters"]["c"] == 2.0
        assert merged["gauges"]["g"] == {"p": 1.5}
        assert merged["histograms"]["h"]["count"] == 1
        assert any(ex[0] == "cccc000011112222"
                   for ex in merged["histograms"]["h"]
                   ["exemplars"].values())

    def test_skew_ratio(self):
        assert fleet.skew_ratio([100.0]) == 0.0
        assert fleet.skew_ratio([1.0, 2.0]) == 0.0  # under the floor
        assert fleet.skew_ratio([100.0, 100.0, 800.0]) == 8.0


# ---------------------------------------------------------------------------
# Snapshot + publisher (in-process)
# ---------------------------------------------------------------------------
class TestPublisher:
    def test_snapshot_shape_and_interesting_records(self, tmp_path):
        s = _session(tmp_path)
        metrics.reset()
        metrics.inc("exec.device.0.kernel_ms", 12.0)
        flight_recorder.reset()
        s.conf.set("hyperspace.serving.flightRecorder.healthySampleN", 1)
        flight_recorder.record(
            s.conf, kind="local", outcome="ok", latency_ms=1.0,
            trace_id="a" * 16, request_id="a" * 16)  # healthy sample
        flight_recorder.record(
            s.conf, kind="spec", outcome="FAILED", latency_ms=1.0,
            trace_id="b" * 16, request_id="b" * 16, error="x")
        snap = fleet.build_snapshot(s.conf)
        assert snap["process"] == fleet.process_identity()
        assert snap["role"] in ("client", "daemon", "server")
        assert snap["device_kernel_ms"] == {"0": 12.0}
        # Only the INTERESTING record rides the snapshot.
        assert [r["trace_id"] for r in snap["records"]] == ["b" * 16]
        flight_recorder.reset()

    def test_publish_disabled_is_noop(self, tmp_path):
        s = _session(tmp_path)
        assert fleet.publish_once(s.conf) is False
        assert fleet.live_snapshots(s.conf) == []

    @pytest.mark.parametrize("store_class", BACKENDS)
    def test_publish_refresh_and_status(self, tmp_path, store_class):
        s = _session(tmp_path, store_class)
        s.conf.set("hyperspace.fleet.telemetry.enabled", True)
        assert fleet.publish_once(s.conf)
        first = fleet.live_snapshots(s.conf)
        assert len(first) == 1
        ts1 = first[0]["ts"]
        time.sleep(0.02)
        assert fleet.publish_once(s.conf)  # CAS refresh, same key
        snaps = fleet.live_snapshots(s.conf)
        assert len(snaps) == 1
        assert snaps[0]["ts"] > ts1
        table = fleet.fleet_status_table(s.conf)
        assert table.num_rows == 1
        assert table.column("process")[0].as_py() == \
            fleet.process_identity()
        assert table.column("fresh")[0].as_py() is True

    def test_stale_flag_and_prune(self, tmp_path):
        s = _session(tmp_path, interval=30.0)
        s.conf.set("hyperspace.fleet.telemetry.enabled", True)
        _put_snapshot(s.conf, _foreign("dead-1-1", ts=time.time() - 120))
        _put_snapshot(s.conf, _foreign("old-2-2", ts=time.time() - 9000))
        table = fleet.fleet_status_table(s.conf)
        fresh = dict(zip(table.column("process").to_pylist(),
                         table.column("fresh").to_pylist()))
        assert fresh == {"dead-1-1": False, "old-2-2": False}
        # A publish prunes entries past pruneAfterS (default 600) but
        # keeps the merely-stale one for the doctor to report.
        assert fleet.publish_once(s.conf)
        procs = set(fleet.fleet_status_table(s.conf)
                    .column("process").to_pylist())
        assert "old-2-2" not in procs
        assert "dead-1-1" in procs
        assert fleet.process_identity() in procs
        assert metrics.registry().counter("fleet.pruned") >= 1

    def test_publish_never_consumes_fault_budget(self, tmp_path):
        """An armed store.put fault aimed at the engine is NOT consumed
        by fleet telemetry, and publishing still succeeds."""
        from hyperspace_tpu.io import faults

        s = _session(tmp_path)
        s.conf.set("hyperspace.fleet.telemetry.enabled", True)
        plan = faults.FaultPlan(site="store.put", kind="eio", at=1,
                                count=1)
        faults.install(plan)
        try:
            assert fleet.publish_once(s.conf)
            assert plan._calls == 0
        finally:
            faults.clear()

    def test_publish_failure_never_breaks_a_query(self, tmp_path):
        """A broken fleet store costs a counter, never a query: point
        the systemPath at an unwritable root, publish (False, no
        raise), and run a real collect."""
        data = tmp_path / "d"
        data.mkdir()
        pq.write_table(pa.table({"a": [1, 2, 3]}),
                       data / "f.parquet")
        s = HyperspaceSession(system_path="/proc/hs-no-such-root/ix")
        s.conf.set("hyperspace.fleet.telemetry.enabled", True)
        errors0 = metrics.registry().counter("fleet.publish.errors")
        assert fleet.publish_once(s.conf) is False
        assert metrics.registry().counter("fleet.publish.errors") \
            == errors0 + 1
        s2 = _session(tmp_path)
        s2.conf.set("hyperspace.fleet.telemetry.enabled", True)
        ds = s2.read.parquet(str(data))
        assert ds.collect().num_rows == 3

    def test_publisher_thread_start_requires_conf(self, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceError

        s = _session(tmp_path)
        with pytest.raises(HyperspaceError):
            fleet.publisher_for(s).start()
        assert fleet.maybe_start(s) is None
        s.conf.set("hyperspace.fleet.telemetry.enabled", True)
        s.conf.set("hyperspace.fleet.telemetry.publishIntervalS", 0.05)
        pub = fleet.maybe_start(s)
        try:
            assert pub is not None and pub.running()
            deadline = time.monotonic() + 10
            while not fleet.live_snapshots(s.conf) \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert len(fleet.live_snapshots(s.conf)) == 1
        finally:
            pub.stop()
        assert not pub.running()

    def test_role_escalates_never_lowers(self, monkeypatch):
        monkeypatch.setattr(fleet, "_role", "client")
        fleet.set_process_role("daemon")
        assert fleet.process_role() == "daemon"
        fleet.set_process_role("server")
        assert fleet.process_role() == "server"
        fleet.set_process_role("client")
        assert fleet.process_role() == "server"


# ---------------------------------------------------------------------------
# Doctor: single-process device skew + the fleet checks
# ---------------------------------------------------------------------------
class TestDoctor:
    def test_device_skew_check(self, tmp_path):
        s = _session(tmp_path)
        hs = Hyperspace(s)
        metrics.reset()
        metrics.inc("exec.device.0.kernel_ms", 100.0)
        metrics.inc("exec.device.1.kernel_ms", 100.0)
        metrics.inc("exec.device.2.kernel_ms", 100.0)
        check = hs.doctor().check("device_skew")
        assert check.status == "ok"
        metrics.inc("exec.device.2.kernel_ms", 900.0)  # 10x skew
        check = hs.doctor().check("device_skew")
        assert check.status == "warn"
        assert check.data["ratio"] >= 4.0
        # Conf 0 disables the grading.
        s.conf.set("hyperspace.doctor.deviceSkewWarn", 0.0)
        assert hs.doctor().check("device_skew").status == "ok"
        metrics.reset()

    def test_fleet_checks_absent_without_flag(self, tmp_path):
        hs = Hyperspace(_session(tmp_path))
        report = hs.doctor()
        assert report.check("fleet.heartbeats") is None

    def test_heartbeat_crit_and_daemon_warn(self, tmp_path):
        s = _session(tmp_path, interval=30.0)
        hs = Hyperspace(s)
        report = hs.doctor(fleet=True)
        assert report.check("fleet.heartbeats").status == "ok"
        _put_snapshot(s.conf, _foreign("p1-1-1", role="daemon"))
        _put_snapshot(s.conf, _foreign("p2-2-2", role="daemon"))
        _put_snapshot(s.conf, _foreign("p3-3-3",
                                       ts=time.time() - 300))
        report = hs.doctor(fleet=True)
        hb = report.check("fleet.heartbeats")
        assert hb.status == "crit"
        assert "p3-3-3" in hb.data["stale"]
        assert report.check("fleet.daemons").status == "warn"
        assert report.status == "crit"
        snap = metrics.snapshot()
        assert snap.get("health.fleet.status") == 2.0

    def test_fleet_serving_aggregate_and_skew(self, tmp_path):
        s = _session(tmp_path, interval=30.0)
        hs = Hyperspace(s)
        _put_snapshot(s.conf, _foreign(
            "srv1-1-1", counters={"serve.requests": 100.0,
                                  "serve.shed": 60.0}))
        _put_snapshot(s.conf, _foreign(
            "srv2-2-2", counters={"serve.requests": 100.0},
            device_kernel_ms={"0": 100.0}))
        _put_snapshot(s.conf, _foreign(
            "srv3-3-3", device_kernel_ms={"0": 100.0}))
        _put_snapshot(s.conf, _foreign(
            "srv4-4-4", device_kernel_ms={"0": 2000.0}))
        report = hs.doctor(fleet=True)
        serving = report.check("fleet.serving")
        # 60 sheds over 200 aggregate requests = 0.3 ratio: crit past
        # 5 x the default 0.05 warn threshold.
        assert serving.status == "crit"
        assert serving.data["requests"] == 200
        skew = report.check("fleet.skew")
        assert skew.status == "warn"
        assert skew.data["process_ratio"] >= 4.0


# ---------------------------------------------------------------------------
# Federated slow queries / trace (in-process: snapshots + bundles)
# ---------------------------------------------------------------------------
class TestFederatedRecords:
    def test_union_and_precedence(self, tmp_path):
        s = _session(tmp_path)
        flight_recorder.reset()
        flight_recorder.clear_bundles(s.conf)
        flight_recorder.record(
            s.conf, kind="spec", outcome="FAILED", latency_ms=1.0,
            trace_id="1" * 16, request_id="1" * 16, error="local")
        _put_snapshot(s.conf, _foreign(
            "live-9-9", records=[{
                "ts": time.time(), "trace_id": "2" * 16,
                "request_id": "2" * 16, "kind": "sql",
                "outcome": "DEADLINE", "latency_ms": 7.0,
                "slow": True, "reason": "error", "error": "remote"}]))
        # A drained process's record survives only in its bundle.
        flight_recorder.record(
            s.conf, kind="spec", outcome="FAILED", latency_ms=1.0,
            trace_id="3" * 16, request_id="3" * 16, error="bundled")
        assert flight_recorder.dump_diagnostics(s.conf)
        table = fleet.fleet_slow_queries_table(s.conf)
        by_trace = dict(zip(table.column("traceId").to_pylist(),
                            table.column("process").to_pylist()))
        assert by_trace["1" * 16] == fleet.process_identity()
        assert by_trace["2" * 16] == "live-9-9"
        rec = fleet.find_trace(s.conf, "2" * 16)
        assert rec["process"] == "live-9-9"
        assert rec["outcome"] == "DEADLINE"
        # Local ring wins for a locally retained id.
        assert fleet.find_trace(s.conf, "1" * 16)["process"] == \
            fleet.process_identity()
        # After the ring is gone (restart), the bundle still answers.
        flight_recorder.reset()
        rec = fleet.find_trace(s.conf, "3" * 16)
        assert rec is not None
        assert rec["process"].startswith("bundle-")
        assert fleet.find_trace(s.conf, "f" * 16) is None
        flight_recorder.clear_bundles(s.conf)

    def test_hyperspace_api_flags(self, tmp_path):
        s = _session(tmp_path)
        hs = Hyperspace(s)
        flight_recorder.reset()
        local = hs.slow_queries()
        assert "process" not in local.column_names
        fed = hs.slow_queries(fleet=True)
        assert "process" in fed.column_names
        assert hs.trace("e" * 16, fleet=True) is None


# ---------------------------------------------------------------------------
# Interop: the inline verb + the fleet scrape mode
# ---------------------------------------------------------------------------
class TestInterop:
    def test_fleet_status_verb_and_doctor_fleet(self, tmp_path):
        from hyperspace_tpu.interop.server import QueryClient, QueryServer

        s = _session(tmp_path)
        s.conf.set("hyperspace.fleet.telemetry.enabled", True)
        assert fleet.publish_once(s.conf)
        with QueryServer(s) as server:
            with QueryClient(server.address) as qc:
                table = qc.query({"verb": "fleet_status"})
                assert fleet.process_identity() in \
                    table.column("process").to_pylist()
            with QueryClient(server.address) as qc:
                table = qc.query({"verb": "doctor", "fleet": True})
                assert "fleet.heartbeats" in \
                    table.column("check").to_pylist()

    def test_drain_deregisters_heartbeat(self, tmp_path):
        """A drained server is a PLANNED exit: its heartbeat key is
        deleted, so the fleet doctor never pages crit on a rolling
        restart (SIGKILL skips this path — that's how a dead process
        IS flagged)."""
        from hyperspace_tpu.interop.server import QueryServer

        s = _session(tmp_path)
        s.conf.set("hyperspace.fleet.telemetry.enabled", True)
        s.conf.set("hyperspace.fleet.telemetry.publishIntervalS", 0.05)
        server = QueryServer(s).start()
        try:
            deadline = time.monotonic() + 10
            while not fleet.live_snapshots(s.conf) \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            snaps = fleet.live_snapshots(s.conf)
            assert snaps and snaps[0]["role"] == "server"
            server.drain(grace_s=5.0)
            assert fleet.live_snapshots(s.conf) == []
            assert Hyperspace(s).doctor(fleet=True).check(
                "fleet.heartbeats").status == "ok"
        finally:
            server.stop()
            from hyperspace_tpu.lifecycle import daemon as _daemon

            _daemon.clear_drain()

    def test_scrape_fleet_mode(self, tmp_path):
        from hyperspace_tpu.interop.server import MetricsScrapeServer

        s = _session(tmp_path)
        s.conf.set("hyperspace.fleet.telemetry.enabled", True)
        _put_snapshot(s.conf, _foreign(
            "peer-8-8", counters={"serve.requests": 3.0}))
        with pytest.raises(ValueError):
            MetricsScrapeServer(fleet=True)
        with MetricsScrapeServer(session=s, fleet=True) as ms:
            host, port = ms.address
            body = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=30) \
                .read().decode("utf-8")
        assert 'process="peer-8-8"' in body
        assert f'process="{fleet.process_identity()}"' in body
        assert 'hyperspace_serve_requests{process="peer-8-8"} 3' in body


# ---------------------------------------------------------------------------
# Real subprocesses over one tree (the acceptance loop)
# ---------------------------------------------------------------------------
class TestSubprocessFleet:
    @pytest.mark.parametrize("store_class", BACKENDS)
    def test_three_process_merge_and_trace(self, tmp_path, store_class):
        """3 real processes publish over the shared tree: merged
        counters equal the per-process sum, and a trace minted in one
        process resolves from THIS one via trace(id, fleet=True)."""
        s = _session(tmp_path, store_class, interval=30.0)
        hs = Hyperspace(s)
        procs = [_spawn(tmp_path / "ix", store_class, "once", c, 30.0)
                 for c in (2, 3, 4)]
        try:
            children = _read_children(procs)
            for p in procs:
                assert p.wait(timeout=60) == 0
            status = hs.fleet_status()
            assert status.num_rows == 3
            assert all(status.column("fresh").to_pylist())
            merged = hs.fleet_metrics()
            assert merged["counters"]["fleet.test.queries"] == 9.0
            for child in children:
                rec = hs.trace(child["trace"], fleet=True)
                assert rec is not None
                assert rec["process"] == child["process"]
                assert rec["error"] == "seeded in child"
        finally:
            for p in procs:
                p.kill()
                p.wait(timeout=30)

    def test_acceptance_kill_flips_fleet_doctor_to_crit(self, tmp_path):
        """The end-to-end fleet demo: 3 live publishers -> all fresh in
        fleet_status -> counters merge -> a record from process B
        resolves from here -> SIGKILL B -> doctor(fleet=True) goes crit
        naming B within 2 publish intervals."""
        interval = 0.4
        s = _session(tmp_path, interval=interval)
        hs = Hyperspace(s)
        procs = [_spawn(tmp_path / "ix", EMULATED, "hold", 5, interval)
                 for _ in range(3)]
        try:
            children = _read_children(procs)
            # Steady state: every publisher fresh, the merged counter
            # carrying the 3-process sum, and the fleet doctor ok —
            # polled together (a 0.4s heartbeat can transiently look
            # stale on a loaded box).
            deadline = time.monotonic() + 60
            state = {}
            while time.monotonic() < deadline:
                status = hs.fleet_status()
                fresh = dict(zip(status.column("process").to_pylist(),
                                 status.column("fresh").to_pylist()))
                merged = hs.fleet_metrics()["counters"].get(
                    "fleet.test.queries", 0.0)
                hb = hs.doctor(fleet=True).check("fleet.heartbeats")
                state = {"fresh": fresh, "merged": merged,
                         "hb": hb.status}
                if all(fresh.get(c["process"]) for c in children) \
                        and merged == 15.0 and hb.status == "ok":
                    break
                time.sleep(0.05)
            else:
                pytest.fail(f"fleet never reached steady state: "
                            f"{state}")
            victim = children[1]
            assert hs.trace(victim["trace"], fleet=True)["process"] \
                == victim["process"]
            os.kill(victim["pid"], signal.SIGKILL)
            t_kill = time.monotonic()
            while time.monotonic() < t_kill + 2 * interval + 2.0:
                hb = hs.doctor(fleet=True).check("fleet.heartbeats")
                if hb.status == "crit":
                    break
                time.sleep(0.05)
            assert hb.status == "crit"
            assert victim["process"] in hb.data["stale"]
            # Within 2 publish intervals of the last heartbeat (the
            # conf-derived stale threshold), plus polling slack.
            assert time.monotonic() - t_kill <= 2 * interval + 2.0
            # The dead process's record is STILL resolvable — its last
            # snapshot outlives it until pruneAfterS.
            assert hs.trace(victim["trace"], fleet=True) is not None
        finally:
            for p in procs:
                p.kill()
                p.wait(timeout=30)

    def test_restart_mints_new_identity(self, tmp_path):
        """A restarted process (same tree, new pid/start) publishes
        under a NEW key; the old process's interesting records stay
        resolvable from its last snapshot."""
        s = _session(tmp_path, interval=30.0)
        hs = Hyperspace(s)
        p1 = _spawn(tmp_path / "ix", EMULATED, "once", 1, 30.0)
        first = _read_children([p1])[0]
        assert p1.wait(timeout=60) == 0
        p2 = _spawn(tmp_path / "ix", EMULATED, "once", 1, 30.0)
        second = _read_children([p2])[0]
        assert p2.wait(timeout=60) == 0
        assert first["process"] != second["process"]
        procs = set(hs.fleet_status().column("process").to_pylist())
        assert {first["process"], second["process"]} <= procs
        assert hs.trace(first["trace"], fleet=True)["process"] \
            == first["process"]
        assert hs.trace(second["trace"], fleet=True)["process"] \
            == second["process"]


# ---------------------------------------------------------------------------
# Multi-host build claims check (docs/21)
# ---------------------------------------------------------------------------
class TestBuildClaimsCheck:
    """``fleet.build_claims`` grades leftover multi-host build claims
    (parallel/multihost_build.scan_build_claims) against the
    heartbeats: expired + nobody alive = reclaimable debris (warn);
    fresh + dead holder = a build stalling a full TTL (crit)."""

    def _plant_claim(self, conf, holder, ttl_s, build="build-1-abc"):
        from hyperspace_tpu.lifecycle.lease import WorkClaims
        from hyperspace_tpu.parallel import multihost_build
        from hyperspace_tpu.telemetry.perf_ledger import store_for

        store = store_for(conf, os.path.join(
            multihost_build.build_root(conf), build))
        claims = WorkClaims(store, conf, owner=holder, ttl_s=ttl_s)
        assert claims.try_claim("chunk-00000") is not None

    def test_no_claims_is_ok(self, tmp_path):
        hs = Hyperspace(_session(tmp_path, interval=30.0))
        assert hs.doctor(fleet=True).check(
            "fleet.build_claims").status == "ok"

    def test_expired_claim_no_heartbeat_warns(self, tmp_path):
        from hyperspace_tpu.lifecycle import journal as lifecycle_journal

        s = _session(tmp_path, interval=30.0)
        hs = Hyperspace(s)
        self._plant_claim(s.conf, "dead-host-1-1", ttl_s=0.2)
        time.sleep(0.3)
        before = len(lifecycle_journal.records(s.conf))
        check = hs.doctor(fleet=True).check("fleet.build_claims")
        assert check.status == "warn"
        assert check.data["expired_no_heartbeat"][0]["holder"] \
            == "dead-host-1-1"
        # The check is READ-ONLY (the doctor verb serves inline while
        # the admission queue sheds): grading must not write anything.
        # The journaled trail comes from the claim protocol itself —
        # the coordinator's expired-sighting records and WorkClaims'
        # reclaim/fence records, covered in test_multihost_build.
        assert len(lifecycle_journal.records(s.conf)) == before

    def test_fresh_claim_dead_holder_is_crit(self, tmp_path):
        s = _session(tmp_path, interval=30.0)
        hs = Hyperspace(s)
        self._plant_claim(s.conf, "dead-host-1-1", ttl_s=60.0)
        # SOMEBODY heartbeats (so liveness is gradeable) — but not the
        # claim's holder.
        _put_snapshot(s.conf, _foreign("other-host-2-2"))
        check = hs.doctor(fleet=True).check("fleet.build_claims")
        assert check.status == "crit"
        assert check.data["fresh_dead_holder"][0]["item"] == "chunk-00000"

    def test_fresh_claim_heartbeating_holder_is_ok(self, tmp_path):
        s = _session(tmp_path, interval=30.0)
        hs = Hyperspace(s)
        self._plant_claim(s.conf, "live-host-3-3", ttl_s=60.0)
        _put_snapshot(s.conf, _foreign("live-host-3-3"))
        check = hs.doctor(fleet=True).check("fleet.build_claims")
        assert check.status == "ok"
        assert check.data["pending"] == 1

    def test_fresh_claim_without_any_heartbeats_not_crit(self, tmp_path):
        # Fleet telemetry off: nothing to cross-check a live claim
        # against — the check must not page crit on a healthy build.
        s = _session(tmp_path, interval=30.0)
        hs = Hyperspace(s)
        self._plant_claim(s.conf, "host-4-4", ttl_s=60.0)
        assert hs.doctor(fleet=True).check(
            "fleet.build_claims").status == "ok"
