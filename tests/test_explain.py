"""Explain / plan-analysis tests.

Mirrors the reference's ExplainTest.scala (side-by-side output shape,
highlight markers, used-index list, verbose operator stats),
DisplayModeTest.scala (mode tags + custom highlight overrides), and
BufferStreamTest.scala (highlight keeps indentation outside the tags).
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.plananalysis import (
    BufferStream,
    ConsoleMode,
    HTMLMode,
    PlainTextMode,
    get_display_mode,
)


@pytest.fixture()
def session(tmp_index_root, tmp_path):
    s = HyperspaceSession(system_path=tmp_index_root)
    s.conf.num_buckets = 4
    n = 100
    table = pa.table({
        "id": np.arange(n, dtype=np.int64),
        "name": pa.array([f"n{i}" for i in range(n)]),
        "other": pa.array(np.arange(n) * 2, type=pa.int64()),
    })
    data = tmp_path / "data"
    data.mkdir()
    pq.write_table(table, str(data / "part-0.parquet"))
    s.data_path = str(data)
    return s


class TestDisplayModes:
    def test_plaintext_default_tags(self):
        mode = PlainTextMode()
        assert mode.highlight_tag.open == "<----"
        assert mode.highlight_tag.close == "---->"
        assert mode.begin_end_tag.open == ""
        assert mode.new_line == "\n"

    def test_html_tags(self):
        mode = HTMLMode()
        assert mode.begin_end_tag.open == "<pre>"
        assert mode.begin_end_tag.close == "</pre>"
        assert mode.new_line == "<br>"
        assert "LightGreen" in mode.highlight_tag.open

    def test_console_tags(self):
        mode = ConsoleMode()
        assert mode.highlight_tag.open == "\033[42m"
        assert mode.highlight_tag.close == "\033[0m"

    def test_custom_highlight_override(self):
        from hyperspace_tpu.config import HyperspaceConf

        conf = HyperspaceConf()
        conf.display_mode = "html"
        conf.highlight_begin_tag = "**"
        conf.highlight_end_tag = "**"
        mode = get_display_mode(conf)
        assert isinstance(mode, HTMLMode)
        assert mode.highlight_tag.open == "**"

    def test_unknown_mode_raises(self):
        from hyperspace_tpu.config import HyperspaceConf

        conf = HyperspaceConf()
        conf.display_mode = "nope"
        with pytest.raises(ValueError, match="display mode"):
            get_display_mode(conf)


class TestBufferStream:
    def test_highlight_keeps_indentation_outside_tags(self):
        stream = BufferStream(PlainTextMode())
        stream.highlight("    Scan foo  ")
        assert str(stream) == "    <----Scan foo---->  "

    def test_highlight_blank_passthrough(self):
        stream = BufferStream(PlainTextMode())
        stream.highlight("   ")
        assert str(stream) == "   "

    def test_with_tag_wraps_html(self):
        stream = BufferStream(HTMLMode())
        stream.write_line("x")
        assert stream.with_tag() == "<pre>x<br></pre>"


class TestExplain:
    def _indexed_session(self, session):
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(session.data_path),
                        IndexConfig("eidx", ["id"], ["name"]))
        return hs

    def test_explain_shape_and_highlight(self, session):
        hs = self._indexed_session(session)
        ds = (session.read.parquet(session.data_path)
              .filter(col("id") == 1).select("id", "name"))
        out = hs.explain(ds)
        assert "Plan with indexes:" in out
        assert "Plan without indexes:" in out
        assert "Indexes used:" in out
        assert "eidx" in out
        # The differing scans are highlighted; shared nodes are not.
        assert "<----Scan Hyperspace(Type: CI, Name: eidx)" in out
        with_section = out.split("Plan without indexes:")[0]
        assert "<----Filter" not in with_section

    def test_explain_no_indexes_used(self, session):
        hs = Hyperspace(session)
        ds = session.read.parquet(session.data_path).filter(col("id") == 1)
        out = hs.explain(ds)
        assert "(none)" in out

    def test_explain_verbose_operator_stats(self, session):
        hs = self._indexed_session(session)
        ds = (session.read.parquet(session.data_path)
              .filter(col("id") == 1).select("id", "name"))
        out = hs.explain(ds, verbose=True)
        assert "Physical operator stats:" in out
        # PHYSICAL operators, spelled out (PhysicalOperatorAnalyzer intent):
        # the indexed plan scans the index, the baseline scans files.
        assert "IndexScanExec" in out
        assert "FileScanExec" in out
        # Per-scan IO detail: files read / listed and bytes.
        assert "Scan IO (with indexes):" in out
        import re

        assert re.search(r"files \d+/\d+, \d+\.\d\d MB", out), out

    def test_explain_verbose_join_strategy(self, session, tmp_path):
        """The predicted join operator comes from the executor's own
        precheck: a numeric-key join without matching bucketed index scans
        on both sides reports a plain sort-merge."""
        hs = self._indexed_session(session)
        other_dir = tmp_path / "other"
        other_dir.mkdir()
        pq.write_table(pa.table({
            "rid": np.arange(50, dtype=np.int64),
            "w": np.arange(50, dtype=np.int64) * 3,
        }), str(other_dir / "p.parquet"))
        ds = (session.read.parquet(session.data_path)
              .join(session.read.parquet(str(other_dir)),
                    col("id") == col("rid"))
              .select("id", "name", "w"))
        out = hs.explain(ds, verbose=True)
        assert "SortMergeJoinExec" in out
        # Index the right side too: the rewrite bucketes both sides and the
        # prediction flips to the shuffle-free per-bucket merge.
        hs.create_index(session.read.parquet(str(other_dir)),
                        IndexConfig("ridx", ["rid"], ["w"]))
        out2 = hs.explain(ds, verbose=True)
        assert "PerBucketMergeJoinExec" in out2

    def test_explain_html_mode(self, session):
        hs = self._indexed_session(session)
        session.conf.display_mode = "html"
        ds = (session.read.parquet(session.data_path)
              .filter(col("id") == 1).select("id", "name"))
        out = hs.explain(ds)
        assert out.startswith("<pre>")
        assert out.endswith("</pre>")
        assert "<br>" in out
        assert "LightGreen" in out

    def test_explain_restores_enabled_state(self, session):
        hs = self._indexed_session(session)
        ds = session.read.parquet(session.data_path).filter(col("id") == 1)
        session.enable_hyperspace()
        hs.explain(ds)
        assert session.is_hyperspace_enabled()
        session.disable_hyperspace()
        hs.explain(ds)
        assert not session.is_hyperspace_enabled()
