"""Outer/semi/anti joins: every SQL join type, plain and bucket-aligned.

The reference's engine (Spark) runs all join types while its REWRITE is
scoped to inner equi-joins (JoinIndexRule.scala:134-140); this engine must
do the same.  Oracle: pandas merge / membership, with null-key rows handled
by SQL semantics (null keys never match, but outer/anti joins still emit
the rows)."""

from __future__ import annotations

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col

HOWS = ("inner", "left", "right", "full", "semi", "anti")


def _pandas_join(ldf: pd.DataFrame, rdf: pd.DataFrame, lk: str, rk: str,
                 how: str) -> pd.DataFrame:
    """Oracle with SQL null-key semantics (pandas would match NaN == NaN)."""
    lv = ldf[ldf[lk].notna()]
    rv = rdf[rdf[rk].notna()]
    if how == "semi":
        return ldf[ldf[lk].isin(rv[rk])]
    if how == "anti":
        return ldf[~ldf[lk].isin(rv[rk])]
    matched = lv.merge(rv, left_on=lk, right_on=rk, how="inner")
    parts = [matched]
    if how in ("left", "full"):
        un = ldf[~ldf[lk].isin(rv[rk])]
        parts.append(un.reindex(columns=matched.columns))
    if how in ("right", "full"):
        un = rdf[~rdf[rk].isin(lv[lk])]
        parts.append(un.reindex(columns=matched.columns))
    if how == "inner":
        return matched
    return pd.concat(parts, ignore_index=True)


def _canon(df: pd.DataFrame) -> pd.DataFrame:
    cols = sorted(df.columns)
    return (df[cols].sort_values(cols, na_position="first")
            .reset_index(drop=True))


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(3)
    n_l, n_r = 500, 200
    ldf = pd.DataFrame({
        # Keys overlap partially; some left keys have no right match and
        # vice versa; ~5% null keys on each side.
        "lk": [None if rng.random() < 0.05 else int(rng.integers(0, 300))
               for _ in range(n_l)],
        "lval": rng.random(n_l),
    })
    rdf = pd.DataFrame({
        "rk": [None if rng.random() < 0.05 else int(rng.integers(100, 400))
               for _ in range(n_r)],
        "rval": rng.random(n_r),
    })
    l_dir, r_dir = str(tmp_path / "l"), str(tmp_path / "r")
    for d, df, key in ((l_dir, ldf, "lk"), (r_dir, rdf, "rk")):
        os.makedirs(d)
        t = pa.table({key: pa.array(df[key], type=pa.int64()),
                      df.columns[1]: pa.array(df[df.columns[1]])})
        for i in range(2):
            pq.write_table(t.slice(i * len(df) // 2, len(df) // 2),
                           os.path.join(d, f"part-{i:05d}.parquet"))
    s = HyperspaceSession(system_path=str(tmp_path / "ix"))
    s.conf.num_buckets = 4
    return s, l_dir, r_dir, ldf, rdf


@pytest.mark.parametrize("how", HOWS)
def test_plain_join_matches_oracle(env, how):
    s, l_dir, r_dir, ldf, rdf = env
    out = (s.read.parquet(l_dir)
           .join(s.read.parquet(r_dir), col("lk") == col("rk"), how=how)
           .collect().to_pandas())
    want = _pandas_join(ldf, rdf, "lk", "rk", how)
    pd.testing.assert_frame_equal(_canon(out), _canon(want),
                                  check_dtype=False)


@pytest.mark.parametrize("how", HOWS)
def test_bucket_aligned_join_matches_oracle(env, how):
    """Both sides covered by matching-bucket indexes: the executor takes
    the bucket-aligned path for EVERY join type (per-bucket null-extension
    composes), and answers equal the plain path's."""
    s, l_dir, r_dir, ldf, rdf = env
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(l_dir), IndexConfig("jl", ["lk"], ["lval"]))
    hs.create_index(s.read.parquet(r_dir), IndexConfig("jr", ["rk"], ["rval"]))
    s.enable_hyperspace()
    ds = (s.read.parquet(l_dir)
          .join(s.read.parquet(r_dir), col("lk") == col("rk"), how=how))
    out = ds.collect().to_pandas()
    want = _pandas_join(ldf, rdf, "lk", "rk", how)
    pd.testing.assert_frame_equal(_canon(out), _canon(want),
                                  check_dtype=False)
    if how == "inner":
        # Inner equi-join: the JoinIndexRule rewrite fires and the executor
        # runs bucket-aligned.
        plan = ds.optimized_plan()
        used = [sc for sc in plan.leaf_relations()
                if sc.relation.index_scan_of]
        assert len(used) == 2, plan.tree_string()
        stats = s.last_execution_stats
        assert any(j.get("strategy") == "bucketed" for j in stats["joins"])
    else:
        # Reference scope: no JOIN rewrite for non-inner joins
        # (JoinIndexRule.scala:134-140).
        plan = ds.optimized_plan()
        used = [sc for sc in plan.leaf_relations()
                if sc.relation.index_scan_of]
        assert not used, plan.tree_string()


@pytest.mark.parametrize("how", ("left", "full", "anti", "semi"))
def test_bucket_aligned_outer_with_filtered_side(env, how):
    """A filter over one indexed side (FilterIndexRule rewrite with bucket
    spec) plus a bucketed other side: non-inner joins execute bucket-aligned
    when the specs match, including one-sided buckets (unmatched rows of a
    bucket absent on the other side must still surface)."""
    s, l_dir, r_dir, ldf, rdf = env
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(l_dir), IndexConfig("jl", ["lk"], ["lval"]))
    hs.create_index(s.read.parquet(r_dir), IndexConfig("jr", ["rk"], ["rval"]))
    s.enable_hyperspace()
    s.conf.filter_rule_use_bucket_spec = True
    # Restrict the right side so some left buckets have no right rows at
    # all — exercises the one-sided-bucket donor path.
    sevens = list(range(0, 400, 7))
    sub_r = rdf[rdf["rk"].notna() & rdf["rk"].isin(sevens)]
    ds = (s.read.parquet(l_dir)
          .join(s.read.parquet(r_dir).filter(col("rk").isin(sevens)),
                col("lk") == col("rk"), how=how))
    out = ds.collect().to_pandas()
    want = _pandas_join(ldf, sub_r, "lk", "rk", how)
    pd.testing.assert_frame_equal(_canon(out), _canon(want),
                                  check_dtype=False)


def test_join_how_validation(env):
    s, l_dir, r_dir, _ldf, _rdf = env
    with pytest.raises(ValueError, match="join type"):
        s.read.parquet(l_dir).join(s.read.parquet(r_dir),
                                   col("lk") == col("rk"), how="cross")


def test_semi_anti_output_columns(env):
    s, l_dir, r_dir, _ldf, _rdf = env
    semi = (s.read.parquet(l_dir)
            .join(s.read.parquet(r_dir), col("lk") == col("rk"), how="semi"))
    assert semi.columns == ["lk", "lval"]
    out = semi.collect()
    assert out.column_names == ["lk", "lval"]


@pytest.mark.parametrize("how", ("left", "full", "anti"))
def test_hybrid_outer_join_with_appended_rows(env, how):
    """Hybrid scan + non-inner join: the left side's index has appended
    source rows (read raw and routed into the bucket space when the filter
    rewrite fires); answers must equal the unindexed run."""
    s, l_dir, r_dir, ldf, rdf = env
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(l_dir), IndexConfig("jl", ["lk"], ["lval"]))
    hs.create_index(s.read.parquet(r_dir), IndexConfig("jr", ["rk"], ["rval"]))
    # Mutate the left source AFTER indexing.
    appended = pd.DataFrame({"lk": [100, 101, 399], "lval": [0.1, 0.2, 0.3]})
    pq.write_table(pa.table({"lk": pa.array(appended["lk"], type=pa.int64()),
                             "lval": pa.array(appended["lval"])}),
                   os.path.join(l_dir, "part-appended.parquet"))
    s.conf.hybrid_scan_enabled = True
    lo = 50
    ds = (s.read.parquet(l_dir).filter(col("lk") >= lo)
          .join(s.read.parquet(r_dir), col("lk") == col("rk"), how=how))
    s.enable_hyperspace()
    got = ds.collect().to_pandas()
    s.disable_hyperspace()
    want = ds.collect().to_pandas()
    pd.testing.assert_frame_equal(_canon(got), _canon(want),
                                  check_dtype=False)
