"""Action state-machine tests: transitions, validation, cancel recovery.

Mirrors actions/ActionTest.scala, DeleteActionTest, RestoreActionTest,
VacuumActionTest, CancelActionTest.
"""

import os

import pytest

from hyperspace_tpu.actions.cancel import CancelAction
from hyperspace_tpu.actions.delete import DeleteAction
from hyperspace_tpu.actions.restore import RestoreAction
from hyperspace_tpu.actions.vacuum import VacuumAction
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import States
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.telemetry.events import CollectingEventLogger, set_event_logger
from tests.utils import sample_entry


@pytest.fixture()
def active_index(tmp_index_root):
    """An index committed as ACTIVE at log id 2 (post-create layout)."""
    path = os.path.join(tmp_index_root, "idx")
    mgr = IndexLogManager(path)
    mgr.write_log(1, sample_entry(state=States.CREATING))
    mgr.write_log(2, sample_entry(state=States.ACTIVE))
    mgr.create_latest_stable_log(2)
    return path, mgr


def test_delete_then_restore(active_index):
    path, mgr = active_index
    DeleteAction(mgr).run()
    assert mgr.get_latest_log().state == States.DELETED
    assert mgr.get_latest_log().id == 4  # begin at 3, end at 4
    assert mgr.get_latest_stable_log().state == States.DELETED

    RestoreAction(mgr).run()
    assert mgr.get_latest_log().state == States.ACTIVE
    assert mgr.get_latest_stable_log().id == 6


def test_delete_requires_active(active_index):
    path, mgr = active_index
    DeleteAction(mgr).run()
    with pytest.raises(HyperspaceError):
        DeleteAction(mgr).run()


def test_restore_requires_deleted(active_index):
    _, mgr = active_index
    with pytest.raises(HyperspaceError):
        RestoreAction(mgr).run()


def test_vacuum_removes_data(active_index):
    path, mgr = active_index
    dm = IndexDataManager(path)
    os.makedirs(dm.version_path(0))
    os.makedirs(dm.version_path(1))
    with pytest.raises(HyperspaceError):
        VacuumAction(mgr, dm).run()  # must be DELETED first
    DeleteAction(mgr).run()
    VacuumAction(mgr, dm).run()
    assert dm.versions() == []
    assert mgr.get_latest_log().state == States.DOESNOTEXIST


def test_cancel_rolls_back_to_stable(active_index):
    path, mgr = active_index
    # Simulate an action dying mid-flight: transient entry is latest.
    mgr.write_log(3, sample_entry(state=States.REFRESHING))
    with pytest.raises(HyperspaceError):
        DeleteAction(mgr).run()  # refuses: not ACTIVE
    CancelAction(mgr).run()
    latest = mgr.get_latest_log()
    assert latest.state == States.ACTIVE
    assert latest.id == 4
    # Now normal operation resumes.
    DeleteAction(mgr).run()
    assert mgr.get_latest_log().state == States.DELETED


def test_cancel_vacuuming_goes_to_doesnotexist(active_index):
    path, mgr = active_index
    mgr.write_log(3, sample_entry(state=States.VACUUMING))
    CancelAction(mgr).run()
    assert mgr.get_latest_log().state == States.DOESNOTEXIST


def test_cancel_rejects_stable(active_index):
    _, mgr = active_index
    with pytest.raises(HyperspaceError):
        CancelAction(mgr).run()


def test_action_events_emitted(active_index):
    _, mgr = active_index
    logger = CollectingEventLogger()
    set_event_logger(logger)
    try:
        DeleteAction(mgr).run()
    finally:
        set_event_logger(None)
    kinds = [e.kind for e in logger.events]
    assert "DeleteActionEvent" in kinds
    assert logger.events[-1].state == States.DELETED


class TestConfEventLogger:
    def test_conf_selected_logger_receives_events(self, tmp_path):
        """The eventLoggerClass conf analog: a logger named in conf is
        installed at session construction and sees action events."""
        from hyperspace_tpu import Hyperspace, HyperspaceConf, HyperspaceSession, IndexConfig
        from hyperspace_tpu.telemetry.events import (
            get_event_logger,
            set_event_logger,
        )
        from tests.utils import write_sample_parquet

        set_event_logger(None)  # reset so conf resolution applies
        try:
            conf = HyperspaceConf()
            conf.event_logger = "CollectingEventLogger"
            s = HyperspaceSession(system_path=str(tmp_path / "ix"), conf=conf)
            logger = get_event_logger()
            assert type(logger).__name__ == "CollectingEventLogger"
            data = str(tmp_path / "data")
            write_sample_parquet(data, n_files=1)
            s.conf.num_buckets = 2
            Hyperspace(s).create_index(s.read.parquet(data),
                                       IndexConfig("i", ["id"], ["name"]))
            kinds = [e.kind for e in logger.events]
            assert "CreateActionEvent" in kinds
        finally:
            set_event_logger(None)

    def test_explicit_noop_beats_conf(self, tmp_path):
        from hyperspace_tpu import HyperspaceConf, HyperspaceSession
        from hyperspace_tpu.telemetry.events import (
            NoOpEventLogger,
            get_event_logger,
            set_event_logger,
        )

        set_event_logger(None)
        try:
            explicit = NoOpEventLogger()
            set_event_logger(explicit)  # explicit opt-out
            conf = HyperspaceConf()
            conf.event_logger = "CollectingEventLogger"
            HyperspaceSession(system_path=str(tmp_path / "ix"), conf=conf)
            assert get_event_logger() is explicit
        finally:
            set_event_logger(None)

    def test_dotted_path_and_unknown_name(self):
        from hyperspace_tpu.telemetry.events import resolve_event_logger

        logger = resolve_event_logger(
            "hyperspace_tpu.telemetry.events.CollectingEventLogger")
        assert type(logger).__name__ == "CollectingEventLogger"
        import pytest

        with pytest.raises(ValueError, match="Unknown event logger"):
            resolve_event_logger("nope")
