#!/usr/bin/env python
"""Zero-dependency hslint launcher.

``python -m hyperspace_tpu.lint`` is the canonical invocation, but it
executes ``hyperspace_tpu/__init__.py`` on the way in — which imports
the engine (numpy, pyarrow, jax).  The linter itself is pure stdlib and
parses rather than imports, so CI's lint lane (and any environment
without the engine's dependencies) launches it through this shim: a
stub package object with the real ``__path__`` is registered first, so
Python resolves ``hyperspace_tpu.lint.*`` without ever running the
package ``__init__``.
"""

import os
import sys
import types

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    if "hyperspace_tpu" not in sys.modules:
        stub = types.ModuleType("hyperspace_tpu")
        stub.__path__ = [os.path.join(_ROOT, "hyperspace_tpu")]
        sys.modules["hyperspace_tpu"] = stub
    sys.path.insert(0, _ROOT)
    from hyperspace_tpu.lint.__main__ import main as lint_main

    argv = sys.argv[1:]
    if not any(a.startswith("--root") for a in argv):
        argv = ["--root", _ROOT] + argv
    return lint_main(argv)


if __name__ == "__main__":
    sys.exit(main())
