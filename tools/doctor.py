#!/usr/bin/env python
"""Headless health gate: ``python tools/doctor.py --system-path PATH``.

Runs the ``Hyperspace.doctor()`` checks (``--fleet`` adds the cluster
checks over published heartbeats; ``--alerts`` folds persisted SLO
alert states in) and exits ok=0 / warn=1 / crit=2 so cron and CI gate
on health without writing Python.  ``--json`` prints the full report
machine-readably.  See docs/16-observability.md.
"""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    sys.path.insert(0, _ROOT)
    from hyperspace_tpu.telemetry.doctor import main as doctor_main
    return doctor_main(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
