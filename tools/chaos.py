#!/usr/bin/env python
"""Seeded fleet chaos drill launcher.

The engine lives at :mod:`hyperspace_tpu.interop.chaos` (importable from
bench and tests); this shim makes it runnable from a checkout without an
install::

    python tools/chaos.py --seed 7 --duration 8
    python tools/chaos.py --seed 7 --schedule-only   # print the plan

Exit status 0 iff every invariant held (zero lost requests, bit-equal
answers, exactly-once maintenance, consistent client.* accounting).
"""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    sys.path.insert(0, _ROOT)
    from hyperspace_tpu.interop.chaos import main as chaos_main

    return chaos_main(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
