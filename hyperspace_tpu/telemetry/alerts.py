"""The SLO alert engine: continuous evaluation, a persisted alert state
machine, and auto-captured incident bundles.

``doctor()`` grades only when a human calls it; this module is the loop
that calls it first.  A conf-gated evaluator thread
(``hyperspace.alerts.enabled``, default off; riding the fleet-heartbeat
cadence unless ``hyperspace.alerts.intervalS`` overrides it) samples the
metrics registry every tick and evaluates the declared objectives with
the pure multi-window multi-burn-rate math in telemetry/slo.py:

  ================  =========================================================
  ``availability``  ``serve.ok`` good vs ``serve.errors`` +
                    ``serve.shed`` + ``serve.send_timeouts`` bad (an
                    answer that never reached the wire counts against
                    the caller), against
                    ``hyperspace.alerts.availabilityTarget``
                    (burn-rate rules: 5m+1h fast burn pages, 6h+3d slow
                    burn warns — windows/factors conf-tunable).
  ``latency``       the ``serve.latency_ms`` histogram split at
                    ``hyperspace.doctor.latencySloMs``, against
                    ``hyperspace.alerts.latencyTarget`` (same rules).
  ``staleness``     max ACTIVE-index staleness seconds via the lifecycle
                    change detector, thresholded at
                    ``hyperspace.alerts.stalenessWarnS`` (warn).
  ``build_claims``  fresh multi-host build claims whose holder publishes
                    no fresh heartbeat (a dead host fencing work) —
                    any such claim pages.
  ================  =========================================================

Each alert runs the flap-damped pending → firing → resolved state
machine (slo.step_state); every state CHANGE is persisted through the
PR 2 LogStore seam under ``<systemPath>/_hyperspace_alerts`` (both
backends, fault-quiet, never raises — same contract as the lifecycle
journal), so a firing alert survives a process restart and re-resolves
from the restarted engine.  On the transition to firing the engine
captures an INCIDENT BUNDLE — the flight-recorder interesting tail, a
metrics snapshot, the doctor report, the live timeline's trace events,
and the alert's evaluation window — through the PR 9 diagnostics store
(``_hyperspace_diagnostics``), so federated ``trace``/``slow_queries``
resolve the incident's trace ids from any process, after the fact.

Surfacing: ``Hyperspace.alerts()`` / ``alert_history()``, the inline
interop ``alerts`` verb (works during overload), fleet federation (the
heartbeat snapshot carries active alerts; ``alerts(fleet=True)`` merges
them with process attribution and a firing fleet alert grades the
cluster doctor), and a notification seam:
``hyperspace.alerts.notify.command`` runs OFF the evaluation thread
with the transition record as JSON on stdin.

Metrics: ``alerts.evaluations`` / ``alerts.transitions`` /
``alerts.bundles_captured`` / ``alerts.notifications`` counters and the
``alerts.firing`` gauge; spans ``alert.evaluate`` and ``alert.capture``
(docs/16-observability.md).  The serve path itself is never touched —
a disabled engine costs the serving workload nothing (bench ``alerts``
section gates the ENABLED engine < 3% on the serving workload).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from hyperspace_tpu.telemetry import slo

ALERT_DIR = "_hyperspace_alerts"
RECORD_VERSION = 1
# Bound on the in-memory sample ring per objective (at the default 5s
# heartbeat cadence this covers the 3d slow window at ~1/12 resolution;
# shrunken test windows are covered exactly).
MAX_SAMPLES = 4096
# Active (pending/firing) alerts carried per heartbeat snapshot.
FLEET_ALERTS_MAX = 16

_seq_lock = threading.Lock()
_seq = 0


# -- conf accessors -----------------------------------------------------------
def enabled(conf) -> bool:
    return bool(getattr(conf, "alerts_enabled", False))


def interval_s(conf) -> float:
    """Evaluation cadence: ``hyperspace.alerts.intervalS`` when set,
    else the fleet-heartbeat cadence (the engine rides the same clock
    the federation reads on)."""
    explicit = float(getattr(conf, "alerts_interval_s", 0.0))
    if explicit > 0:
        return max(0.05, explicit)
    from hyperspace_tpu.telemetry import fleet

    return fleet.publish_interval_s(conf)


def alert_root(conf) -> str:
    from hyperspace_tpu.index.path_resolver import PathResolver

    return os.path.join(PathResolver(conf).system_path, ALERT_DIR)


def _store(conf):
    from hyperspace_tpu.telemetry.perf_ledger import store_for

    return store_for(conf, alert_root(conf))


def _rules(conf) -> List[slo.BurnRule]:
    return slo.default_rules(
        fast_short_s=float(getattr(conf, "alerts_fast_short_s", 300.0)),
        fast_long_s=float(getattr(conf, "alerts_fast_long_s", 3600.0)),
        fast_factor=float(getattr(conf, "alerts_fast_factor", 14.4)),
        slow_short_s=float(getattr(conf, "alerts_slow_short_s", 21600.0)),
        slow_long_s=float(getattr(conf, "alerts_slow_long_s", 259200.0)),
        slow_factor=float(getattr(conf, "alerts_slow_factor", 1.0)))


def _next_key() -> str:
    global _seq
    with _seq_lock:
        _seq += 1
        seq = _seq
    return f"a-{int(time.time() * 1000):013d}-{os.getpid()}-{seq:05d}"


# -- persistence --------------------------------------------------------------
def append_transition(conf, record: Dict[str, Any]) -> Optional[str]:
    """Persist one state-change record; returns its key, or None on
    failure.  Never raises; runs fault-quiet (the journal contract —
    alert IO must neither fail the engine nor consume an armed fault
    budget aimed at the system under test).  Pruning respects
    ``hyperspace.alerts.maxEntries`` but NEVER drops the latest record
    of any alert — that record IS the restart-proof state."""
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.telemetry import metrics

    try:
        with faults.quiet():
            store = _store(conf)
            rec = {"v": RECORD_VERSION, "ts": time.time(), **record}
            payload = json.dumps(rec, default=str).encode("utf-8")
            key = None
            for _ in range(4):
                key = _next_key()
                if store.put_if_absent(key, payload):
                    break
            else:
                metrics.inc("alerts.errors")
                return None
            cap = int(getattr(conf, "alerts_max_entries", 512))
            if cap > 0:
                keys = sorted(store.list_keys())
                if len(keys) > cap:
                    protected = set(_latest_keys(conf))
                    for old in keys[:len(keys) - cap]:
                        if old not in protected:
                            store.delete(old)
            return key
    except Exception:  # noqa: BLE001 — alert IO never fails the engine
        metrics.inc("alerts.errors")
        return None


def records(conf) -> List[Dict[str, Any]]:
    """Every parseable alert-transition record, oldest first.  Torn or
    unparseable records are skipped — the log is advisory data."""
    from hyperspace_tpu.io import faults

    out: List[Dict[str, Any]] = []
    try:
        with faults.quiet():
            store = _store(conf)
            for key in sorted(store.list_keys()):
                try:
                    rec = json.loads(store.read(key).decode("utf-8"))
                except (FileNotFoundError, ValueError, UnicodeDecodeError):
                    continue
                if not isinstance(rec, dict):
                    continue
                rec["key"] = key
                out.append(rec)
    except Exception:  # noqa: BLE001 — an unreadable log reads empty
        pass
    return out


def _latest_keys(conf) -> List[str]:
    """The newest record key per alert name (pruning protection)."""
    latest: Dict[str, str] = {}
    for rec in records(conf):
        name = str(rec.get("alert", ""))
        if name:
            latest[name] = str(rec.get("key", ""))
    return list(latest.values())


def load_states(conf) -> Dict[str, Dict[str, Any]]:
    """Rebuild the per-alert state map from the persisted log (newest
    record per alert wins) — how a firing alert survives restart."""
    states: Dict[str, Dict[str, Any]] = {}
    for rec in records(conf):
        name = str(rec.get("alert", ""))
        if not name:
            continue
        states[name] = {"state": str(rec.get("state", slo.RESOLVED)),
                        "streak": 0,
                        "since": float(rec.get("since", rec.get("ts", 0.0))
                                       or 0.0),
                        "severity": str(rec.get("severity", "")),
                        "bundle_key": rec.get("bundle_key"),
                        "detail": rec.get("detail") or {}}
    return states


def clear(conf) -> None:
    """Wipe the persisted alert log (tests)."""
    from hyperspace_tpu.io import faults

    with faults.quiet():
        store = _store(conf)
        for key in store.list_keys():
            store.delete(key)


# -- the engine ---------------------------------------------------------------
class AlertEngine:
    """One evaluator per session (``engine_for``); opt-in via
    ``hyperspace.alerts.enabled`` like the lifecycle daemon and the
    fleet publisher.  ``run_once()`` is the synchronous evaluation the
    thread loops on — tests, the bench section, and the chaos drill
    drive it directly."""

    def __init__(self, session) -> None:
        self.session = session
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._samples: Dict[str, List[slo.Sample]] = {}
        self._states: Optional[Dict[str, Dict[str, Any]]] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "AlertEngine":
        from hyperspace_tpu.exceptions import HyperspaceError

        if not enabled(self.session.conf):
            raise HyperspaceError(
                "The SLO alert engine is opt-in: set "
                "hyperspace.alerts.enabled=true (evaluation rides the "
                "fleet-heartbeat cadence unless "
                "hyperspace.alerts.intervalS overrides it)")
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="hs-alert-engine", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.run_once()
            self._stop.wait(interval_s(self.session.conf))

    # -- evaluation ---------------------------------------------------------
    def run_once(self) -> List[Dict[str, Any]]:
        """One evaluation tick: sample, evaluate every objective, step
        the state machines, persist/capture/notify on transitions.
        Returns the transition records written (empty most ticks).
        Never raises; runs fault-quiet like every diagnostics path."""
        from hyperspace_tpu.io import faults
        from hyperspace_tpu.telemetry import metrics
        from hyperspace_tpu.telemetry.trace import span

        conf = self.session.conf
        transitions: List[Dict[str, Any]] = []
        try:
            with faults.quiet(), span("alert.evaluate") as sp:
                now = time.time()
                # Every store/filesystem touch stays OUTSIDE the state
                # lock: warm the lazily-loaded states, then run the
                # IO-bearing probes, THEN step the pure state machines
                # under the lock, and only afterwards commit the
                # resulting transitions (bundle capture + log append)
                # back through the store.
                self.current_states()
                probes = {"staleness": self._probe_staleness(),
                          "build_claims": self._probe_dead_claims(conf)}
                changes: List[Dict[str, Any]] = []
                with self._lock:
                    evaluations = self._evaluate_objectives(conf, now,
                                                            probes)
                    for name, ev in evaluations.items():
                        change = self._step_alert(conf, name, ev, now)
                        if change is not None:
                            changes.append(change)
                    firing = sum(1 for st in self._states.values()
                                 if st.get("state") == slo.FIRING)
                for change in changes:
                    transitions.append(
                        self._commit_transition(conf, change, now))
                metrics.inc("alerts.evaluations")
                metrics.set_gauge("alerts.firing", firing)
                if transitions:
                    metrics.inc("alerts.transitions", len(transitions))
                sp.set(firing=firing, transitions=len(transitions))
        except Exception:  # noqa: BLE001 — evaluation never fails callers
            metrics.inc("alerts.errors")
        for rec in transitions:
            _notify(conf, rec)
        return transitions

    def _evaluate_objectives(self, conf, now: float,
                             probes: Dict[str, Optional[float]],
                             ) -> Dict[str, Dict[str, Any]]:
        from hyperspace_tpu.telemetry import metrics

        typed = metrics.registry().typed_snapshot()
        counters = typed["counters"]
        rules = _rules(conf)
        out: Dict[str, Dict[str, Any]] = {}

        # Bad = errors + sheds + responses we failed to DELIVER
        # (``serve.send_timeouts``): a wire fault that eats the answer
        # after a clean execution is still an unavailable request from
        # the caller's side, and it is the only server-side trace some
        # injected net.send faults leave.
        good = float(counters.get("serve.ok", 0.0))
        bad = (float(counters.get("serve.errors", 0.0))
               + float(counters.get("serve.shed", 0.0))
               + float(counters.get("serve.send_timeouts", 0.0)))
        ring = self._append_sample("availability", now, good, bad)
        out["availability"] = slo.evaluate_objective(
            ring, now, rules,
            float(getattr(conf, "alerts_availability_target", 0.999)))

        slo_ms = float(getattr(conf, "doctor_latency_slo_ms", 1000.0))
        g_lat, b_lat = slo.hist_split(
            typed["histograms"].get("serve.latency_ms"), slo_ms)
        ring = self._append_sample("latency", now, g_lat, b_lat)
        out["latency"] = slo.evaluate_objective(
            ring, now, rules,
            float(getattr(conf, "alerts_latency_target", 0.99)))

        out["staleness"] = slo.threshold_objective(
            probes.get("staleness"),
            float(getattr(conf, "alerts_staleness_warn_s", 600.0)),
            "warn")
        out["build_claims"] = slo.threshold_objective(
            probes.get("build_claims"), 1.0, "page")
        return out

    def _append_sample(self, objective: str, now: float, good: float,
                       bad: float) -> List[slo.Sample]:
        ring = self._samples.setdefault(objective, [])
        ring.append(slo.Sample(now, good, bad))
        if len(ring) > MAX_SAMPLES:
            del ring[:len(ring) - MAX_SAMPLES]
        return ring

    def _probe_staleness(self) -> Optional[float]:
        """Max staleness seconds across ACTIVE indexes (stat-level, the
        doctor's detector); None when the probe cannot run."""
        try:
            from hyperspace_tpu.index.log_entry import States
            from hyperspace_tpu.lifecycle.change_detector import (
                detect_changes,
            )

            manager = self.session.index_collection_manager
            worst = 0.0
            now = time.time()
            for entry in manager.get_indexes():
                if entry.state != States.ACTIVE:
                    continue
                change = detect_changes(self.session, entry)
                if change.changed:
                    age = (max(0.0, now - change.newest_change_ms / 1000.0)
                           if change.newest_change_ms > 0 else 0.0)
                    worst = max(worst, age)
            return worst
        except Exception:  # noqa: BLE001 — a blind probe never pages
            return None

    def _probe_dead_claims(self, conf) -> Optional[float]:
        """Count of FRESH multi-host build claims whose holder publishes
        no fresh heartbeat (the fleet.build_claims crit condition);
        None when ungradeable (no heartbeats to cross-check)."""
        try:
            from hyperspace_tpu.parallel.multihost_build import (
                scan_build_claims,
            )
            from hyperspace_tpu.telemetry import fleet

            claims = scan_build_claims(conf)
            if not claims:
                return 0.0
            fresh = {str(s.get("process", ""))
                     for s in fleet.fresh_snapshots(conf)}
            if not fresh:
                return None
            now = time.time()
            return float(sum(
                1 for rec in claims
                if float(rec.get("expires_at", 0.0)) >= now
                and str(rec.get("holder", "")) not in fresh))
        except Exception:  # noqa: BLE001 — a blind probe never pages
            return None

    def _step_alert(self, conf, name: str, evaluation: Dict[str, Any],
                    now: float) -> Optional[Dict[str, Any]]:
        """Advance one alert's state machine (pure; caller holds the
        state lock).  Returns a change descriptor on a state change —
        the store-touching commit happens in :meth:`_commit_transition`,
        outside the lock."""
        prev = self._states.get(name)
        prev_state = str(prev.get("state", slo.RESOLVED)) if prev \
            else slo.RESOLVED
        new_state, transition = slo.step_state(
            prev, bool(evaluation.get("breached")),
            str(evaluation.get("severity", "")), now,
            pending_evals=int(getattr(conf, "alerts_pending_evals", 2)),
            resolve_evals=int(getattr(conf, "alerts_resolve_evals", 2)))
        new_state["detail"] = evaluation
        if prev is not None and prev.get("bundle_key") \
                and new_state["state"] != slo.RESOLVED:
            new_state["bundle_key"] = prev["bundle_key"]
        self._states[name] = new_state
        if new_state["state"] == prev_state:
            return None
        return {"name": name, "prev_state": prev_state,
                "transition": transition or "",
                "state": new_state["state"],
                "severity": new_state.get("severity", ""),
                "since": new_state.get("since", now),
                "evaluation": evaluation}

    def _commit_transition(self, conf, change: Dict[str, Any],
                           now: float) -> Dict[str, Any]:
        """Persist one state change: capture the incident bundle on a
        transition to firing, then append the transition record — all
        store IO, run after the state lock is released."""
        name = change["name"]
        bundle_key = None
        if change["transition"] == "firing":
            bundle_key = self._capture_incident(conf, name,
                                                change["evaluation"])
            with self._lock:
                st = self._states.get(name)
                if st is not None and st["state"] != slo.RESOLVED:
                    st["bundle_key"] = bundle_key
        rec = {"alert": name, "state": change["state"],
               "prev_state": change["prev_state"],
               "severity": change["severity"],
               "transition": change["transition"],
               "since": change["since"],
               "bundle_key": bundle_key, "detail": change["evaluation"]}
        rec["key"] = append_transition(conf, rec)
        return rec

    def _capture_incident(self, conf, name: str,
                          evaluation: Dict[str, Any]) -> Optional[str]:
        """Freeze the "why" at the moment of the page: the diagnostics
        bundle (flight tail + metrics + perf tail) plus the doctor
        report, the live timeline's trace events, and this alert's
        evaluation window, persisted through the PR 9 diagnostics store
        so federated trace/slow-queries readers resolve it after the
        fact.  Returns the bundle key, or None on failure (a capture
        failure must not lose the transition record)."""
        from hyperspace_tpu.telemetry import (
            flight_recorder,
            metrics,
            timeline,
        )
        from hyperspace_tpu.telemetry.perf_ledger import store_for
        from hyperspace_tpu.telemetry.trace import span

        try:
            with span("alert.capture", alert=name) as sp:
                bundle = flight_recorder.diagnostics_bundle(conf)
                try:
                    from hyperspace_tpu.telemetry.doctor import doctor

                    report = doctor(self.session).to_dict()
                except Exception:  # noqa: BLE001 — a blind doctor is
                    report = None  # still a capturable incident
                rec = timeline.recorder()
                window = {
                    obj: [[s.ts, s.good, s.bad] for s in ring[-256:]]
                    for obj, ring in self._samples.items()}
                bundle["incident"] = {
                    "alert": name,
                    "ts": time.time(),
                    "evaluation": evaluation,
                    "doctor": report,
                    "timeline": timeline.to_trace_events(
                        rec.intervals(), rec.memory_samples(), ()),
                    "window": window,
                }
                store = store_for(conf,
                                  flight_recorder.flight_root(conf))
                payload = json.dumps(bundle,
                                     default=str).encode("utf-8")
                key = None
                for _ in range(4):
                    key = (f"b-{int(time.time() * 1000):013d}-"
                           f"{os.getpid()}-i{_next_seq():05d}")
                    if store.put_if_absent(key, payload):
                        break
                else:
                    return None
                cap = max(1, int(getattr(conf,
                                         "flight_recorder_max_bundles",
                                         8)))
                keys = store.list_keys()
                if len(keys) > cap:
                    for old in sorted(keys)[:len(keys) - cap]:
                        store.delete(old)
                metrics.inc("alerts.bundles_captured")
                sp.set(key=key, bytes=len(payload))
                return key
        except Exception:  # noqa: BLE001 — capture never loses the page
            return None

    # -- reads --------------------------------------------------------------
    def current_states(self) -> Dict[str, Dict[str, Any]]:
        """The per-alert state map (loaded from the persisted log on
        first read, so it answers before the first evaluation too).
        The store read happens outside the state lock; the first loader
        to take the lock wins."""
        with self._lock:
            if self._states is not None:
                return {k: dict(v) for k, v in self._states.items()}
        loaded = load_states(self.session.conf)
        with self._lock:
            if self._states is None:
                self._states = loaded
            return {k: dict(v) for k, v in self._states.items()}

    def active_alerts(self) -> List[Dict[str, Any]]:
        """Pending/firing alerts as compact dicts — what the fleet
        heartbeat snapshot carries."""
        out = []
        for name, st in sorted(self.current_states().items()):
            if st.get("state") in (slo.PENDING, slo.FIRING):
                out.append({"alert": name, "state": st["state"],
                            "severity": st.get("severity", ""),
                            "since": st.get("since", 0.0),
                            "bundle_key": st.get("bundle_key")})
        return out[:FLEET_ALERTS_MAX]


def _next_seq() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def engine_for(session) -> AlertEngine:
    """The session's engine, created lazily (thread starts only via
    :meth:`AlertEngine.start`)."""
    e = getattr(session, "_alert_engine", None)
    if e is None:
        e = AlertEngine(session)
        session._alert_engine = e
    return e


def maybe_start(session) -> Optional[AlertEngine]:
    """Start the engine when the conf gate is on; never raises (an
    alerting failure must not break session construction or server
    start)."""
    try:
        if not enabled(session.conf):
            return None
        return engine_for(session).start()
    except Exception:  # noqa: BLE001 — telemetry never breaks callers
        return None


def carried_alerts(conf) -> List[Dict[str, Any]]:
    """Active (pending/firing) alerts for the fleet heartbeat snapshot,
    rebuilt from the persisted log — conf-only, so the publisher thread
    needs no session.  Empty (and store-free) when the engine is
    disabled.  Never raises."""
    try:
        if not enabled(conf):
            return []
        out = []
        for name, st in sorted(load_states(conf).items()):
            if st.get("state") in (slo.PENDING, slo.FIRING):
                out.append({"alert": name, "state": st["state"],
                            "severity": st.get("severity", ""),
                            "since": st.get("since", 0.0),
                            "bundle_key": st.get("bundle_key")})
        return out[:FLEET_ALERTS_MAX]
    except Exception:  # noqa: BLE001 — telemetry never breaks publishers
        return []


# -- notification seam --------------------------------------------------------
def _notify(conf, record: Dict[str, Any]) -> None:
    """Run ``hyperspace.alerts.notify.command`` with the transition
    record as JSON on stdin, on a dedicated short-lived thread — the
    evaluation thread never blocks on a webhook.  Fires for ``firing``
    and ``resolved`` transitions only.  Never raises."""
    command = str(getattr(conf, "alerts_notify_command", "") or "")
    if not command or record.get("transition") not in ("firing",
                                                       "resolved"):
        return

    def run() -> None:
        import subprocess

        from hyperspace_tpu.telemetry import metrics

        try:
            payload = json.dumps(record, default=str).encode("utf-8")
            env = dict(os.environ)
            env["HYPERSPACE_ALERT"] = str(record.get("alert", ""))
            env["HYPERSPACE_ALERT_STATE"] = str(record.get("state", ""))
            proc = subprocess.Popen(  # noqa: S602 — operator-configured
                command, shell=True, stdin=subprocess.PIPE, env=env)
            proc.communicate(payload, timeout=30.0)
            metrics.inc("alerts.notifications")
        except Exception:  # noqa: BLE001 — a webhook failure never
            metrics.inc("alerts.errors")  # touches the engine

    threading.Thread(target=run, name="hs-alert-notify",
                     daemon=True).start()


# -- tables -------------------------------------------------------------------
def alerts_table(session, fleet: bool = False):
    """Current alert states, one row per alert — the shape
    ``Hyperspace.alerts()`` and the inline interop ``alerts`` verb
    serve.  ``fleet=True`` federates: this process's states plus every
    fresh heartbeat's carried active alerts, with a ``process`` column
    attributing each row."""
    import pyarrow as pa

    rows: List[Dict[str, Any]] = []
    for name, st in sorted(engine_for(session).current_states().items()):
        rows.append({"process": "", "alert": name,
                     "state": str(st.get("state", "")),
                     "severity": str(st.get("severity", "")),
                     "since": float(st.get("since", 0.0) or 0.0),
                     "bundleKey": str(st.get("bundle_key") or ""),
                     "detailJson": json.dumps(st.get("detail") or {},
                                              default=str)})
    if fleet:
        from hyperspace_tpu.telemetry import fleet as _fleet

        own = _fleet.process_identity()
        for row in rows:
            row["process"] = own
        for snap in _fleet.fresh_snapshots(session.conf):
            proc = str(snap.get("process", ""))
            if proc == own:
                continue
            for a in snap.get("alerts") or []:
                if not isinstance(a, dict):
                    continue
                rows.append({
                    "process": proc,
                    "alert": str(a.get("alert", "")),
                    "state": str(a.get("state", "")),
                    "severity": str(a.get("severity", "")),
                    "since": float(a.get("since", 0.0) or 0.0),
                    "bundleKey": str(a.get("bundle_key") or ""),
                    "detailJson": json.dumps({}),
                })
    return pa.table({
        "process": pa.array([r["process"] for r in rows],
                            type=pa.string()),
        "alert": pa.array([r["alert"] for r in rows], type=pa.string()),
        "state": pa.array([r["state"] for r in rows], type=pa.string()),
        "severity": pa.array([r["severity"] for r in rows],
                             type=pa.string()),
        "since": pa.array([r["since"] for r in rows],
                          type=pa.float64()),
        "bundleKey": pa.array([r["bundleKey"] for r in rows],
                              type=pa.string()),
        "detailJson": pa.array([r["detailJson"] for r in rows],
                               type=pa.string()),
    })


def history_table(conf):
    """The persisted transition log as an arrow table, oldest first —
    the shape ``Hyperspace.alert_history()`` returns."""
    import pyarrow as pa

    recs = records(conf)
    return pa.table({
        "key": pa.array([str(r.get("key", "")) for r in recs],
                        type=pa.string()),
        "ts": pa.array([float(r.get("ts", 0.0) or 0.0) for r in recs],
                       type=pa.float64()),
        "alert": pa.array([str(r.get("alert", "")) for r in recs],
                          type=pa.string()),
        "state": pa.array([str(r.get("state", "")) for r in recs],
                          type=pa.string()),
        "prevState": pa.array([str(r.get("prev_state", ""))
                               for r in recs], type=pa.string()),
        "severity": pa.array([str(r.get("severity", "")) for r in recs],
                             type=pa.string()),
        "transition": pa.array([str(r.get("transition", ""))
                                for r in recs], type=pa.string()),
        "bundleKey": pa.array([str(r.get("bundle_key") or "")
                               for r in recs], type=pa.string()),
        "recordJson": pa.array([json.dumps(r, default=str)
                                for r in recs], type=pa.string()),
    })


def fleet_alert_check(session):
    """The cluster-doctor check (``doctor(fleet=True)``): a FIRING alert
    anywhere in the fleet — this process or any fresh heartbeat — is
    the page the engine already decided to send, so it grades the
    cluster ``crit`` (page severity) or ``warn``."""
    from hyperspace_tpu.telemetry import fleet as _fleet
    from hyperspace_tpu.telemetry.doctor import DoctorCheck

    firing: List[Dict[str, Any]] = []
    if enabled(session.conf):
        for a in engine_for(session).active_alerts():
            if a.get("state") == slo.FIRING:
                firing.append({**a,
                               "process": _fleet.process_identity()})
    own = _fleet.process_identity()
    for snap in _fleet.fresh_snapshots(session.conf):
        proc = str(snap.get("process", ""))
        if proc == own:
            continue
        for a in snap.get("alerts") or []:
            if isinstance(a, dict) and a.get("state") == slo.FIRING:
                firing.append({**a, "process": proc})
    if not firing:
        return DoctorCheck("fleet.alerts", "ok",
                           "no firing SLO alerts across the fleet", {})
    pages = [a for a in firing if a.get("severity") == "page"]
    status = "crit" if pages else "warn"
    names = sorted({f"{a.get('alert')}@{a.get('process', '')[:24]}"
                    for a in firing})
    return DoctorCheck(
        "fleet.alerts", status,
        f"{len(firing)} firing SLO alert(s) across the fleet: "
        f"{', '.join(names[:4])} — incident bundles are in "
        f"diagnostics_bundles()", {"firing": firing})
