"""Intra-phase pipeline timeline: who was busy WHEN, on which lane.

PR 6's BuildReport proved WHERE build time goes (sf10: spill_route +
spill_finish dwarf read, BENCH_r04) — but summed phase seconds cannot
show WHY: whether the read lane sits idle while spill runs, how the
device kernel overlaps host IO, where memory peaks inside a phase.  This
module records *intervals* — ``(lane, kind, start_ns, end_ns)`` — for
every BuildReport phase (actions + spill worker threads report through
``BuildReport.add_phase``), every executor operator dispatch, and every
block_until_ready-timed device kernel, into one process-global bounded
ring, plus a background memory sampler (host RSS + jax live
device-buffer bytes at a conf cadence) whose samples intersect with the
phase intervals to yield per-phase high-water marks instead of PR 6's
end-of-action peak.

On top of the raw intervals:

  - **gap/overlap analysis** (:func:`busy_report`): fraction of the wall
    window each lane is busy, plus the pairwise "X idle while Y busy"
    matrix — the number ROADMAP item 2's prefetch rewrite must move
    (today's serialization claim becomes ``read idle-while
    spill_route busy = 0.9``, and the rewrite is accepted when it
    drops).
  - **device/kernel attribution** (:func:`kernel_begin` /
    :func:`kernel_end`): dispatch seams in ``execution/executor.py`` and
    ``ops/`` time the jitted program to ``jax.block_until_ready`` and
    emit ``exec.kernel.<name>.device_ms`` histograms plus per-device
    ``exec.device.<id>.kernel_ms`` counters — keyed by jax device id, so
    the output is already multichip-shaped for ROADMAP item 1.
    Host↔device traffic lands in ``exec.transfer.h2d.bytes`` /
    ``.d2h.bytes`` (:func:`record_transfer`).
  - **Perfetto/Chrome trace-event export** (:func:`export_chrome_trace`):
    intervals, memory counter tracks, and span trees render into
    trace-event JSON loadable in ui.perfetto.dev — also reconstructable
    from a flight-recorder retained record (:func:`spans_to_trace_events`)
    or a perf-ledger entry (:func:`ledger_to_trace_events`), so "what did
    yesterday's slow build look like" survives the process.

Cost contract, same shape as tracing (telemetry/trace.py): OFF by
default (``hyperspace.system.timeline.enabled``); the disabled path is
one module-global bool check — no allocation, no clock read, and the
kernel seams do NOT call ``block_until_ready`` (forcing a device sync
the async dispatcher would otherwise hide is exactly the cost the gate
exists to avoid).  Enabled, instrumentation stays at phase/operator/
kernel granularity — never per row — and the ring is bounded
(``hyperspace.system.timeline.maxIntervals``, oldest dropped and
counted).  The bench ``timeline`` section gates recorder + sampler
overhead < 3% on the build_profile workload.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

_enabled = False  # module-global: the whole disabled-path cost is this bool

_DEFAULT_MAX_INTERVALS = 8192
_DEFAULT_MAX_SAMPLES = 4096


def timeline_enabled() -> bool:
    return _enabled


def enable_timeline() -> None:
    global _enabled
    _enabled = True


def disable_timeline() -> None:
    global _enabled
    _enabled = False


def configure_from_conf(conf) -> None:
    """Apply the timeline conf keys (called per action run and per
    collect, like the trace/fault-injector conf hooks): enables the
    recorder when ``hyperspace.system.timeline.enabled`` is set and
    applies the ring bound.  Conf never force-disables —
    ``disable_timeline()`` is the explicit opt-out."""
    if getattr(conf, "timeline_enabled", False):
        enable_timeline()
    try:
        _RECORDER.set_capacity(int(getattr(
            conf, "timeline_max_intervals", _DEFAULT_MAX_INTERVALS)))
    except (TypeError, ValueError):
        pass


class TimelineRecorder:
    """Lock-safe bounded ring of intervals + memory samples.

    An interval is ``(lane, kind, start_ns, end_ns)`` (monotonic
    nanoseconds); a memory sample is ``(ts_ns, rss_mb, device_bytes)``.
    Bounded: past capacity the OLDEST entries drop and
    ``timeline.dropped`` counts them — the ring is a diagnosis window,
    not an archive."""

    def __init__(self, capacity: int = _DEFAULT_MAX_INTERVALS) -> None:
        self._lock = threading.Lock()
        self._capacity = capacity
        self._intervals: List[Tuple[str, str, int, int]] = []
        self._samples: List[Tuple[int, float, int]] = []

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._capacity = max(1, int(capacity))

    def record(self, lane: str, kind: str, start_ns: int,
               end_ns: int) -> None:
        from hyperspace_tpu.telemetry import metrics

        dropped = 0
        with self._lock:
            self._intervals.append((lane, kind, int(start_ns),
                                    int(end_ns)))
            while len(self._intervals) > self._capacity:
                del self._intervals[0]
                dropped += 1
            size = len(self._intervals)
        metrics.set_gauge("timeline.ring_size", size)
        if dropped:
            metrics.inc("timeline.dropped", dropped)

    def add_memory_sample(self, ts_ns: int, rss_mb: float,
                          device_bytes: int) -> None:
        with self._lock:
            self._samples.append((int(ts_ns), float(rss_mb),
                                  int(device_bytes)))
            while len(self._samples) > _DEFAULT_MAX_SAMPLES:
                del self._samples[0]

    def intervals(self, lane: Optional[str] = None
                  ) -> List[Tuple[str, str, int, int]]:
        with self._lock:
            out = list(self._intervals)
        return out if lane is None else [iv for iv in out if iv[0] == lane]

    def memory_samples(self) -> List[Tuple[int, float, int]]:
        with self._lock:
            return list(self._samples)

    def reset(self) -> None:
        with self._lock:
            self._intervals.clear()
            self._samples.clear()


# One recorder per process, like the metrics registry: the build/executor
# lanes it observes are process-level resources.
_RECORDER = TimelineRecorder()


def recorder() -> TimelineRecorder:
    return _RECORDER


def reset() -> None:
    _RECORDER.reset()


def record_interval(lane: str, kind: str, start_ns: int,
                    end_ns: int) -> None:
    """Record one finished interval into the process ring (no-op when
    the timeline is disabled — one bool check)."""
    if not _enabled:
        return
    _RECORDER.record(lane, kind, start_ns, end_ns)


def op_begin() -> Optional[int]:
    """Start timestamp for an operator/kernel interval, or None when the
    timeline is disabled (callers pass it straight to the matching end
    helper — the disabled path never reads a clock)."""
    return time.monotonic_ns() if _enabled else None


def op_end(lane: str, kind: str, t0_ns: Optional[int]) -> None:
    if t0_ns is None:
        return
    _RECORDER.record(lane, kind, t0_ns, time.monotonic_ns())


# ---------------------------------------------------------------------------
# Device/kernel attribution (the block_until_ready-timed dispatch seams)
# ---------------------------------------------------------------------------
def kernel_begin() -> Optional[int]:
    """Alias of :func:`op_begin` for the device-kernel seams, so call
    sites read as begin/end pairs around the dispatch."""
    return time.monotonic_ns() if _enabled else None


def _device_id_of(out: Any) -> int:
    """The jax device id of (the first leaf of) ``out``, or -1 when it
    has none (host-mirror outputs)."""
    try:
        import jax

        for leaf in jax.tree_util.tree_leaves(out):
            devices = getattr(leaf, "devices", None)
            if devices is None:
                continue
            ids = sorted(getattr(d, "id", -1) for d in devices())
            if ids:
                return int(ids[0])
    except Exception:  # noqa: BLE001 — attribution must never fail the op
        pass
    return -1


def kernel_end(name: str, t0_ns: Optional[int], out: Any = None,
               devices: Optional[Sequence] = None) -> None:
    """Close one device-kernel dispatch: block until ``out`` (a jax array
    or pytree of them) is ready, then attribute the elapsed time —
    ``exec.kernel.<name>.device_ms`` histogram, per-device
    ``exec.device.<id>.kernel_ms`` counter, a ``device:<id>`` timeline
    lane interval, and a ``kernel`` decision on the active run report
    (the flight recorder's device-bound/queue-bound discriminator).
    ``devices`` names the mesh an SPMD program ran over: the program
    occupies EVERY mesh device for its duration, so the elapsed ms is
    attributed to each (one counter bump and one lane interval per
    device — the per-device skew view the multichip bench reads).
    No-op (and no sync!) when the timeline is disabled."""
    if t0_ns is None:
        return
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry import report as run_report

    try:
        if out is not None:
            import jax

            jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 — a failed sync is the caller's
        pass           # problem at ITS use site, not attribution's
    end_ns = time.monotonic_ns()
    ms = (end_ns - t0_ns) / 1e6
    metrics.observe(f"exec.kernel.{name}.device_ms", ms)
    if devices:
        ids = sorted(int(getattr(d, "id", -1)) for d in devices)
        for dev in ids:
            metrics.inc(f"exec.device.{dev}.kernel_ms", ms)
            _RECORDER.record(f"device:{dev}", f"kernel.{name}",
                             t0_ns, end_ns)
        run_report.record("kernel", name=name, device_ms=round(ms, 3),
                          device=ids[0], devices=ids)
        return
    dev = _device_id_of(out)
    metrics.inc(f"exec.device.{dev}.kernel_ms", ms)
    _RECORDER.record(f"device:{dev}", f"kernel.{name}", t0_ns, end_ns)
    run_report.record("kernel", name=name, device_ms=round(ms, 3),
                      device=dev)


def record_transfer(direction: str, nbytes: int) -> None:
    """Count one host↔device transfer (``direction`` is ``h2d`` or
    ``d2h``).  Disabled path: one bool check."""
    if not _enabled or nbytes <= 0:
        return
    from hyperspace_tpu.telemetry import metrics

    metrics.inc(f"exec.transfer.{direction}.bytes", int(nbytes))


def device_ms_summary(report) -> float:
    """Total attributed device-kernel milliseconds of one run report —
    what the flight recorder stamps on a record so ``slow_queries()``
    can tell a device-bound tail from a queue-bound one."""
    try:
        return round(sum(float(d.get("device_ms", 0.0))
                         for d in report.decisions
                         if d.get("kind") == "kernel"), 3)
    except Exception:  # noqa: BLE001 — a foreign report shape reads 0
        return 0.0


# ---------------------------------------------------------------------------
# Background memory sampler
# ---------------------------------------------------------------------------
def _rss_mb() -> float:
    """CURRENT host RSS in MB (``/proc/self/statm`` — getrusage reports
    the historical peak, useless for per-phase high-water marks)."""
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as f:
            pages = int(f.read().split()[1])
        import resource

        return pages * resource.getpagesize() / (1024.0 * 1024.0)
    except Exception:  # noqa: BLE001 — non-Linux: no current-RSS source
        return 0.0


def _device_live_bytes() -> int:
    """Live jax device-buffer bytes — only when jax is ALREADY loaded
    (the sampler must never force the import for a metadata action)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        return int(sum(int(getattr(a, "nbytes", 0))
                       for a in jax.live_arrays()))
    except Exception:  # noqa: BLE001 — backend without live_arrays
        return 0


class MemorySampler:
    """Daemon thread sampling (host RSS, device live bytes) every
    ``cadence_ms`` into a sink (a :class:`BuildReport` exposing
    ``add_memory_sample``) AND the process ring.  Bounded lifetime:
    stops itself after ``max_s`` even if the owner leaked it (an
    injected crash skips the owner's finally)."""

    def __init__(self, cadence_ms: float, sink=None,
                 max_s: float = 3600.0) -> None:
        self.cadence_s = max(0.001, float(cadence_ms) / 1000.0)
        self.sink = sink
        self.max_s = max_s
        self.samples = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="hs-memory-sampler", daemon=True)

    def start(self) -> "MemorySampler":
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 2.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout_s)

    def _run(self) -> None:
        from hyperspace_tpu.telemetry import metrics

        deadline = time.monotonic() + self.max_s
        while not self._stop.wait(self.cadence_s):
            if time.monotonic() > deadline:
                return
            ts = time.monotonic_ns()
            rss = _rss_mb()
            dev = _device_live_bytes()
            self.samples += 1
            metrics.inc("timeline.memory.samples")
            _RECORDER.add_memory_sample(ts, rss, dev)
            sink = self.sink
            if sink is not None:
                try:
                    sink.add_memory_sample(ts, rss, dev)
                except Exception:  # noqa: BLE001 — diagnostics never
                    pass           # fail the sampled work


def start_sampler(conf, sink=None) -> Optional[MemorySampler]:
    """Start a sampler when the timeline is enabled and the cadence conf
    is positive; None otherwise (callers hold the returned handle and
    ``stop()`` it in a finally)."""
    if not _enabled:
        return None
    try:
        cadence = float(getattr(conf, "timeline_memory_sample_ms", 25.0))
    except (TypeError, ValueError):
        cadence = 25.0
    if cadence <= 0:
        return None
    return MemorySampler(cadence, sink).start()


# ---------------------------------------------------------------------------
# Gap/overlap analysis
# ---------------------------------------------------------------------------
def _merge(spans: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Union of possibly-overlapping (start, end) spans."""
    out: List[Tuple[int, int]] = []
    for s, e in sorted(spans):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _measure(spans: List[Tuple[int, int]], lo: int, hi: int) -> int:
    return sum(min(e, hi) - max(s, lo) for s, e in spans
               if min(e, hi) > max(s, lo))


def _subtract(a: List[Tuple[int, int]], b: List[Tuple[int, int]]
              ) -> List[Tuple[int, int]]:
    """Merged spans of ``a`` minus merged spans of ``b``."""
    out: List[Tuple[int, int]] = []
    j = 0
    for s, e in a:
        cur = s
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < e:
            bs, be = b[k]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= e:
                break
            k += 1
        if cur < e:
            out.append((cur, e))
    return out


def busy_report(intervals: Iterable[Sequence],
                lanes: Optional[Sequence[str]] = None) -> Dict[str, Any]:
    """Gap/overlap analysis over ``intervals`` (items shaped
    ``(lane, kind, start_ns, end_ns)`` or ``(lane, start_ns, end_ns)``).

    Returns::

        {"window_s": wall seconds spanned,
         "lanes": {lane: {"busy_s": ..., "busy_fraction": ...}},
         "idle_while_busy": {x: {y: fraction of the wall window where
                                 lane x is IDLE while lane y is BUSY}}}

    ``idle_while_busy["read"]["spill_route"]`` near 1.0 is ROADMAP item
    2's serialization claim as a measured number — the figure the
    double-buffered prefetch rewrite must drive toward 0."""
    by_lane: Dict[str, List[Tuple[int, int]]] = {}
    for item in intervals:
        if len(item) == 4:
            lane, _kind, s, e = item
        else:
            lane, s, e = item
        if lanes is not None and lane not in lanes:
            continue
        by_lane.setdefault(str(lane), []).append((int(s), int(e)))
    merged = {lane: _merge(spans) for lane, spans in by_lane.items()}
    merged = {lane: spans for lane, spans in merged.items() if spans}
    if not merged:
        return {"window_s": 0.0, "lanes": {}, "idle_while_busy": {}}
    lo = min(s[0][0] for s in merged.values())
    hi = max(s[-1][1] for s in merged.values())
    window = max(1, hi - lo)
    lane_stats = {}
    for lane, spans in sorted(merged.items()):
        busy = _measure(spans, lo, hi)
        lane_stats[lane] = {"busy_s": round(busy / 1e9, 4),
                            "busy_fraction": round(busy / window, 4)}
    matrix: Dict[str, Dict[str, float]] = {}
    for x, x_spans in sorted(merged.items()):
        row: Dict[str, float] = {}
        for y, y_spans in sorted(merged.items()):
            if x == y:
                continue
            # y busy while x idle = measure(y \ x) over the wall window.
            row[y] = round(
                _measure(_subtract(y_spans, x_spans), lo, hi) / window, 4)
        matrix[x] = row
    return {"window_s": round(window / 1e9, 4), "lanes": lane_stats,
            "idle_while_busy": matrix}


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace-event export
# ---------------------------------------------------------------------------
def _lane_tids(lanes: Iterable[str]) -> Dict[str, int]:
    return {lane: i + 1 for i, lane in enumerate(sorted(set(lanes)))}


def to_trace_events(intervals: Iterable[Sequence] = (),
                    memory_samples: Iterable[Sequence] = (),
                    span_roots: Iterable = (),
                    pid: int = 1) -> List[Dict[str, Any]]:
    """Render intervals + memory samples + span trees as Chrome
    trace-event dicts (``ph: X`` complete events on one tid per lane,
    ``ph: C`` counter tracks for memory, ``ph: M`` thread-name
    metadata) — the list ``{"traceEvents": [...]}`` wraps."""
    events: List[Dict[str, Any]] = []
    ivs = [tuple(i) for i in intervals]
    tids = _lane_tids(i[0] for i in ivs)
    for lane, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": lane}})
    for lane, kind, s, e in ivs:
        events.append({"name": kind, "cat": "timeline", "ph": "X",
                       "ts": s / 1000.0, "dur": max(0.0, (e - s) / 1000.0),
                       "pid": pid, "tid": tids[lane],
                       "args": {"lane": lane}})
    for ts, rss_mb, dev_bytes in memory_samples:
        events.append({"name": "memory", "cat": "memory", "ph": "C",
                       "ts": ts / 1000.0, "pid": pid,
                       "args": {"host_rss_mb": round(float(rss_mb), 1),
                                "device_live_mb": round(
                                    int(dev_bytes) / (1024.0 * 1024.0),
                                    3)}})
    base_us = 0.0
    if ivs:
        base_us = min(i[2] for i in ivs) / 1000.0
    for root in span_roots:
        events.extend(spans_to_trace_events(root, base_ts_us=base_us,
                                            pid=pid, tid=0))
    return events


def spans_to_trace_events(root, base_ts_us: float = 0.0, pid: int = 1,
                          tid: int = 0) -> List[Dict[str, Any]]:
    """One span tree (a live :class:`~hyperspace_tpu.telemetry.trace.Span`
    or its ``to_dict`` form — the shape a flight-recorder retained record
    carries) as nested ``ph: X`` events.  Serialized spans keep only
    durations, so children are laid out sequentially inside their
    parent — a faithful reconstruction of the tree's shape, not of its
    real concurrency."""
    if root is None:
        return []
    node = root.to_dict() if hasattr(root, "to_dict") else dict(root)
    events: List[Dict[str, Any]] = []

    def emit(span: Dict[str, Any], start_us: float) -> float:
        dur_us = max(0.0, float(span.get("duration_ms", 0.0)) * 1000.0)
        args = dict(span.get("tags") or {})
        if span.get("status") not in (None, "ok"):
            args["status"] = span.get("status")
            if span.get("error"):
                args["error"] = span["error"]
        events.append({"name": str(span.get("name", "span")),
                       "cat": "span", "ph": "X", "ts": start_us,
                       "dur": dur_us, "pid": pid, "tid": tid,
                       "args": args})
        child_us = start_us
        for child in span.get("children", ()) or ():
            if isinstance(child, dict):
                child_us += emit(child, child_us)
        return dur_us

    emit(node, base_ts_us)
    return events


def ledger_to_trace_events(record: Dict[str, Any], pid: int = 1
                           ) -> List[Dict[str, Any]]:
    """Reconstruct a timeline from one perf-ledger record: its
    ``phases_s`` laid out sequentially (summed phase seconds carry no
    interleaving — the reconstruction shows magnitude, the live ring
    shows overlap)."""
    phases = record.get("phases_s") or {}
    events: List[Dict[str, Any]] = [
        {"ph": "M", "pid": pid, "tid": 1, "name": "thread_name",
         "args": {"name": str(record.get("name", "ledger"))}}]
    cursor = 0.0
    for name, seconds in sorted(phases.items(),
                                key=lambda kv: -float(kv[1])):
        dur_us = max(0.0, float(seconds) * 1e6)
        events.append({"name": f"phase.{name}", "cat": "ledger",
                       "ph": "X", "ts": cursor, "dur": dur_us,
                       "pid": pid, "tid": 1,
                       "args": {"seconds": round(float(seconds), 4)}})
        cursor += dur_us
    return events


def export_chrome_trace(path: str,
                        intervals: Optional[Iterable[Sequence]] = None,
                        memory_samples: Optional[Iterable[Sequence]]
                        = None,
                        span_roots: Iterable = ()) -> int:
    """Write a ``{"traceEvents": [...]}`` JSON file loadable in
    ui.perfetto.dev / chrome://tracing; defaults to the process ring.
    Returns the number of events written."""
    from hyperspace_tpu.telemetry.trace import span

    with span("timeline.export", path=path) as sp:
        if intervals is None:
            intervals = _RECORDER.intervals()
        if memory_samples is None:
            memory_samples = _RECORDER.memory_samples()
        events = to_trace_events(intervals, memory_samples, span_roots)
        payload = {"traceEvents": events, "displayTimeUnit": "ms"}
        # hslint: allow[io-seam] user-chosen export path, not index data
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, default=str)
        sp.set(events=len(events))
        return len(events)


@contextlib.contextmanager
def lane(lane_name: str, kind: str):
    """Context manager recording the with-block as one interval on
    ``lane_name`` (enabled-checked once at entry)."""
    t0 = op_begin()
    try:
        yield
    finally:
        op_end(lane_name, kind, t0)
