"""``Hyperspace.doctor()``: one aggregated ok/warn/crit health report.

Six PRs of telemetry (quarantine records, the lifecycle change detector,
daemon backoffs, the perf ledger, the serving counters, degraded-event
metrics) each answer their own question; an operator paged at 3am needs
ONE.  The doctor runs every check, grades each ``ok`` / ``warn`` /
``crit``, and reports the worst as the overall status — also published
as the ``health.status`` gauge (0/1/2) so a scrape alert fires without
parsing anything.

Checks (each never raises — a check that cannot run reports itself
``warn`` with the error, because "the doctor is blind here" is itself a
finding):

  ================  =========================================================
  ``integrity``     per-index quarantine records (index/quarantine.py):
                    any quarantined file is ``crit`` — queries still
                    answer (containment), but data is damaged and a
                    ``refresh_index(mode="repair")`` is pending.  A
                    degraded index LISTING is ``crit`` too.
  ``staleness``     per-ACTIVE-index stat-level change detection
                    (lifecycle/change_detector.py): source drifted from
                    the recorded set → ``warn`` with the per-index
                    appended/deleted/mutated counts and staleness
                    seconds.
  ``maintenance``   lifecycle-daemon failure backoffs in force → ``warn``
                    (an index the daemon cannot maintain is quietly
                    going stale).
  ``perf``          perf-ledger trend: for each action name with enough
                    history, the latest ``wall_s`` against the median of
                    its predecessors, judged by the bench_compare
                    direction rules — a ≥ 25% AND ≥ 0.5 s regression is
                    ``warn``.
  ``serving``       shed rate (``serve.shed`` / ``serve.requests``)
                    above ``hyperspace.doctor.shedWarnRatio`` → ``warn``
                    (``crit`` past 5× the ratio); latency SLO burn — the
                    fraction of ``serve.latency_ms`` observations above
                    ``hyperspace.doctor.latencySloMs`` — past 10% →
                    ``warn``, past 50% → ``crit``.
  ``degraded``      ``degraded.fallbacks`` / ``quarantine.files``
                    counters nonzero this process → ``warn``.
  ``lint``          lint freshness (docs/18): a NON-EMPTY checked-in
                    ``.hslint-baseline.json`` (grandfathered findings
                    nobody burned down) or a baseline written against an
                    older rule-catalog version than the installed
                    ``lint.rules.CATALOG_VERSION`` (its fingerprints may
                    hide what the new rules would raise) → ``warn``;
                    also publishes the ``lint.baseline.entries`` gauge.
  ================  =========================================================

The report is cheap (stat-level listings, process counters, one ledger
read — no data reads, no query execution), which is why the interop
``doctor`` verb answers INLINE like ``metrics``: it keeps working while
the admission queue is shedding, exactly when an operator needs it.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Any, Dict, List, Optional

SEVERITY = {"ok": 0, "warn": 1, "crit": 2}
_STATUS = {v: k for k, v in SEVERITY.items()}


@dataclasses.dataclass
class DoctorCheck:
    name: str
    status: str            # "ok" | "warn" | "crit"
    summary: str
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "status": self.status,
                "summary": self.summary, "data": dict(self.data)}


class DoctorReport:
    def __init__(self, checks: List[DoctorCheck]) -> None:
        self.ts = time.time()
        self.checks = checks

    @property
    def status(self) -> str:
        worst = max((SEVERITY[c.status] for c in self.checks), default=0)
        return _STATUS[worst]

    def check(self, name: str) -> Optional[DoctorCheck]:
        for c in self.checks:
            if c.name == name:
                return c
        return None

    def to_dict(self) -> Dict[str, Any]:
        return {"ts": self.ts, "status": self.status,
                "checks": [c.to_dict() for c in self.checks]}

    def render(self) -> str:
        lines = [f"Doctor: {self.status.upper()}"]
        for c in self.checks:
            lines.append(f"  [{c.status:<4}] {c.name:<12} {c.summary}")
        return "\n".join(lines)

    def table(self):
        """Arrow shape the interop ``doctor`` verb serves: one row per
        check plus the ``overall`` row."""
        import json

        import pyarrow as pa

        names = ["overall"] + [c.name for c in self.checks]
        statuses = [self.status] + [c.status for c in self.checks]
        summaries = [f"{len(self.checks)} checks"] \
            + [c.summary for c in self.checks]
        data = [json.dumps({})] + [json.dumps(c.data, default=str)
                                   for c in self.checks]
        return pa.table({
            "check": pa.array(names, type=pa.string()),
            "status": pa.array(statuses, type=pa.string()),
            "summary": pa.array(summaries, type=pa.string()),
            "dataJson": pa.array(data, type=pa.string()),
        })


def _guarded(name: str, fn) -> DoctorCheck:
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — a blind check is a finding,
        return DoctorCheck(  # never a crash
            name, "warn", f"check failed: {type(e).__name__}: {e}")


def doctor(session, fleet: bool = False) -> DoctorReport:
    """Run every health check against ``session``'s index tree and this
    process's telemetry; publish ``health.status``.  ``fleet=True``
    additionally runs the CLUSTER checks over the published heartbeats
    (telemetry/fleet.py: stale processes, duplicate lifecycle daemons,
    aggregate shed/SLO burn, kernel-ms skew) and publishes their worst
    grade as the ``health.fleet.status`` gauge."""
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.trace import span

    with span("doctor.run") as sp:
        checks = [
            _guarded("integrity", lambda: _check_integrity(session)),
            _guarded("staleness", lambda: _check_staleness(session)),
            _guarded("cdc.merge_debt",
                     lambda: _check_merge_debt(session)),
            _guarded("maintenance", lambda: _check_maintenance(session)),
            _guarded("perf", lambda: _check_perf(session)),
            _guarded("serving", lambda: _check_serving(session)),
            _guarded("client", lambda: _check_client(session)),
            _guarded("degraded", lambda: _check_degraded(session)),
            _guarded("lint", lambda: _check_lint(session)),
            _guarded("device_skew",
                     lambda: _check_device_skew(session)),
        ]
        # health.status keeps grading the LOCAL process regardless of
        # the fleet flag — a fleet-wide crit must not mask (or fake)
        # this process's own state on the single-process gauge.
        local = DoctorReport(checks)
        metrics.inc("doctor.runs")
        metrics.set_gauge("health.status", SEVERITY[local.status])
        if fleet:
            from hyperspace_tpu.telemetry import fleet as _fleet

            fleet_part = _fleet.fleet_checks(session)
            worst = max((SEVERITY[c.status] for c in fleet_part),
                        default=0)
            metrics.set_gauge("health.fleet.status", worst)
            checks = checks + fleet_part
        report = DoctorReport(checks)
        sp.set(status=report.status, checks=len(checks))
        return report


# ---------------------------------------------------------------------------
# Checks
# ---------------------------------------------------------------------------
def _check_integrity(session) -> DoctorCheck:
    manager = session.index_collection_manager
    entries = manager.get_indexes()
    quarantined: Dict[str, int] = {}
    for entry in entries:
        count = len(manager.quarantine_manager(entry.name).records())
        if count:
            quarantined[entry.name] = count
    if getattr(manager, "last_listing_degraded", False):
        return DoctorCheck(
            "integrity", "crit",
            "index listing degraded: at least one index log is unreadable",
            {"indexes": len(entries)})
    if quarantined:
        total = sum(quarantined.values())
        return DoctorCheck(
            "integrity", "crit",
            f"{total} quarantined file(s) across "
            f"{len(quarantined)} index(es) — queries answer via "
            f"containment; run refresh_index(mode=\"repair\")",
            {"quarantined": quarantined})
    return DoctorCheck("integrity", "ok",
                       f"{len(entries)} index(es), no quarantine records",
                       {"indexes": len(entries)})


def _check_staleness(session) -> DoctorCheck:
    from hyperspace_tpu.index.log_entry import States
    from hyperspace_tpu.lifecycle.change_detector import detect_changes

    manager = session.index_collection_manager
    entries = [e for e in manager.get_indexes()
               if e.state == States.ACTIVE]
    stale: Dict[str, Dict[str, Any]] = {}
    now = time.time()
    for entry in entries:
        try:
            change = detect_changes(session, entry)
        except Exception as e:  # noqa: BLE001 — an unlistable source is
            stale[entry.name] = {"error": str(e)}  # itself staleness risk
            continue
        if change.changed:
            staleness_s = (max(0.0, now - change.newest_change_ms / 1000.0)
                           if change.newest_change_ms > 0 else 0.0)
            stale[entry.name] = {"appended": change.appended,
                                 "deleted": change.deleted,
                                 "mutated": change.mutated,
                                 "staleness_s": round(staleness_s, 1)}
    if stale:
        return DoctorCheck(
            "staleness", "warn",
            f"{len(stale)}/{len(entries)} ACTIVE index(es) behind their "
            f"source — refresh (or enable the lifecycle daemon)",
            {"stale": stale})
    return DoctorCheck("staleness", "ok",
                       f"{len(entries)} ACTIVE index(es) current",
                       {"indexes": len(entries)})


def _check_merge_debt(session) -> DoctorCheck:
    """CDC merge-on-read debt (lifecycle/cdc.py): WARN when an index's
    pending overlay outgrew the ``hyperspace.lifecycle.cdc.
    mergeDebtRatio`` budget (a refresh is overdue), CRIT when an index
    carries a delete overlay it cannot apply at scan time — no lineage
    column, or hybrid scan disabled — because hybrid candidate math
    drops such an entry and every query over it silently falls back to
    a full source scan."""
    from hyperspace_tpu.index.log_entry import States
    from hyperspace_tpu.lifecycle.cdc import merge_debt

    conf = session.conf
    budget = float(getattr(conf, "lifecycle_cdc_merge_debt_ratio", 0.2))
    hybrid_on = bool(getattr(conf, "hybrid_scan_enabled", False))
    entries = [e for e in session.index_collection_manager.get_indexes()
               if e.state == States.ACTIVE]
    unreadable: Dict[str, Dict[str, Any]] = {}
    over: Dict[str, Dict[str, Any]] = {}
    for entry in entries:
        debt = merge_debt(entry)
        if debt.total_bytes == 0:
            continue
        if debt.deleted_files > 0 and (not debt.readable or not hybrid_on):
            unreadable[entry.name] = debt.to_dict()
        elif debt.ratio > budget:
            over[entry.name] = debt.to_dict()
    if unreadable:
        return DoctorCheck(
            "cdc.merge_debt", "crit",
            f"{len(unreadable)} index(es) carry a delete overlay they "
            f"cannot apply at scan time — queries fall back to source; "
            f"run refresh_index(mode=\"incremental\")",
            {"unreadable": unreadable})
    if over:
        return DoctorCheck(
            "cdc.merge_debt", "warn",
            f"{len(over)} index(es) past the merge-debt budget "
            f"({budget:.2f}) — a real refresh is overdue",
            {"over_budget": over, "budget": budget})
    return DoctorCheck(
        "cdc.merge_debt", "ok",
        f"{len(entries)} ACTIVE index(es) within the merge-debt budget",
        {"budget": budget})


def _check_maintenance(session) -> DoctorCheck:
    from hyperspace_tpu.lifecycle.daemon import daemon_for

    backoffs = daemon_for(session).backoff_snapshot()
    if backoffs:
        return DoctorCheck(
            "maintenance", "warn",
            f"{len(backoffs)} index(es) in failure backoff — the daemon "
            f"cannot maintain them right now",
            {"backoffs": backoffs})
    return DoctorCheck("maintenance", "ok", "no failure backoffs", {})


def _check_perf(session, min_history: int = 4,
                threshold_pct: float = 25.0,
                min_abs_s: float = 0.5) -> DoctorCheck:
    """Latest-vs-history trend per recorded action name, judged by the
    bench_compare direction rules (``wall_s`` → lower is better)."""
    from hyperspace_tpu.telemetry import bench_compare, perf_ledger

    direction = bench_compare._direction("wall_s")
    by_name: Dict[str, List[float]] = {}
    for rec in perf_ledger.records(session.conf):
        if rec.get("kind") != "action" or rec.get("outcome") != "ok":
            continue
        try:
            by_name.setdefault(str(rec.get("name", "")), []).append(
                float(rec.get("wall_s", 0.0)))
        except (TypeError, ValueError):
            continue
    regressions: Dict[str, Dict[str, float]] = {}
    for name, walls in by_name.items():
        if len(walls) < min_history:
            continue
        latest = walls[-1]
        baseline = statistics.median(walls[-9:-1])
        if baseline <= 0:
            continue
        worse = latest - baseline if direction == "lower" \
            else baseline - latest
        if worse > min_abs_s and worse / baseline * 100.0 > threshold_pct:
            regressions[name] = {"latest_s": round(latest, 3),
                                 "baseline_s": round(baseline, 3)}
    if regressions:
        return DoctorCheck(
            "perf", "warn",
            f"{len(regressions)} action(s) trending slower than their "
            f"ledger history",
            {"regressions": regressions})
    return DoctorCheck("perf", "ok",
                       f"{len(by_name)} action name(s) in the ledger, "
                       f"no regression trend", {})


def _check_serving(session) -> DoctorCheck:
    from hyperspace_tpu.telemetry import metrics

    conf = session.conf
    snap = metrics.snapshot()
    requests = float(snap.get("serve.requests", 0.0) or 0.0)
    shed = float(snap.get("serve.shed", 0.0) or 0.0)
    if requests <= 0:
        return DoctorCheck("serving", "ok", "no served traffic", {})
    shed_ratio = shed / requests
    warn_ratio = float(getattr(conf, "doctor_shed_warn_ratio", 0.05))
    slo_ms = float(getattr(conf, "doctor_latency_slo_ms", 1000.0))
    burn = _slo_burn(snap.get("serve.latency_ms"), slo_ms)
    data = {"requests": int(requests), "shed_ratio": round(shed_ratio, 4),
            "slo_ms": slo_ms, "slo_burn": round(burn, 4)}
    if (warn_ratio > 0 and shed_ratio >= 5 * warn_ratio) or burn >= 0.5:
        return DoctorCheck(
            "serving", "crit",
            f"overloaded: shed ratio {shed_ratio:.2f}, SLO burn "
            f"{burn:.2f}", data)
    if (warn_ratio > 0 and shed_ratio >= warn_ratio) or burn >= 0.1:
        return DoctorCheck(
            "serving", "warn",
            f"shed ratio {shed_ratio:.2f}, SLO burn {burn:.2f}", data)
    return DoctorCheck(
        "serving", "ok",
        f"{int(requests)} requests, shed ratio {shed_ratio:.2f}, "
        f"SLO burn {burn:.2f}", data)


def _check_client(session) -> DoctorCheck:
    """Front-door health (FleetQueryClient in THIS process): open
    circuit breakers mean whole endpoints are being routed around —
    the fleet is effectively smaller than provisioned — and a high
    hedge rate means the configured hedge delay no longer matches the
    fleet's actual latency."""
    from hyperspace_tpu.telemetry import metrics

    snap = metrics.snapshot()
    open_now = int(float(snap.get("client.breaker.open_now", 0.0) or 0.0))
    opens = float(snap.get("client.breaker.open", 0.0) or 0.0)
    hedged = float(snap.get("client.hedge.sent", 0.0) or 0.0)
    wins = float(snap.get("client.hedge.wins", 0.0) or 0.0)
    data = {"breaker_open_now": open_now, "breaker_opens": int(opens),
            "hedges_sent": int(hedged), "hedge_wins": int(wins)}
    if open_now > 0:
        return DoctorCheck(
            "client", "warn",
            f"{open_now} endpoint breaker(s) OPEN — requests are being "
            f"routed around them; check those servers (docs/20 FAQ: "
            f"tuning hyperspace.client.breaker.*)", data)
    if opens > 0 or hedged > 0:
        return DoctorCheck(
            "client", "ok",
            f"breakers closed ({int(opens)} open event(s) so far), "
            f"{int(hedged)} hedge(s) sent / {int(wins)} won", data)
    return DoctorCheck("client", "ok", "no front-door traffic", data)


def _slo_burn(hist_snapshot, slo_ms: float) -> float:
    """Fraction of latency observations ABOVE the SLO, from a histogram
    snapshot's cumulative-by-construction fixed buckets (the first
    bucket bound ≥ the SLO splits under/over conservatively)."""
    if not isinstance(hist_snapshot, dict) or slo_ms <= 0:
        return 0.0
    count = float(hist_snapshot.get("count", 0) or 0)
    buckets = hist_snapshot.get("buckets")
    if count <= 0 or not isinstance(buckets, dict):
        return 0.0
    under = 0.0
    for bound, n in buckets.items():
        b = float("inf") if bound == "+Inf" else float(bound)
        if b <= slo_ms:
            under += float(n)
    return max(0.0, (count - under) / count)


def _check_lint(session, path: Optional[str] = None) -> DoctorCheck:
    """Lint freshness (docs/18-static-analysis.md): the repo contract is
    an EMPTY baseline, re-validated against the current rule-catalog
    version.  Graded ``warn`` — stale static guarantees are a risk, not
    an outage — and ``ok`` when no baseline file exists at all (an
    installed package without the repo checkout has nothing to grade).
    ``path`` overrides the repo-root default (tests)."""
    import json
    import os

    from hyperspace_tpu.lint.engine import BASELINE_NAME
    from hyperspace_tpu.lint.rules import CATALOG_VERSION
    from hyperspace_tpu.telemetry import metrics

    if path is None:
        root = __file__
        for _ in range(3):  # telemetry/doctor.py -> telemetry -> pkg -> repo
            root = os.path.dirname(root)
        path = os.path.join(root, BASELINE_NAME)
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return DoctorCheck("lint", "ok", "no baseline file (nothing "
                           "grandfathered)", {})
    except ValueError:
        return DoctorCheck("lint", "warn",
                           f"{BASELINE_NAME} is unparseable", {"path": path})
    entries = data.get("entries", []) if isinstance(data, dict) else []
    written_version = data.get("catalog_version") \
        if isinstance(data, dict) else None
    metrics.set_gauge("lint.baseline.entries", len(entries))
    if entries:
        return DoctorCheck(
            "lint", "warn",
            f"{len(entries)} grandfathered lint finding(s) in the "
            f"baseline — the contract is EMPTY; burn them down "
            f"(docs/18-static-analysis.md)",
            {"entries": len(entries), "path": path})
    if written_version is not None and written_version != CATALOG_VERSION:
        return DoctorCheck(
            "lint", "warn",
            f"baseline written against rule catalog v{written_version}, "
            f"installed rules are v{CATALOG_VERSION} — rerun "
            f"`python -m hyperspace_tpu.lint --update-baseline` (it "
            f"should stay empty)",
            {"written": written_version, "current": CATALOG_VERSION})
    return DoctorCheck("lint", "ok", "baseline empty and current", {})


def _check_device_skew(session) -> DoctorCheck:
    """Single-process mesh-straggler check: max/median ratio over the
    per-device attributed kernel-ms counters
    (``exec.device.<id>.kernel_ms``, PR 14) graded against
    ``hyperspace.doctor.deviceSkewWarn`` — a straggler device is
    visible without a fleet (the fleet.skew check extends the same
    grading across processes)."""
    from hyperspace_tpu.telemetry import fleet, metrics

    warn_at = float(getattr(session.conf, "doctor_device_skew_warn",
                            4.0))
    typed = metrics.registry().typed_snapshot()
    per_device = fleet.device_kernel_ms_map(typed["counters"])
    ratio = fleet.skew_ratio(list(per_device.values()))
    data = {"per_device_ms": {k: round(v, 1)
                              for k, v in sorted(per_device.items())},
            "ratio": round(ratio, 2)}
    if warn_at > 0 and ratio >= warn_at:
        return DoctorCheck(
            "device_skew", "warn",
            f"per-device kernel-ms skew: max/median {ratio:.1f} >= "
            f"{warn_at:g} — one device is a straggler (check the mesh "
            f"busy matrix, docs/16-observability.md)", data)
    return DoctorCheck(
        "device_skew", "ok",
        f"{len(per_device)} device(s) attributed, no kernel-ms skew",
        data)


def _check_degraded(session) -> DoctorCheck:
    from hyperspace_tpu.telemetry import metrics

    snap = metrics.snapshot()
    fallbacks = float(snap.get("degraded.fallbacks", 0.0) or 0.0)
    contained = float(snap.get("quarantine.files", 0.0) or 0.0)
    if fallbacks or contained:
        return DoctorCheck(
            "degraded", "warn",
            f"{int(fallbacks)} degraded fallback(s), "
            f"{int(contained)} execution-time quarantine(s) this process",
            {"fallbacks": int(fallbacks), "quarantines": int(contained)})
    return DoctorCheck("degraded", "ok",
                       "no degraded events this process", {})


# ---------------------------------------------------------------------------
# Headless CLI (tools/doctor.py shim): cron/CI gate on health without Python
# ---------------------------------------------------------------------------
def _alerts_check(conf) -> DoctorCheck:
    """Persisted SLO alert states folded into the CLI gate: a FIRING
    page is crit, a firing warn-severity alert (or any pending one)
    warns — so ``tools/doctor.py --alerts`` exits nonzero while an
    incident the engine already detected is still open."""
    from hyperspace_tpu.telemetry import alerts as _alerts

    states = _alerts.load_states(conf)
    firing = {n: s for n, s in states.items()
              if s.get("state") == "firing"}
    pending = {n: s for n, s in states.items()
               if s.get("state") == "pending"}
    data = {"firing": sorted(firing), "pending": sorted(pending)}
    if firing:
        pages = [n for n, s in firing.items()
                 if s.get("severity") == "page"]
        status = "crit" if pages else "warn"
        return DoctorCheck(
            "alerts", status,
            f"{len(firing)} firing SLO alert(s): "
            f"{', '.join(sorted(firing))} — see alert_history() and "
            f"the captured incident bundle(s)", data)
    if pending:
        return DoctorCheck(
            "alerts", "warn",
            f"{len(pending)} pending SLO alert(s): "
            f"{', '.join(sorted(pending))}", data)
    return DoctorCheck("alerts", "ok",
                       f"{len(states)} alert(s) tracked, none active",
                       data)


def main(argv: Optional[List[str]] = None) -> int:
    """Headless doctor: grade a system path and exit ok=0 / warn=1 /
    crit=2 so cron and CI gate on health without writing Python::

        python tools/doctor.py --system-path /lake/indexes
        python tools/doctor.py --system-path /lake/indexes --fleet --json
        python tools/doctor.py --system-path /lake/indexes --alerts

    ``--fleet`` adds the cluster checks over the published heartbeats
    (including ``fleet.alerts``); ``--alerts`` folds the PERSISTED SLO
    alert states into the grade (a firing page exits 2 even from a
    fresh process); ``--json`` prints the machine-readable report;
    ``--conf key=value`` passes extra session conf (repeatable)."""
    import argparse
    import json as _json

    parser = argparse.ArgumentParser(
        prog="doctor",
        description="Aggregated ok/warn/crit health report "
                    "(exit code 0/1/2)")
    parser.add_argument("--system-path", default=None,
                        help="hyperspace.system.path to grade "
                             "(default: the conf default)")
    parser.add_argument("--fleet", action="store_true",
                        help="add the cluster checks over published "
                             "fleet heartbeats")
    parser.add_argument("--alerts", action="store_true",
                        help="fold persisted SLO alert states into the "
                             "grade (firing page = exit 2)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="print the full report as JSON")
    parser.add_argument("--conf", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="extra session conf (repeatable)")
    args = parser.parse_args(argv)

    from hyperspace_tpu.session import HyperspaceSession

    session = HyperspaceSession(args.system_path)
    for item in args.conf:
        key, sep, value = item.partition("=")
        if not sep:
            parser.error(f"--conf needs KEY=VALUE, got {item!r}")
        session.conf.set(key, value)
    report = doctor(session, fleet=args.fleet)
    checks = list(report.checks)
    if args.alerts:
        checks.append(_guarded("alerts",
                               lambda: _alerts_check(session.conf)))
        report = DoctorReport(checks)
    if args.as_json:
        print(_json.dumps(report.to_dict(), default=str, indent=2))
    else:
        print(report.render())
    return SEVERITY[report.status]
