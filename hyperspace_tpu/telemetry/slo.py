"""Pure SLO math: multi-window multi-burn-rate evaluation over metric
samples, plus the alert-state transition function.

The engine half (telemetry/alerts.py) owns threads, conf, and the
LogStore; everything HERE is a pure function over plain data — a list of
``(ts, counters, histograms)`` samples in, burn rates and state
transitions out — so the clock-skew / flap-damping matrix is unit-testable
with zero IO (tests/test_alerts.py).

The model is the Google-SRE multi-window multi-burn-rate recipe:

  - An **objective** declares a target ratio of GOOD events (availability:
    ``serve.ok`` over ok+errors+shed; latency: observations under the SLO
    bound over all observations).  The **error budget** is ``1 - target``.
  - The **burn rate** over a window is ``(bad/total in window) /
    budget`` — 1.0 means the budget is being spent exactly at the rate
    that exhausts it at the window's end; 14.4 over 5m+1h means ~2% of a
    30-day budget gone in an hour (the classic fast-burn page).
  - A **rule** breaches only when BOTH its short and long windows exceed
    the factor: the long window is the signal, the short window is the
    "is it still happening" guard that ends the page quickly after
    recovery.

Sampling model: the engine appends one cumulative sample per evaluation
tick.  A window's delta is computed against the NEWEST sample at least
``window_s`` old (clamped to the oldest available) — with samples riding
the heartbeat cadence this is exact for monotonic counters.  Skew and
restarts are tolerated, not assumed away: samples are sorted by ts, a
negative counter delta (process restart, registry reset) reads as an
EMPTY window (no data beats wrong data), and a window that spans less
than ``min_fraction`` of its nominal width is marked incomplete so young
processes do not page off seconds of data.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Alert states (persisted by telemetry/alerts.py; docs/16).
PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"

# Fraction of the nominal window that must be covered by samples before
# a rule is allowed to breach (young process / sparse ring guard).
MIN_WINDOW_FRACTION = 0.5


@dataclasses.dataclass(frozen=True)
class BurnRule:
    """One multi-window burn-rate rule: breach when BOTH windows burn
    faster than ``factor`` budgets-per-window."""

    name: str          # "fast_burn" | "slow_burn"
    short_s: float
    long_s: float
    factor: float
    severity: str      # "page" | "warn"


@dataclasses.dataclass(frozen=True)
class Sample:
    """One cumulative observation of the metrics registry."""

    ts: float
    good: float
    bad: float

    @property
    def total(self) -> float:
        return self.good + self.bad


def hist_split(hist: Optional[Dict[str, Any]],
               slo_ms: float) -> Tuple[float, float]:
    """``(good, bad)`` cumulative observation counts from a histogram
    snapshot's fixed buckets: good = observations in buckets bounded
    ``<= slo_ms`` (the conservative split telemetry/doctor.py uses),
    bad = the rest.  ``(0, 0)`` for missing/malformed input."""
    if not isinstance(hist, dict) or slo_ms <= 0:
        return 0.0, 0.0
    try:
        count = float(hist.get("count", 0) or 0)
        buckets = hist.get("buckets")
        if count <= 0 or not isinstance(buckets, dict):
            return 0.0, 0.0
        under = 0.0
        for bound, n in buckets.items():
            b = float("inf") if str(bound) == "+Inf" else float(bound)
            if b <= slo_ms:
                under += float(n or 0)
        under = min(under, count)
        return under, count - under
    except (TypeError, ValueError):
        return 0.0, 0.0


def window_delta(samples: Sequence[Sample], now: float,
                 window_s: float) -> Tuple[float, float, float]:
    """``(good_delta, bad_delta, covered_s)`` between the latest sample
    and the newest sample at least ``window_s`` old (clamped to the
    oldest).  Pure and skew-tolerant: samples are sorted by ts (an NTP
    step reordering the ring cannot invert a delta), and a NEGATIVE
    delta on either counter — a restart or registry reset inside the
    window — reads as an empty window rather than a huge phantom burn."""
    if not samples or window_s <= 0:
        return 0.0, 0.0, 0.0
    ordered = sorted(samples, key=lambda s: s.ts)
    head = ordered[-1]
    target = now - window_s
    base = ordered[0]
    for s in ordered:
        if s.ts <= target:
            base = s
        else:
            break
    covered = max(0.0, head.ts - base.ts)
    good = head.good - base.good
    bad = head.bad - base.bad
    if good < 0 or bad < 0 or covered <= 0:
        return 0.0, 0.0, 0.0
    return good, bad, covered


def burn_rate(good: float, bad: float, budget: float) -> float:
    """Budget-consumption rate of one window: observed bad ratio over
    the error budget.  0.0 for an empty window or a degenerate budget
    (target >= 1 would page on any single error — treat as unburnable)."""
    total = good + bad
    if total <= 0 or budget <= 0:
        return 0.0
    return (bad / total) / budget


def evaluate_rule(samples: Sequence[Sample], now: float, rule: BurnRule,
                  budget: float) -> Dict[str, Any]:
    """One rule over one objective's sample ring: both window burns, the
    breach verdict, and window-coverage diagnostics.  A window covering
    less than ``MIN_WINDOW_FRACTION`` of its nominal width cannot breach
    (but CAN clear — recovery is never suppressed)."""
    g_s, b_s, cov_s = window_delta(samples, now, rule.short_s)
    g_l, b_l, cov_l = window_delta(samples, now, rule.long_s)
    burn_short = burn_rate(g_s, b_s, budget)
    burn_long = burn_rate(g_l, b_l, budget)
    complete = (cov_s >= rule.short_s * MIN_WINDOW_FRACTION
                and cov_l >= rule.long_s * MIN_WINDOW_FRACTION)
    breached = (complete and burn_short >= rule.factor
                and burn_long >= rule.factor)
    return {"rule": rule.name, "severity": rule.severity,
            "factor": rule.factor,
            "burn_short": round(burn_short, 4),
            "burn_long": round(burn_long, 4),
            "covered_short_s": round(cov_s, 3),
            "covered_long_s": round(cov_l, 3),
            "complete": complete, "breached": breached}


def evaluate_objective(samples: Sequence[Sample], now: float,
                       rules: Sequence[BurnRule],
                       target: float) -> Dict[str, Any]:
    """Every rule over one objective; the worst breached rule (page
    beats warn) decides ``breached``/``severity``."""
    budget = 1.0 - float(target)
    evaluations = [evaluate_rule(samples, now, r, budget) for r in rules]
    breached = [e for e in evaluations if e["breached"]]
    worst = None
    for e in breached:
        if worst is None or (e["severity"] == "page"
                             and worst["severity"] != "page"):
            worst = e
    return {"target": target, "breached": bool(breached),
            "severity": worst["severity"] if worst else "",
            "worst_rule": worst["rule"] if worst else "",
            "rules": evaluations}


def threshold_objective(value: Optional[float], threshold: float,
                        severity: str) -> Dict[str, Any]:
    """Gauge-style objective (staleness seconds, dead-holder build
    claims): breached while ``value >= threshold``.  A None value (probe
    failed) never breaches — a blind probe is the doctor's finding, not
    a page."""
    breached = (value is not None and threshold > 0
                and float(value) >= threshold)
    return {"value": value, "threshold": threshold,
            "breached": bool(breached),
            "severity": severity if breached else "", "rules": []}


# ---------------------------------------------------------------------------
# The alert state machine (flap damping)
# ---------------------------------------------------------------------------
def step_state(prev: Optional[Dict[str, Any]], breached: bool,
               severity: str, now: float, pending_evals: int = 2,
               resolve_evals: int = 2) -> Tuple[Dict[str, Any],
                                                Optional[str]]:
    """One evaluation tick of one alert's state machine.  Returns
    ``(new_state, transition)`` where ``transition`` is ``"firing"`` /
    ``"resolved"`` / None.

    Flap damping: a breach must persist ``pending_evals`` consecutive
    evaluations before pending promotes to firing (a single bad tick
    never pages), and a firing alert must see ``resolve_evals``
    consecutive clear evaluations before it resolves (a single good
    tick mid-incident never closes the page).  ``pending_evals <= 1``
    fires immediately on the first breach."""
    state = str(prev.get("state", RESOLVED)) if prev else RESOLVED
    streak = int(prev.get("streak", 0) or 0) if prev else 0
    since = float(prev.get("since", now) or now) if prev else now
    pending_evals = max(1, int(pending_evals))
    resolve_evals = max(1, int(resolve_evals))

    if breached:
        if state == FIRING:
            return ({"state": FIRING, "streak": 0, "since": since,
                     "severity": severity or str(
                         prev.get("severity", "") if prev else "")},
                    None)
        streak = streak + 1 if state == PENDING else 1
        if streak >= pending_evals:
            return ({"state": FIRING, "streak": 0, "since": now,
                     "severity": severity}, "firing")
        return ({"state": PENDING, "streak": streak, "since": since
                 if state == PENDING else now,
                 "severity": severity}, None)
    if state == FIRING:
        streak += 1
        if streak >= resolve_evals:
            return ({"state": RESOLVED, "streak": 0, "since": now,
                     "severity": ""}, "resolved")
        return ({"state": FIRING, "streak": streak, "since": since,
                 "severity": str(prev.get("severity", "")
                                 if prev else "")}, None)
    if state == PENDING:
        # A pending alert that stops breaching goes straight back: it
        # never fired, so there is nothing to damp.
        return ({"state": RESOLVED, "streak": 0, "since": now,
                 "severity": ""}, None)
    return ({"state": RESOLVED, "streak": 0, "since": since,
             "severity": ""}, None)


def default_rules(fast_short_s: float = 300.0, fast_long_s: float = 3600.0,
                  fast_factor: float = 14.4,
                  slow_short_s: float = 21600.0,
                  slow_long_s: float = 259200.0,
                  slow_factor: float = 1.0) -> List[BurnRule]:
    """The classic two-rule ladder: 5m+1h fast burn pages, 6h+3d slow
    burn warns (windows/factors conf-tunable — tests shrink them to
    sub-second so a drill fires within two evaluation intervals)."""
    return [
        BurnRule("fast_burn", fast_short_s, fast_long_s, fast_factor,
                 "page"),
        BurnRule("slow_burn", slow_short_s, slow_long_s, slow_factor,
                 "warn"),
    ]
