"""Bench regression watchdog: diff one bench run against a baseline.

``bench.py --compare <baseline>`` feeds two artifacts here — the current
run's per-section checkpoint JSONL and a baseline, which may be a prior
checkpoint JSONL, a headline-shaped JSON (the checked-in ``BENCH_r0N``
artifacts), or ``auto`` (the previous run's rotated results file / the
perf ledger).  The diff is metric-level, not section-level: both shapes
flatten to the same dotted metric paths (a section checkpoint's updates
are exactly what ``finalize`` merges into the headline detail), so any
two of them compare.

What counts as comparable (conservative allowlist — everything else is
ignored, so new metrics never false-positive):

  - ``*_s`` scalar seconds and ``*_s.median`` timing stats → LOWER is
    better;
  - ``*speedup*`` ratios (per-workload, geomean, warm-vs-host) → HIGHER
    is better;
  - ``*_mrows_per_s`` / ``*_mb_s`` throughput rates → HIGHER is better.

A metric regresses when it moves past ``threshold_pct`` in the bad
direction AND by more than ``min_abs_s`` — the absolute floor that
keeps toy-scale timer noise from tripping the watchdog.  For seconds
metrics the floor applies to the delta directly; a RATIO/RATE metric
(speedup, mrows/s) carries no seconds of its own, so the floor applies
to its *reference seconds* — the sibling timing metric of the same
workload (``X_speedup`` → ``X_scan_s.median``; ``geomean_speedup`` →
the largest contributing scan median).  A 2 ms workload whose speedup
halves is timer noise; a 20 s workload whose speedup halves is a
regression.  Ratios with no resolvable sibling fall back to
threshold-only.  For any
regressed metric whose section carries per-index build-phase records
(``build_phases`` / ``index_build_phases``), the report renders a
per-phase attribution table: which phase of which index's build ate the
delta (the question BENCH_r04's spill numbers begged).

This module is pure diff logic — no jax, no bench imports — so the test
suite exercises regression/no-regression/missing-baseline directly and
``bench.py --compare-only`` runs it without paying a bench.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_THRESHOLD_PCT = 25.0
DEFAULT_MIN_ABS_S = 0.5

# Headline-detail bookkeeping keys that are not metrics.
_SKIP_KEYS = frozenset({
    "section", "status", "elapsed_s", "reason", "platform", "sections_run",
    "results_file", "trace_file", "bench_elapsed_s", "note", "scale",
    "skipped", "budget_s", "bench",
})
_PHASE_KEYS = ("build_phases", "index_build_phases")


class BaselineError(Exception):
    """The named baseline cannot be read/parsed (exit code 2 in bench)."""


@dataclasses.dataclass
class RunMetrics:
    """One run, flattened: metric path → value, plus attribution data."""

    path: str
    metrics: Dict[str, float]
    key_section: Dict[str, str]          # top metric path → section name
    phases: Dict[str, List[dict]]        # section → per-index phase dicts


@dataclasses.dataclass
class CompareResult:
    regressions: List[dict]
    improvements: List[dict]
    compared: int
    baseline_path: str

    @property
    def ok(self) -> bool:
        return not self.regressions


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------
def _flatten(prefix: str, value: Any, out: Dict[str, float]) -> None:
    if isinstance(value, bool):
        return
    if isinstance(value, (int, float)):
        out[prefix] = float(value)
        return
    if isinstance(value, dict):
        for k, v in value.items():
            if k in _SKIP_KEYS:
                continue
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)


def _merge_detail(detail: Dict[str, Any], section_of_key: Dict[str, str],
                  phases: Dict[str, List[dict]], section: str) -> dict:
    clean: Dict[str, Any] = {}
    for k, v in detail.items():
        if k in _PHASE_KEYS:
            if isinstance(v, list):
                phases.setdefault(section, []).extend(
                    p for p in v if isinstance(p, dict))
            continue
        if k in _SKIP_KEYS:
            continue
        clean[k] = v
        section_of_key[k] = section
        # One level of nesting also carries phase lists (sf10/sf100 put
        # theirs inside their own sub-dict).
        if isinstance(v, dict):
            for pk in _PHASE_KEYS:
                pv = v.get(pk)
                if isinstance(pv, list):
                    phases.setdefault(k, []).extend(
                        p for p in pv if isinstance(p, dict))
    return clean


def load_run(path: str) -> RunMetrics:
    """Load a results artifact: per-section checkpoint JSONL (preferred)
    or headline-shaped JSON.  Raises :class:`BaselineError` when the file
    is missing or holds neither shape."""
    try:
        with open(path, encoding="utf-8") as f:
            lines = [ln for ln in f if ln.strip()]
    except OSError as e:
        raise BaselineError(f"cannot read {path!r}: {e}") from e
    records = []
    for ln in lines:
        try:
            records.append(json.loads(ln))
        except ValueError:
            continue  # a torn checkpoint line is survivable
    if not records:
        raise BaselineError(f"{path!r} holds no parseable JSON")

    key_section: Dict[str, str] = {}
    phases: Dict[str, List[dict]] = {}
    merged: Dict[str, Any] = {}
    section_records = [r for r in records
                       if isinstance(r, dict) and r.get("status") == "ok"
                       and "section" in r]
    if section_records:
        for r in section_records:
            detail = {k: v for k, v in r.items()}
            merged.update(_merge_detail(detail, key_section, phases,
                                        str(r["section"])))
    else:
        headline = None
        for r in records:
            if isinstance(r, dict) and isinstance(r.get("headline"), dict):
                headline = r["headline"]
            elif isinstance(r, dict) and "detail" in r \
                    and isinstance(r["detail"], dict):
                headline = r
        if headline is None:
            raise BaselineError(
                f"{path!r} holds neither section checkpoints nor a "
                f"headline record")
        merged = _merge_detail(dict(headline.get("detail", {})),
                               key_section, phases, "headline")
        if isinstance(headline.get("value"), (int, float)):
            merged.setdefault("geomean_speedup", headline["value"])
            key_section.setdefault("geomean_speedup", "headline")

    flat: Dict[str, float] = {}
    _flatten("", merged, flat)
    return RunMetrics(path=path, metrics=flat, key_section=key_section,
                      phases=phases)


# ---------------------------------------------------------------------------
# Classification + diff
# ---------------------------------------------------------------------------
def _direction(path: str) -> Optional[str]:
    """"lower" / "higher" is better, or None (not comparable)."""
    parts = path.split(".")
    last = parts[-1]
    if last.endswith("_mrows_per_s") or last.endswith("_mb_s"):
        return "higher"
    if "speedup" in last or last == "geomean_speedup":
        return "higher"
    if last == "firing" or last.endswith("_ratio"):
        # Alert gauges and overhead ratios: fewer firing alerts and a
        # smaller ratio are better.  Unitless — the seconds floor does
        # not apply (and ``*_rate`` stays out: hedge_win_rate is
        # neither better high nor low).
        return "lower"
    if last.endswith("_s"):
        return "lower"
    if last == "median" and len(parts) >= 2 and parts[-2].endswith("_s") \
            and not parts[-2].endswith("_per_s"):
        return "lower"
    return None


def _seconds_metric(path: str) -> bool:
    """True when the metric is in seconds — the only unit the
    ``min_abs_s`` absolute floor is meaningful for."""
    last = path.split(".")[-1]
    return last.endswith("_s") or last == "median"


def _section_of(run: RunMetrics, path: str) -> str:
    return run.key_section.get(path.split(".")[0], "")


def _ratio_reference_seconds(path: str, current: RunMetrics,
                             baseline: RunMetrics) -> Optional[float]:
    """The seconds a ratio/rate metric is ABOUT — the sibling timing of
    the same workload, max over both runs (either run being slow enough
    makes the ratio meaningful).  None when no sibling resolves."""
    parts = path.split(".")
    last = parts[-1]
    prefix = parts[:-1]

    def key(name: str) -> str:
        return ".".join(prefix + [name]) if prefix else name

    candidates: List[str] = []
    if last == "geomean_speedup":
        # The geomean's reference is the slowest contributing workload:
        # every *_scan_s.median under the same prefix.
        scope = ".".join(prefix) + "." if prefix else ""
        for run in (current, baseline):
            for k in run.metrics:
                if k.startswith(scope) and k.endswith("_scan_s.median") \
                        and k.count(".") == len(prefix) + 1:
                    candidates.append(k)
    elif last.endswith("_speedup"):
        stem = last[: -len("_speedup")]
        candidates += [key(f"{stem}_scan_s.median"),
                       key(f"{stem}_indexed_s.median")]
    elif last.endswith("speedup_vs_host"):
        candidates += [key("host_s.median"), key("warm_s.median"),
                       key("warm_resident_s.median")]
    elif last.endswith("_mrows_per_s"):
        stem = last[: -len("_mrows_per_s")]
        candidates.append(key(f"{stem}_s.median"))
    elif last.endswith("_mb_s"):
        stem = last[: -len("_mb_s")]
        candidates.append(key(f"{stem}_full_s.median"))
    vals = [run.metrics[c] for run in (current, baseline)
            for c in candidates if c in run.metrics]
    return max(vals) if vals else None


def compare_runs(current: RunMetrics, baseline: RunMetrics,
                 threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                 min_abs_s: float = DEFAULT_MIN_ABS_S) -> CompareResult:
    regressions: List[dict] = []
    improvements: List[dict] = []
    compared = 0
    for path, cur in sorted(current.metrics.items()):
        direction = _direction(path)
        if direction is None or path not in baseline.metrics:
            continue
        base = baseline.metrics[path]
        if base <= 0:
            continue
        compared += 1
        delta_pct = (cur - base) / base * 100.0
        finding = {"metric": path,
                   "section": _section_of(current, path)
                   or _section_of(baseline, path),
                   "baseline": round(base, 4), "current": round(cur, 4),
                   "delta_pct": round(delta_pct, 1),
                   "direction": direction}
        if direction == "lower":
            floor = min_abs_s if _seconds_metric(path) else 0.0
            if delta_pct > threshold_pct and (cur - base) > floor:
                regressions.append(finding)
            elif delta_pct < -threshold_pct and (base - cur) > floor:
                improvements.append(finding)
        else:
            # Higher is better (ratios/rates): the abs floor applies to
            # the workload's reference seconds — a halved speedup on a
            # 2 ms workload is timer noise, on a 20 s one a regression.
            ref = _ratio_reference_seconds(path, current, baseline)
            if ref is not None and ref <= min_abs_s:
                continue
            if delta_pct < -threshold_pct:
                regressions.append(finding)
            elif delta_pct > threshold_pct:
                improvements.append(finding)
    regressions.sort(key=lambda r: -abs(r["delta_pct"]))
    improvements.sort(key=lambda r: -abs(r["delta_pct"]))
    return CompareResult(regressions=regressions, improvements=improvements,
                         compared=compared, baseline_path=baseline.path)


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------
def _phase_rows(recs: List[dict]) -> Dict[Tuple[str, str], float]:
    out: Dict[Tuple[str, str], float] = {}
    for i, rec in enumerate(recs):
        index = str(rec.get("index", f"#{i}"))
        for k, v in rec.items():
            if k == "index" or not isinstance(v, (int, float)):
                continue
            key = (index, k[:-2] if k.endswith("_s") else k)
            out[key] = out.get(key, 0.0) + float(v)
    return out


def phase_attribution(current: RunMetrics, baseline: RunMetrics,
                      section: str) -> str:
    """Per-phase build attribution table for ``section`` — empty string
    when either run lacks phase records for it."""
    cur = current.phases.get(section)
    base = baseline.phases.get(section)
    if not cur or not base:
        return ""
    c_rows, b_rows = _phase_rows(cur), _phase_rows(base)
    keys = sorted(set(c_rows) | set(b_rows))
    lines = [f"  per-phase attribution for section {section!r}:",
             f"    {'index':<14}{'phase':<14}{'baseline_s':>12}"
             f"{'current_s':>12}{'delta_s':>10}"]
    for index, phase in keys:
        b = b_rows.get((index, phase), 0.0)
        c = c_rows.get((index, phase), 0.0)
        lines.append(f"    {index:<14}{phase:<14}{b:>12.3f}{c:>12.3f}"
                     f"{c - b:>+10.3f}")
    return "\n".join(lines)


def render_report(result: CompareResult, current: RunMetrics,
                  baseline: RunMetrics,
                  threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                  min_abs_s: float = DEFAULT_MIN_ABS_S) -> str:
    lines = [f"bench compare: {os.path.basename(current.path)} vs "
             f"{os.path.basename(baseline.path)} "
             f"({result.compared} comparable metrics, "
             f"threshold {threshold_pct:g}% / {min_abs_s:g}s)"]
    if not result.regressions:
        lines.append("no regression")
    else:
        lines.append(f"REGRESSED: {len(result.regressions)} metric(s)")
        for r in result.regressions:
            word = "slower" if r["direction"] == "lower" else "worse"
            lines.append(
                f"  [{r['section'] or '?'}] {r['metric']}: "
                f"{r['baseline']} -> {r['current']} "
                f"({r['delta_pct']:+.1f}% {word})")
        for section in sorted({r["section"] for r in result.regressions
                               if r["section"]}):
            table = phase_attribution(current, baseline, section)
            if table:
                lines.append(table)
    if result.improvements:
        lines.append(f"improved: {len(result.improvements)} metric(s)")
        for r in result.improvements[:10]:
            lines.append(
                f"  [{r['section'] or '?'}] {r['metric']}: "
                f"{r['baseline']} -> {r['current']} "
                f"({r['delta_pct']:+.1f}%)")
    return "\n".join(lines)


def compare_files(current_path: str, baseline_path: str,
                  threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                  min_abs_s: float = DEFAULT_MIN_ABS_S
                  ) -> Tuple[CompareResult, str]:
    """Convenience: load both artifacts, diff, render.  Raises
    :class:`BaselineError` for an unreadable baseline OR current."""
    current = load_run(current_path)
    baseline = load_run(baseline_path)
    result = compare_runs(current, baseline, threshold_pct, min_abs_s)
    return result, render_report(result, current, baseline,
                                 threshold_pct, min_abs_s)
