"""Per-action build reports: where an index build/maintenance run spent
its time, bytes, and memory.

PR 4 made *queries* explain themselves (telemetry/report.py); this is
the same idea for the build/maintenance path — the side BENCH_r04 showed
dominating wall-clock (sf10_li: 5.0 s of read vs 43.2 s + 40.9 s of
spill) with nothing but a flat seconds dict to show for it.  Every
action run through ``actions/base.Action.run()`` owns one
:class:`BuildReport`:

  - **phases**: wall seconds per named phase (``read`` → ``spill_route``
    → ``kernel`` → ``spill_finish`` → ``write`` → ``sketch``, plus the
    protocol's ``validate``/``commit`` and the pipelined builder's two
    STALL phases ``prefetch``/``finalize`` — consumer time blocked on
    decode, and the exposed finalize tail after routing drains),
    accumulated across conflict retries and across the prefetch/route/
    finalize pools' worker threads (the report is lock-protected and
    owned by the ACTION, not a contextvar — worker threads do not
    inherit context; overlapped phases are CPU-attributed seconds and
    may sum past wall clock on a pipelined spill build).  Phases are classified device vs
    host (``kernel`` is device compute; everything else is host/IO) so
    ``device_s``/``host_s`` fall out.
  - **bytes**: decoded source bytes in (``bytes_read``), index data
    bytes out (``bytes_written``), and the external build's temporary
    spill-run bytes (``spill_bytes`` — the figure that must match what
    actually landed on disk) with run/file counts.
  - **memory**: peak host RSS plus live device-buffer bytes, sampled at
    action end via :func:`sample_memory` — lightweight gauges, never a
    profiler.

Finish exports the report into the PR 4 metrics registry
(``build.phase.<name>.seconds``, ``build.spill.bytes``,
``build.bytes.written``, ``build.actions``, ``build.peak_rss_mb``
gauge), synthesizes ``build.phase.<name>`` child spans onto the live
``action.*`` span (so a JSONL trace greps for phase attribution), and
publishes the report as ``session.last_build_report_value`` /
:func:`last_report` — surfaced by ``Hyperspace.last_build_report()``.

Cost contract: ``hyperspace.system.buildProfiling.enabled`` (default on)
gates the memory sampling, metric export, span synthesis, and the perf
ledger append; phase timing itself predates this module (the
``build_stats_log`` seconds bench.py already records) and stays on.  The
bench ``build_profile`` section gates the on-vs-off delta < 3%.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

# Phase → attribution class.  ``kernel`` is the device hash+sort pass
# (or its bit-identical host mirror — still "compute", and the mirror
# only runs when the cost model says the chip would lose); everything
# else is host-side IO/shuffle.
_DEVICE_PHASES = frozenset({"kernel"})


def _phase_key(name: str) -> str:
    """Normalize legacy ``<phase>_s`` keys (build_stats_log) to bare
    phase names."""
    return name[:-2] if name.endswith("_s") else name


class BuildReport:
    """The explain-yourself artifact of one action run."""

    def __init__(self, action: str = "", index: str = "") -> None:
        self.action = action
        self.index = index
        self.started_at = time.time()
        self.wall_s = 0.0
        self.outcome = "ok"  # "ok" | "noop" | "error"
        self.error = ""
        self.conflict_retries = 0
        self.phases: Dict[str, float] = {}
        self.bytes_read = 0
        self.bytes_written = 0
        self.files_written = 0
        self.spill_bytes = 0
        self.spill_runs = 0
        self.peak_rss_mb: Optional[float] = None
        self.device_live_bytes: Optional[int] = None
        # Action-specific annotations (a refresh records its mode and
        # diff counts here — the RefreshSummary surfaced through
        # ``last_build_report()``); flat scalars only.
        self.properties: Dict[str, Any] = {}
        # Per-jax-device attributed kernel milliseconds (mesh-sharded
        # route/kernel passes: the SPMD program occupies every mesh
        # device for its duration).  Lands in the perf-ledger record so
        # ``doctor()``'s ledger-trend check and ``--compare``
        # attribution can see per-device skew across builds.
        self.device_kernel_ms: Dict[int, float] = {}
        # Timeline intervals (telemetry/timeline.py, when enabled): one
        # (lane, start_ns, end_ns) per add_phase call — lane = phase
        # name — so the gap/overlap analysis can say "read idle while
        # spill_route busy", which summed seconds cannot.  Memory
        # samples are fed by the background sampler; per-phase
        # high-water marks come from intersecting the two.
        self.intervals: list = []
        self.memory_samples: list = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    # -- recording (thread-safe: spill route/finish pools call in) ----------
    def add_phase(self, name: str, seconds: float) -> None:
        from hyperspace_tpu.telemetry import timeline

        name = _phase_key(name)
        with self._lock:
            self.phases[name] = self.phases.get(name, 0.0) + float(seconds)
        if timeline.timeline_enabled():
            # The caller timed [now - seconds, now]: reconstruct the
            # interval without touching any call site.
            end_ns = time.monotonic_ns()
            start_ns = end_ns - int(float(seconds) * 1e9)
            with self._lock:
                if len(self.intervals) < 8192:  # a runaway phase loop
                    self.intervals.append((name, start_ns, end_ns))
            timeline.record_interval(name, "build.phase", start_ns,
                                     end_ns)

    def add_device_kernel_ms(self, device_id: int, ms: float) -> None:
        """Attribute ``ms`` of kernel time to one jax device (mesh route
        workers call in concurrently)."""
        with self._lock:
            self.device_kernel_ms[int(device_id)] = \
                self.device_kernel_ms.get(int(device_id), 0.0) + float(ms)

    def add_memory_sample(self, ts_ns: int, rss_mb: float,
                          device_bytes: int) -> None:
        """One background-sampler observation (timeline.MemorySampler
        sink contract)."""
        with self._lock:
            if len(self.memory_samples) < 8192:
                self.memory_samples.append(
                    (int(ts_ns), float(rss_mb), int(device_bytes)))

    def add_bytes(self, *, read: int = 0, written: int = 0, files: int = 0,
                  spill: int = 0, spill_runs: int = 0) -> None:
        with self._lock:
            self.bytes_read += int(read)
            self.bytes_written += int(written)
            self.files_written += int(files)
            self.spill_bytes += int(spill)
            self.spill_runs += int(spill_runs)

    def sample_memory(self) -> None:
        """Peak host RSS + live device-buffer bytes — one getrusage call
        and, when jax is already loaded, a live-array walk.  Called at
        action end (never per row/file)."""
        try:
            import resource

            self.peak_rss_mb = round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                / 1024.0, 1)
        except Exception:  # noqa: BLE001 — non-POSIX: report without it
            pass
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return  # never force the jax import for a metadata-only action
        try:
            self.device_live_bytes = int(sum(
                int(getattr(a, "nbytes", 0)) for a in jax.live_arrays()))
        except Exception:  # noqa: BLE001 — backend without live_arrays
            pass

    # -- derived -------------------------------------------------------------
    def phase_total_s(self) -> float:
        return sum(self.phases.values())

    def lane_report(self) -> Dict[str, Any]:
        """Gap/overlap analysis over this build's recorded intervals
        (``hyperspace.system.timeline.enabled`` must have been on):
        per-lane busy fractions plus the pairwise "X idle while Y busy"
        matrix — ``idle_while_busy["read"]["spill_route"]`` is ROADMAP
        item 2's serialization claim as a measured number."""
        from hyperspace_tpu.telemetry import timeline

        with self._lock:
            intervals = [(lane, s, e) for lane, s, e in self.intervals]
        return timeline.busy_report(intervals)

    def phase_memory_mb(self) -> Dict[str, float]:
        """Per-phase high-water host RSS (MB): the max sampled RSS whose
        timestamp falls inside any of that phase's intervals — what
        "the spill phase peaks at X" means, instead of one end-of-action
        peak that cannot name its phase."""
        with self._lock:
            intervals = list(self.intervals)
            samples = list(self.memory_samples)
        out: Dict[str, float] = {}
        for lane, s, e in intervals:
            for ts, rss_mb, _dev in samples:
                if s <= ts <= e and rss_mb > out.get(lane, 0.0):
                    out[lane] = rss_mb
        return {k: round(v, 1) for k, v in sorted(out.items())}

    @property
    def mesh_devices(self) -> int:
        """How many mesh devices this build's sharded kernels spanned
        (0 = the single-device path ran throughout)."""
        return int(self.properties.get("mesh_devices", 0) or 0)

    @property
    def device_s(self) -> float:
        return sum(v for k, v in self.phases.items() if k in _DEVICE_PHASES)

    @property
    def host_s(self) -> float:
        return sum(v for k, v in self.phases.items()
                   if k not in _DEVICE_PHASES)

    # -- lifecycle (driven by actions/base.Action.run) -----------------------
    def finish(self, outcome: str = "ok", error: str = "") -> None:
        self.wall_s = time.perf_counter() - self._t0
        self.outcome = outcome
        self.error = error

    def export_metrics(self) -> None:
        """One report → the process metrics registry
        (docs/16-observability.md catalog)."""
        from hyperspace_tpu.telemetry import metrics

        metrics.inc("build.actions")
        metrics.observe("build.wall.seconds", self.wall_s * 1000.0)
        for name, s in self.phases.items():
            metrics.inc(f"build.phase.{name}.seconds", s)
        if self.spill_bytes:
            metrics.inc("build.spill.bytes", self.spill_bytes)
        if self.spill_runs:
            metrics.inc("build.spill.runs", self.spill_runs)
        if self.bytes_written:
            metrics.inc("build.bytes.written", self.bytes_written)
        if self.bytes_read:
            metrics.inc("build.bytes.read", self.bytes_read)
        if self.peak_rss_mb is not None:
            metrics.set_gauge("build.peak_rss_mb", self.peak_rss_mb)
        if self.device_live_bytes is not None:
            metrics.set_gauge("build.device.live_bytes",
                              self.device_live_bytes)

    def attach_to_span(self, sp) -> None:
        """Summarize onto the live ``action.*`` span and synthesize one
        ``build.phase.<name>`` child per phase, so a JSONL trace carries
        per-phase build attribution (the CI smoke grep's contract)."""
        from hyperspace_tpu.telemetry.trace import Span

        sp.set(build_wall_s=round(self.wall_s, 4),
               build_phase_total_s=round(self.phase_total_s(), 4),
               build_bytes_written=self.bytes_written,
               build_spill_bytes=self.spill_bytes)
        children = getattr(sp, "children", None)
        if children is None:
            return  # tracing off: sp is the shared no-op
        for name, s in sorted(self.phases.items()):
            child = Span(f"build.phase.{name}", {})
            child.start_s = self.started_at
            child.duration_ms = s * 1000.0
            children.append(child)

    # -- serialization -------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "action": self.action,
            "index": self.index,
            "started_at": self.started_at,
            "wall_s": round(self.wall_s, 4),
            "outcome": self.outcome,
            **({"error": self.error} if self.error else {}),
            "conflict_retries": self.conflict_retries,
            "phases_s": {k: round(v, 4)
                         for k, v in sorted(self.phases.items())},
            "device_s": round(self.device_s, 4),
            "host_s": round(self.host_s, 4),
            "bytes_read": self.bytes_read,
            "bytes_written": self.bytes_written,
            "files_written": self.files_written,
            "spill_bytes": self.spill_bytes,
            "spill_runs": self.spill_runs,
            "peak_rss_mb": self.peak_rss_mb,
            "device_live_bytes": self.device_live_bytes,
            **({"properties": dict(sorted(self.properties.items()))}
               if self.properties else {}),
            **({"device_kernel_ms": {
                str(k): round(v, 3)
                for k, v in sorted(self.device_kernel_ms.items())}}
               if self.device_kernel_ms else {}),
            # Timeline extras (present only when the interval recorder
            # was on for this build): the busy-fraction matrix and the
            # per-phase memory high-water marks.
            **({"lanes": self.lane_report()} if self.intervals else {}),
            **({"phase_peak_rss_mb": self.phase_memory_mb()}
               if self.memory_samples and self.intervals else {}),
        }

    def render(self) -> str:
        lines = [f"Build report: {self.action} index={self.index or '?'} "
                 f"outcome={self.outcome} wall={self.wall_s:.3f}s"]
        if self.conflict_retries:
            lines.append(f"  conflicts absorbed: {self.conflict_retries}")
        for name, s in sorted(self.phases.items(),
                              key=lambda kv: -kv[1]):
            side = "device" if name in _DEVICE_PHASES else "host"
            lines.append(f"  phase {name:<14}{s:>10.3f} s  [{side}]")
        lines.append(f"  bytes: read={self.bytes_read} "
                     f"written={self.bytes_written} "
                     f"spill={self.spill_bytes} "
                     f"(runs={self.spill_runs}, "
                     f"files={self.files_written})")
        if self.peak_rss_mb is not None:
            lines.append(f"  peak host RSS: {self.peak_rss_mb:.1f} MB")
        if self.device_live_bytes is not None:
            lines.append(f"  live device buffers: "
                         f"{self.device_live_bytes} bytes")
        return "\n".join(lines)


# Last finished report, process-wide (the session carries its own copy;
# this is the fallback for actions constructed without a session).
_last: Optional[BuildReport] = None
_last_lock = threading.Lock()


def publish(report: BuildReport, session=None) -> None:
    global _last
    with _last_lock:
        _last = report
    if session is not None:
        session.last_build_report_value = report


def last_report() -> Optional[BuildReport]:
    with _last_lock:
        return _last


def profiling_enabled(conf) -> bool:
    return bool(getattr(conf, "build_profiling_enabled", True))
