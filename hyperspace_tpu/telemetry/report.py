"""Per-query run reports: why this query ran the way it did.

``Dataset.collect()`` opens a :class:`QueryRunReport` for the duration of
the query; instrumentation points append structured *decisions* to it
(rule applied/skipped + reason, degraded fallbacks, quarantine
containment, transient-IO retries) through :func:`record` — a contextvar
lookup plus an append, always on, independent of whether span tracing is
enabled.  When tracing IS enabled the query's root span tree is attached
too, so the report carries per-span timings.

Retrieval: ``ds.last_run_report()`` (thread-local on the session, like
``last_execution_stats``) or rendered inside ``explain(verbose=True)``.

The :func:`observe_event` hook is the second feeder: every telemetry
event emitted through ``events.emit_event`` is translated here into the
active report's decision list AND the process metrics registry — one
mapping from event taxonomy to metric catalog, instead of per-site
counter calls drifting apart.
"""

from __future__ import annotations

import contextvars
import time
from typing import Any, Dict, List, Optional

from hyperspace_tpu.telemetry import metrics
from hyperspace_tpu.telemetry.trace import Span


class QueryRunReport:
    """The explain-yourself artifact of one ``collect()``.

    ``decisions`` is an append-only list of dicts, each with a ``kind``:

    ========================  ===============================================
    ``rule``                  one optimizer rule ran: ``rule``, ``applied``,
                              optional ``skipped_reason``
    ``indexes.considered``    ACTIVE entries the optimizer pass loaded
    ``index.used``            a rule rewrote the plan to use ``index``
    ``degraded``              an index was skipped / the query fell back:
                              ``index``, ``reason``
    ``quarantine``            execution-failure containment quarantined
                              files: ``index``, ``files``
    ``replan``                the query re-planned (``mode``:
                              ``containment`` or ``source-fallback``)
    ``io.retry``              a transient IO retry fired
    ``scan``                  one executed scan's IO: ``relation``,
                              ``is_index``, ``files_read``,
                              ``files_listed``, ``bytes_read`` — the
                              measured-bytes feed the advisor's workload
                              capture consumes (advisor/workload.py)
    ========================  ===============================================
    """

    def __init__(self) -> None:
        self.started_at = time.time()
        self.duration_ms = 0.0
        self.outcome = "ok"  # "ok" | "degraded" | "error"
        self.decisions: List[Dict[str, Any]] = []
        self.indexes_considered: List[str] = []
        self.indexes_used: List[str] = []
        self.root_span: Optional[Span] = None

    # -- classification ------------------------------------------------------
    @property
    def degraded(self) -> bool:
        return any(d["kind"] == "degraded" for d in self.decisions)

    def degraded_reasons(self) -> List[str]:
        return [d.get("reason", "") for d in self.decisions
                if d["kind"] == "degraded"]

    def skipped_indexes(self) -> List[str]:
        """Indexes that were considered (or explicitly degraded) but did
        not end up serving the query."""
        named = {d.get("index", "") for d in self.decisions
                 if d["kind"] in ("degraded", "quarantine") and d.get("index")}
        used = set(self.indexes_used)
        return sorted((set(self.indexes_considered) | named) - used)

    def rules(self) -> List[Dict[str, Any]]:
        return [d for d in self.decisions if d["kind"] == "rule"]

    def scans(self) -> List[Dict[str, Any]]:
        """Per-scan IO records of the execution (kind ``scan``)."""
        return [d for d in self.decisions if d["kind"] == "scan"]

    def bytes_read(self, is_index: Optional[bool] = None) -> int:
        """Total bytes the query's scans read — all scans, or only the
        index / only the source side.  A containment/fallback re-plan's
        scans count too: the report describes what the query actually
        cost, and the advisor's capture wants exactly that."""
        return sum(d.get("bytes_read", 0) for d in self.scans()
                   if is_index is None or bool(d.get("is_index")) == is_index)

    def span_timings(self) -> List[Dict[str, Any]]:
        """Flattened (name, duration_ms, status) rows from the attached
        trace, document order — empty when tracing was disabled."""
        if self.root_span is None:
            return []
        return [{"name": s.name, "duration_ms": round(s.duration_ms, 3),
                 "status": s.status} for s in self.root_span.walk()]

    # -- serialization -------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "started_at": self.started_at,
            "duration_ms": round(self.duration_ms, 3),
            "outcome": self.outcome,
            "indexes_considered": list(self.indexes_considered),
            "indexes_used": list(self.indexes_used),
            "indexes_skipped": self.skipped_indexes(),
            "decisions": [dict(d) for d in self.decisions],
            "spans": (self.root_span.to_dict()
                      if self.root_span is not None else None),
        }

    def render(self) -> str:
        """Human-readable report (what explain(verbose=True) embeds)."""
        lines = [f"Query run report: outcome={self.outcome} "
                 f"duration={self.duration_ms:.1f}ms"]
        lines.append(f"  indexes considered: "
                     f"{', '.join(self.indexes_considered) or '(none)'}")
        lines.append(f"  indexes used:       "
                     f"{', '.join(self.indexes_used) or '(none)'}")
        skipped = self.skipped_indexes()
        if skipped:
            lines.append(f"  indexes skipped:    {', '.join(skipped)}")
        for d in self.decisions:
            kind = d["kind"]
            if kind == "rule":
                state = "applied" if d.get("applied") else (
                    f"skipped ({d['skipped_reason']})"
                    if d.get("skipped_reason") else "no match")
                lines.append(f"  rule {d.get('rule')}: {state}")
            elif kind == "degraded":
                lines.append(f"  degraded: index={d.get('index') or '?'} "
                             f"reason={d.get('reason')}")
            elif kind == "quarantine":
                lines.append(f"  quarantine: index={d.get('index')} "
                             f"files={d.get('files')}")
            elif kind == "replan":
                lines.append(f"  re-planned: {d.get('mode')}")
            elif kind == "scan":
                side = "index" if d.get("is_index") else "source"
                lines.append(
                    f"  scan [{side}] {d.get('relation')}: "
                    f"{d.get('files_read')}/{d.get('files_listed')} files, "
                    f"{d.get('bytes_read', 0)} bytes")
        timings = self.span_timings()
        if timings:
            lines.append("  where time went:")
            for row in timings:
                flag = "" if row["status"] == "ok" else f" [{row['status']}]"
                lines.append(f"    {row['name']:<28}"
                             f"{row['duration_ms']:>10.2f} ms{flag}")
        return "\n".join(lines)


_active: "contextvars.ContextVar[Optional[QueryRunReport]]" = \
    contextvars.ContextVar("hyperspace_run_report", default=None)


def start() -> "contextvars.Token":
    """Install a fresh report for the calling context (Dataset.collect);
    pair with :func:`finish`."""
    return _active.set(QueryRunReport())


def finish(token: "contextvars.Token") -> QueryRunReport:
    report = _active.get()
    _active.reset(token)
    assert report is not None
    report.duration_ms = (time.time() - report.started_at) * 1000.0
    if report.outcome == "ok" and report.degraded:
        report.outcome = "degraded"
    return report


def active() -> Optional[QueryRunReport]:
    return _active.get()


def record(kind: str, **data: Any) -> None:
    """Append one decision to the active report (no-op outside a query —
    the cost of that no-op is one contextvar read)."""
    report = _active.get()
    if report is None:
        return
    data["kind"] = kind
    report.decisions.append(data)
    if kind == "indexes.considered":
        for n in data.get("names", ()):
            if n not in report.indexes_considered:
                report.indexes_considered.append(n)
    elif kind == "index.used":
        n = data.get("index", "")
        if n and n not in report.indexes_used:
            report.indexes_used.append(n)


def observe_event(event) -> None:
    """Translate one telemetry event (events.emit_event) into the active
    report and the metrics registry — the single event→metrics mapping."""
    from hyperspace_tpu.telemetry.events import (
        HyperspaceIndexUsageEvent,
        IndexDegradedEvent,
        IndexScrubEvent,
        _IndexActionEvent,
    )

    if isinstance(event, IndexDegradedEvent):
        metrics.inc("degraded.fallbacks")
        record("degraded", index=event.index_name, reason=event.reason)
    elif isinstance(event, HyperspaceIndexUsageEvent):
        for name in event.index_names:
            record("index.used", index=name, message=event.message)
    elif isinstance(event, IndexScrubEvent):
        metrics.inc("scrub.files_checked", event.files_checked)
        metrics.inc("scrub.files_flagged", event.files_flagged)
    elif isinstance(event, _IndexActionEvent):
        if event.state.startswith("CONFLICT_RETRY"):
            metrics.inc("action.conflict.retries")
        elif event.state.startswith("FAILURE"):
            metrics.inc("action.failures")
