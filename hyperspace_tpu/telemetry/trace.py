"""Query-lifecycle tracing: lightweight nested spans over the whole stack.

PRs 1–3 gave queries silent self-healing (transient-IO retries, conflict
rebases, degraded fallback, quarantine containment); this module makes
those decisions *visible*.  A span is one timed region with outcome tags
(``span("exec.scan", files=3)``); spans nest through a ``contextvar`` so
a query's trace is a tree — optimize under collect, rules under optimize,
file reads under the scan — and the finished ROOT span is delivered to
the registered sinks (a collecting sink for tests, a JSONL sink for bench
and production runs, conf ``hyperspace.system.telemetry.trace.sink``).

Cost contract: tracing is OFF by default
(``hyperspace.system.telemetry.tracing.enabled``) and the disabled path
is one module-global bool check returning a shared no-op context manager
— no allocation, no contextvar touch, no clock read.  Instrumentation
sits at file/action/operator granularity, never per row; bench.py's
``telemetry_overhead`` section holds the line on both claims.

Contextvar propagation means worker threads (``utils/parallel_map``) do
NOT inherit the submitting thread's span: their spans are isolated roots,
which keeps the tree race-free without locks.  Root spans emitted from
worker threads still reach the sinks (sinks lock internally).

The XLA profiler seam lives here too (``profiler_trace``, folded in from
``utils/profiling.py``): spans time the engine's decisions; the XLA trace
times the device's execution of them.  One timing subsystem, two zoom
levels.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

_enabled = False  # module-global: the whole disabled-path cost is this bool


class Span:
    """One timed region: name, outcome tags, nested children."""

    __slots__ = ("name", "tags", "children", "status", "error",
                 "start_s", "duration_ms", "_t0")

    def __init__(self, name: str, tags: Dict[str, Any]) -> None:
        self.name = name
        self.tags = tags
        self.children: List["Span"] = []
        self.status = "ok"
        self.error = ""
        self.start_s = 0.0
        self.duration_ms = 0.0
        self._t0 = 0.0

    def set(self, **tags: Any) -> None:
        """Attach/overwrite outcome tags on the live span."""
        self.tags.update(tags)

    def walk(self) -> Iterator["Span"]:
        yield self
        for c in self.children:
            yield from c.walk()

    def find(self, name: str) -> List["Span"]:
        return [s for s in self.walk() if s.name == name]

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "duration_ms": round(self.duration_ms, 3),
            "status": self.status,
        }
        if self.error:
            d["error"] = self.error
        if self.tags:
            d["tags"] = dict(self.tags)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class _NoopSpan:
    """Shared do-nothing span/context-manager: the disabled fast path AND
    the parentless ``current_span()`` answer, so instrumentation can tag
    unconditionally."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: object) -> bool:
        return False

    def set(self, **tags: Any) -> None:
        pass


NOOP_SPAN = _NoopSpan()

_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "hyperspace_span", default=None)


class _SpanCtx:
    """Context manager for one live span: links into the parent via the
    contextvar, times the region, records exception outcomes, and emits
    the root to the sinks on close."""

    __slots__ = ("span", "_token")

    def __init__(self, span: Span) -> None:
        self.span = span
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> Span:
        span = self.span
        parent = _current.get()
        if parent is not None:
            parent.children.append(span)
        self._token = _current.set(span)
        span.start_s = time.time()
        span._t0 = time.perf_counter()
        return span

    def __exit__(self, exc_type, exc, tb) -> bool:
        span = self.span
        span.duration_ms = (time.perf_counter() - span._t0) * 1000.0
        if exc is not None:
            span.status = "error"
            span.error = f"{type(exc).__name__}: {exc}"
        if self._token is not None:
            parent = self._token.old_value
            if parent is contextvars.Token.MISSING:
                parent = None
            _current.reset(self._token)
            if parent is None:
                _deliver(span)
        return False


def span(name: str, **tags: Any):
    """Open a span named ``name`` (``with span("optimize") as s: ...``).
    Disabled tracing returns the shared no-op — the hot-path contract."""
    if not _enabled:
        return NOOP_SPAN
    return _SpanCtx(Span(name, tags))


def current_span():
    """The innermost live span, or the shared no-op when tracing is off /
    no span is open — callers tag without any enabled check."""
    cur = _current.get()
    return cur if cur is not None else NOOP_SPAN


# -- request (trace) context -------------------------------------------------
# The wire-propagated trace identity of the request currently executing on
# this context (interop/server.py sets it on the worker around the job):
# a (trace_id, request_id) pair.  Orthogonal to span nesting — it exists
# even when span tracing is disabled, so the flight recorder
# (telemetry/flight_recorder.py) can correlate records to client-side ids
# without paying the tracing cost, and so Dataset.collect can tell a
# SERVED query (the handler records it) from a local one.
_request_ctx: "contextvars.ContextVar[Optional[Tuple[str, str]]]" = \
    contextvars.ContextVar("hyperspace_request_ctx", default=None)


@contextlib.contextmanager
def request_scope(trace_id: str, request_id: str) -> Iterator[None]:
    """Run the with-block under the given wire trace context."""
    token = _request_ctx.set((trace_id, request_id))
    try:
        yield
    finally:
        _request_ctx.reset(token)


def current_request_context() -> Optional[Tuple[str, str]]:
    """(trace_id, request_id) of the served request this context is
    executing, or None outside the serving path."""
    return _request_ctx.get()


def tracing_enabled() -> bool:
    return _enabled


def enable_tracing() -> None:
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


# -- sinks ------------------------------------------------------------------
class TraceSink:
    def emit(self, root: Span) -> None:
        raise NotImplementedError


class CollectingTraceSink(TraceSink):
    """Buffers finished root spans for assertions (the
    ``CollectingEventLogger`` analog for traces)."""

    def __init__(self) -> None:
        self.spans: List[Span] = []
        self._lock = threading.Lock()

    def emit(self, root: Span) -> None:
        with self._lock:
            self.spans.append(root)

    def find(self, name: str) -> List[Span]:
        with self._lock:
            roots = list(self.spans)
        return [s for r in roots for s in r.find(name)]

    def reset(self) -> None:
        with self._lock:
            self.spans.clear()


class JsonlTraceSink(TraceSink):
    """One JSON object per finished root span, appended to ``path`` — the
    machine-readable artifact bench.py and production runs leave behind
    (conf ``hyperspace.system.telemetry.trace.sink``).

    Bounded by size-based rotation (conf
    ``hyperspace.system.telemetry.trace.maxBytes``; 0 = unbounded): once
    the sink file would grow past ``max_bytes`` it is rotated to
    ``<path>.1`` (replacing the previous rotation) and a fresh file
    starts — a long-lived traced server keeps at most ~2x ``max_bytes``
    of trace on disk instead of growing without limit."""

    def __init__(self, path: str, max_bytes: int = 0) -> None:
        self.path = path
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()

    def emit(self, root: Span) -> None:
        line = json.dumps(root.to_dict(), default=str)
        try:
            with self._lock:
                self._rotate_if_needed(len(line) + 1)
                # hslint: allow[io-seam] user-chosen trace sink, not index data
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
        except OSError:
            pass  # a full disk must never fail the traced query

    def _rotate_if_needed(self, incoming: int) -> None:
        if self.max_bytes <= 0:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return  # no file yet
        if size + incoming <= self.max_bytes:
            return
        try:
            # hslint: allow[io-seam] trace-sink rotation, not index data
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass  # rotation is best-effort; appends keep working


_sinks: List[TraceSink] = []
_sinks_lock = threading.Lock()


def add_sink(sink: TraceSink) -> TraceSink:
    with _sinks_lock:
        _sinks.append(sink)
    return sink


def remove_sink(sink: TraceSink) -> None:
    with _sinks_lock:
        if sink in _sinks:
            _sinks.remove(sink)


def clear_sinks() -> None:
    with _sinks_lock:
        _sinks.clear()


def _deliver(root: Span) -> None:
    with _sinks_lock:
        sinks = list(_sinks)
    for s in sinks:
        try:
            s.emit(root)
        except Exception:  # noqa: BLE001 — a broken sink must never
            pass           # fail the traced query


def configure_from_conf(conf) -> None:
    """Apply the telemetry conf keys (called at session construction and
    per query, so ``conf.set`` after construction still takes effect):
    enables tracing when ``hyperspace.system.telemetry.tracing.enabled``
    is set and installs a JSONL sink for
    ``hyperspace.system.telemetry.trace.sink`` (idempotent per path).
    Conf never force-disables — ``disable_tracing()`` is the explicit
    opt-out, and an enabled-by-conf session would just re-enable."""
    if getattr(conf, "telemetry_tracing_enabled", False):
        enable_tracing()
    path = getattr(conf, "telemetry_trace_sink", "")
    if path:
        max_bytes = int(getattr(conf, "telemetry_trace_max_bytes", 0))
        with _sinks_lock:
            # Check+append under one lock hold: this runs per query, and
            # two concurrent first-queries must not double-install.
            for s in _sinks:
                if isinstance(s, JsonlTraceSink) and s.path == path:
                    s.max_bytes = max_bytes  # conf.set after install wins
                    break
            else:
                _sinks.append(JsonlTraceSink(path, max_bytes=max_bytes))


# -- the XLA zoom level -----------------------------------------------------
@contextlib.contextmanager
def profiler_trace(log_dir: str) -> Iterator[None]:
    """Trace device activity in the with-block into ``log_dir`` (view with
    TensorBoard's profile plugin or Perfetto).  Folded in from
    ``utils/profiling.py`` (which remains as a deprecation alias): spans
    time the engine's decisions, the XLA trace times the kernels.

    >>> with profiler_trace("/tmp/hs-trace"):
    ...     hs.create_index(df, config)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
