"""Fleet telemetry federation: cross-process heartbeats, merged metrics,
and the inputs of the cluster doctor.

Every observability surface before this PR — the metrics registry, the
flight recorder, the timeline, the doctor — is process-local, while the
index tree itself is a SHARED lake-resident artifact.  ROADMAP item 3's
serving fleet ("N processes behaving as one system") is undebuggable
until telemetry crosses process boundaries the same way the operation
log already does.  This module is that crossing, built on the PR 2
:class:`~hyperspace_tpu.io.log_store.LogStore` seam so the same code
works over ``PosixLogStore`` and ``EmulatedObjectStore`` and survives
restarts:

  - **Heartbeat publisher** (:class:`FleetPublisher`): a conf-gated
    daemon thread (``hyperspace.fleet.telemetry.enabled``, default off;
    ``publishIntervalS`` cadence) that writes ONE bounded snapshot per
    process under ``<systemPath>/_hyperspace_fleet``: process identity
    and role (``server``/``daemon``/``client``), a typed metrics
    snapshot, the ``health.status`` grade, the per-device kernel-ms map
    (PR 14's ``exec.device.<id>.kernel_ms`` counters), and the bounded
    tail of INTERESTING flight-recorder records (error/slow — the ones
    tail-retention always keeps) so federated ``slow_queries``/``trace``
    see LIVE processes, not just drained ones.  First publish is a
    ``put_if_absent``; refreshes ride a generation-CAS loop; ancient
    entries (``pruneAfterS``) are garbage-collected.  Publishing is
    fault-quiet (``faults.quiet()``) and never raises: diagnostic IO
    must neither fail the process it describes nor consume an armed
    fault counter aimed at the system under test.
  - **Federation readers**: :func:`fleet_status_table` (one row per
    heartbeat, freshness-graded), :func:`fleet_metrics` (counters merged
    by SUM, gauges kept per-process — a fleet-wide "sum" of
    ``health.status`` means nothing — and fixed-bucket histograms merged
    by bucket-sum with exemplar carry; the fixed ``metrics._BUCKETS``
    scale is what makes cross-process bucket addition exact),
    :func:`render_fleet_prometheus` (the merged text exposition with a
    ``process="<id>"`` label on every series), and
    :func:`find_trace` / :func:`fleet_slow_queries_table` resolving a
    trace id across the local ring, every live snapshot, and the
    persisted diagnostics bundles of drained processes.
  - **Cluster doctor inputs**: :func:`fleet_checks` — stale heartbeat
    (dead/hung process) crit, more-than-one-lifecycle-daemon warn,
    aggregate shed-ratio/SLO burn over the merged counters, and
    cross-process / cross-device kernel-ms skew — consumed by
    ``Hyperspace.doctor(fleet=True)`` and published as the
    ``health.fleet.status`` gauge.

A snapshot is stale past ``staleAfterS`` (default: 2x the publish
interval — how the fleet doctor flags a SIGKILLed process within two
heartbeats) and pruned past ``pruneAfterS``.  See
docs/16-observability.md for the snapshot schema and merge semantics.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

FLEET_DIR = "_hyperspace_fleet"
SNAPSHOT_VERSION = 1
_KEY_PREFIX = "hb-"
# Bounded tail of interesting flight-recorder records per snapshot.
FLEET_RECORDS_MAX = 32
# Device-skew grading floor: below this many attributed kernel ms the
# max/median ratio is start-up noise, not a straggler.
SKEW_FLOOR_MS = 50.0

# -- process identity and role ------------------------------------------------
_ROLE_RANK = {"client": 0, "daemon": 1, "server": 2}
_role = "client"
_identity: Optional[str] = None
_identity_lock = threading.Lock()
# Serving-process state carried in the heartbeat so the front door
# (interop/server.py FleetQueryClient) can map endpoints to rows and
# skip draining servers during their grace window.  QueryServer
# start()/drain() set these.
_serving_address = ""
_serving_draining = False


def process_identity() -> str:
    """Stable per-process identity: ``<host>-<pid>-<start_ms>`` — a
    restart mints a NEW identity, so the old heartbeat goes stale (and
    is later pruned) instead of being silently overwritten."""
    global _identity
    with _identity_lock:
        if _identity is None:
            import platform

            _identity = (f"{platform.node() or 'host'}-{os.getpid()}-"
                         f"{int(time.time() * 1000)}")
        return _identity


def process_role() -> str:
    return _role


def set_process_role(role: str) -> None:
    """Raise this process's published role (``server`` > ``daemon`` >
    ``client``; a serving process that also runs the lifecycle daemon
    reports ``server``).  Lowering is ignored — roles only escalate."""
    global _role
    if _ROLE_RANK.get(role, -1) > _ROLE_RANK.get(_role, 0):
        _role = role


def set_serving_address(address: str) -> None:
    """The ``host:port`` this process serves on, carried in its
    heartbeat so the front door can match fleet rows to endpoints."""
    global _serving_address
    _serving_address = str(address or "")


def set_serving_draining(draining: bool) -> None:
    """Flip the heartbeat's ``draining`` flag — ``QueryServer.drain``
    sets it (and publishes immediately) so the front door stops
    routing here DURING the grace window, not only after the final
    deregister."""
    global _serving_draining
    _serving_draining = bool(draining)


# -- conf accessors -----------------------------------------------------------
def enabled(conf) -> bool:
    return bool(getattr(conf, "fleet_telemetry_enabled", False))


def publish_interval_s(conf) -> float:
    return max(0.05, float(getattr(conf, "fleet_publish_interval_s", 5.0)))


def stale_after_s(conf) -> float:
    """Age past which a heartbeat counts as a dead/hung process.  The
    conf default of 0 derives 2x the publish interval — the acceptance
    contract that a SIGKILLed process flips the fleet doctor to crit
    within two publish intervals."""
    explicit = float(getattr(conf, "fleet_stale_after_s", 0.0))
    return explicit if explicit > 0 else 2.0 * publish_interval_s(conf)


def prune_after_s(conf) -> float:
    return float(getattr(conf, "fleet_prune_after_s", 600.0))


def fleet_root(conf) -> str:
    from hyperspace_tpu.index.path_resolver import PathResolver

    return os.path.join(PathResolver(conf).system_path, FLEET_DIR)


def _store(conf):
    from hyperspace_tpu.telemetry.perf_ledger import store_for

    return store_for(conf, fleet_root(conf))


# -- the snapshot -------------------------------------------------------------
def device_kernel_ms_map(counters: Dict[str, Any]) -> Dict[str, float]:
    """The per-device attributed kernel-ms map out of a counters dict
    (PR 14's ``exec.device.<id>.kernel_ms`` series)."""
    out: Dict[str, float] = {}
    for name, value in counters.items():
        if not name.startswith("exec.device.") \
                or not name.endswith(".kernel_ms"):
            continue
        dev = name[len("exec.device."):-len(".kernel_ms")]
        try:
            out[dev] = float(value)
        except (TypeError, ValueError):
            continue
    return out


def build_snapshot(conf) -> Dict[str, Any]:
    """This process's current fleet snapshot: identity/role, the typed
    metrics snapshot, the health grade, the per-device kernel-ms map,
    and the bounded interesting flight-recorder tail."""
    from hyperspace_tpu.telemetry import alerts, flight_recorder, metrics

    typed = metrics.registry().typed_snapshot()
    interesting = [r for r in flight_recorder.recorder().records()
                   if r.get("reason") != "sample"]
    return {
        "v": SNAPSHOT_VERSION,
        "ts": time.time(),
        "process": process_identity(),
        "host": process_identity().rsplit("-", 2)[0],
        "pid": os.getpid(),
        "role": process_role(),
        "health": typed["gauges"].get("health.status"),
        "address": _serving_address,
        "draining": _serving_draining,
        "metrics": typed,
        "device_kernel_ms": device_kernel_ms_map(typed["counters"]),
        "records": interesting[-FLEET_RECORDS_MAX:],
        # Active SLO alerts (telemetry/alerts.py; [] when the engine is
        # off) — what alerts(fleet=True) and the fleet.alerts doctor
        # check federate with process attribution.
        "alerts": alerts.carried_alerts(conf),
    }


def publish_once(conf) -> bool:
    """Publish (or CAS-refresh) this process's heartbeat and prune
    ancient entries.  Fault-quiet, never raises — an armed fault budget
    aimed at the engine is never consumed by fleet telemetry, and a
    broken store costs a counter, not a query."""
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.trace import span

    if not enabled(conf):
        return False
    try:
        with faults.quiet(), span("fleet.publish") as sp:
            store = _store(conf)
            key = _KEY_PREFIX + process_identity()
            payload = json.dumps(build_snapshot(conf),
                                 default=str).encode("utf-8")
            committed = False
            for _ in range(4):
                # First publish lands via the put_if_absent form
                # (generation 0); refreshes CAS against the generation
                # we just observed — a racing duplicate identity (there
                # is none by construction) would lose cleanly.
                gen = store.generation(key)
                if store.put_if_generation_match(key, payload, gen):
                    committed = True
                    break
            if not committed:
                metrics.inc("fleet.publish.errors")
                return False
            _prune_stale(store, conf)
            metrics.inc("fleet.publishes")
            sp.set(bytes=len(payload))
            return True
    except Exception:  # noqa: BLE001 — fleet telemetry never fails its
        metrics.inc("fleet.publish.errors")  # process
        return False


def _prune_stale(store, conf) -> None:
    """Garbage-collect heartbeats older than ``pruneAfterS`` (long-dead
    processes the doctor already reported).  Unparseable entries are
    left alone — their owner's next CAS refresh replaces them."""
    from hyperspace_tpu.telemetry import metrics

    cutoff = prune_after_s(conf)
    if cutoff <= 0:
        return
    own = _KEY_PREFIX + process_identity()
    now = time.time()
    for key in store.list_keys(_KEY_PREFIX):
        if key == own:
            continue
        try:
            rec = json.loads(store.read(key).decode("utf-8"))
            ts = float(rec.get("ts", 0.0))
        except (FileNotFoundError, ValueError, UnicodeDecodeError,
                TypeError):
            continue
        if now - ts > cutoff:
            store.delete(key)
            metrics.inc("fleet.pruned")


# -- the publisher thread -----------------------------------------------------
class FleetPublisher:
    """One heartbeat thread per session (``publisher_for``); opt-in via
    ``hyperspace.fleet.telemetry.enabled`` like the lifecycle daemon."""

    def __init__(self, session) -> None:
        self.session = session
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FleetPublisher":
        from hyperspace_tpu.exceptions import HyperspaceError

        if not enabled(self.session.conf):
            raise HyperspaceError(
                "Fleet telemetry is opt-in: set "
                "hyperspace.fleet.telemetry.enabled=true (or publish "
                "one snapshot via telemetry.fleet.publish_once)")
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="hs-fleet-publisher", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0,
             deregister: bool = True) -> None:
        """Stop heartbeating; by default also DEREGISTER (delete this
        process's heartbeat key): a planned exit must not read as a
        dead process to the fleet doctor — a SIGKILLed process never
        runs this, which is exactly how it IS flagged."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        if deregister and enabled(self.session.conf):
            deregister_process(self.session.conf)

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop.is_set():
            publish_once(self.session.conf)
            self._stop.wait(publish_interval_s(self.session.conf))


def publisher_for(session) -> FleetPublisher:
    """The session's publisher, created lazily (thread starts only via
    :meth:`FleetPublisher.start`)."""
    p = getattr(session, "_fleet_publisher", None)
    if p is None:
        p = FleetPublisher(session)
        session._fleet_publisher = p
    return p


def maybe_start(session) -> Optional[FleetPublisher]:
    """Start the publisher when the conf gate is on; never raises (a
    fleet-telemetry failure must not break session construction or
    server start)."""
    try:
        if not enabled(session.conf):
            return None
        return publisher_for(session).start()
    except Exception:  # noqa: BLE001 — telemetry never breaks callers
        return None


# -- federation reads ---------------------------------------------------------
def live_snapshots(conf) -> List[Dict[str, Any]]:
    """Every parseable published heartbeat (stale ones included — the
    doctor grades them), with ``key`` and computed ``age_s`` attached.
    Unreadable stores read empty; torn snapshots are skipped."""
    from hyperspace_tpu.io import faults

    out: List[Dict[str, Any]] = []
    now = time.time()
    try:
        with faults.quiet():
            store = _store(conf)
            for key in sorted(store.list_keys(_KEY_PREFIX)):
                try:
                    rec = json.loads(store.read(key).decode("utf-8"))
                except (FileNotFoundError, ValueError,
                        UnicodeDecodeError):
                    continue
                if not isinstance(rec, dict):
                    continue
                rec["key"] = key
                rec["age_s"] = max(0.0, now - float(rec.get("ts", 0.0)
                                                    or 0.0))
                out.append(rec)
    except Exception:  # noqa: BLE001 — an unreadable fleet reads empty
        pass
    return out


def fresh_snapshots(conf) -> List[Dict[str, Any]]:
    cutoff = stale_after_s(conf)
    return [s for s in live_snapshots(conf) if s["age_s"] <= cutoff]


_HEALTH_NAMES = {0: "ok", 1: "warn", 2: "crit"}


def fleet_status_table(conf):
    """One row per published heartbeat — the shape
    ``Hyperspace.fleet_status()`` and the inline ``fleet_status`` interop
    verb serve.  Columns: process, host, pid, role, address (the
    serving ``host:port``, empty for non-servers), status (the
    process's last published ``health.status`` grade, empty before its
    first ``doctor()``), ageSeconds, fresh, draining (the server is in
    its drain grace window — the front door skips it), records
    (interesting flight records carried), snapshotJson."""
    import pyarrow as pa

    snaps = live_snapshots(conf)
    cutoff = stale_after_s(conf)

    def health_name(s) -> str:
        h = s.get("health")
        try:
            return _HEALTH_NAMES.get(int(h), "") if h is not None else ""
        except (TypeError, ValueError):
            return ""

    return pa.table({
        "process": pa.array([str(s.get("process", "")) for s in snaps],
                            type=pa.string()),
        "host": pa.array([str(s.get("host", "")) for s in snaps],
                         type=pa.string()),
        "pid": pa.array([int(s.get("pid", 0) or 0) for s in snaps],
                        type=pa.int64()),
        "role": pa.array([str(s.get("role", "")) for s in snaps],
                         type=pa.string()),
        "address": pa.array([str(s.get("address", "") or "")
                             for s in snaps], type=pa.string()),
        "status": pa.array([health_name(s) for s in snaps],
                           type=pa.string()),
        "ageSeconds": pa.array([round(float(s.get("age_s", 0.0)), 3)
                                for s in snaps], type=pa.float64()),
        "fresh": pa.array([float(s.get("age_s", 0.0)) <= cutoff
                           for s in snaps], type=pa.bool_()),
        "draining": pa.array([bool(s.get("draining", False))
                              for s in snaps], type=pa.bool_()),
        "records": pa.array([len(s.get("records") or [])
                             for s in snaps], type=pa.int64()),
        "snapshotJson": pa.array([json.dumps(s, default=str)
                                  for s in snaps], type=pa.string()),
    })


# -- merge semantics ----------------------------------------------------------
def merge_metrics(snapshots: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-process typed metric snapshots: counters by SUM (they
    only go up, so the fleet total is meaningful), gauges PER-PROCESS
    (``name -> {process: value}`` — summing ``health.status`` across a
    fleet means nothing), histograms by BUCKET-SUM over the shared
    fixed bucket scale, with count/sum summed, min/max folded, mean
    recomputed, and exemplars carried (per bucket, the last process's
    retained trace link wins).  Pure — no IO, unit-testable."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, Dict[str, float]] = {}
    histograms: Dict[str, Dict[str, Any]] = {}
    processes: List[str] = []
    for snap in snapshots:
        proc = str(snap.get("process", ""))
        processes.append(proc)
        typed = snap.get("metrics") or {}
        for name, value in (typed.get("counters") or {}).items():
            try:
                counters[name] = counters.get(name, 0.0) + float(value)
            except (TypeError, ValueError):
                continue
        for name, value in (typed.get("gauges") or {}).items():
            try:
                gauges.setdefault(name, {})[proc] = float(value)
            except (TypeError, ValueError):
                continue
        for name, h in (typed.get("histograms") or {}).items():
            if not isinstance(h, dict):
                continue
            merged = histograms.setdefault(name, {
                "count": 0, "sum": 0.0, "min": None, "max": None,
                "buckets": {}, "exemplars": {}})
            merged["count"] += int(h.get("count", 0) or 0)
            merged["sum"] += float(h.get("sum", 0.0) or 0.0)
            for bound, n in (h.get("buckets") or {}).items():
                b = str(bound)
                merged["buckets"][b] = merged["buckets"].get(b, 0) \
                    + int(n or 0)
            for side, fold in (("min", min), ("max", max)):
                v = h.get(side)
                if v is not None:
                    cur = merged[side]
                    merged[side] = float(v) if cur is None \
                        else fold(cur, float(v))
            for bucket, ex in (h.get("exemplars") or {}).items():
                merged["exemplars"][str(bucket)] = ex
    for merged in histograms.values():
        merged["mean"] = round(merged["sum"] / merged["count"], 6) \
            if merged["count"] else None
    return {"processes": processes, "counters": counters,
            "gauges": gauges, "histograms": histograms}


def _merge_inputs(conf) -> List[Dict[str, Any]]:
    """Fresh published snapshots, with THIS process's entry replaced by
    its live registry (a scrape must see the local process current even
    between heartbeats — or when its publisher is off entirely)."""
    own = process_identity()
    snaps = [s for s in fresh_snapshots(conf)
             if str(s.get("process", "")) != own]
    snaps.append(build_snapshot(conf))
    return snaps


def fleet_metrics(conf) -> Dict[str, Any]:
    """The fleet-merged metrics view over every FRESH heartbeat plus
    this process's live registry — what ``Hyperspace.fleet_metrics()``
    returns (docs/16-observability.md has the merge semantics)."""
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.trace import span

    with span("fleet.merge") as sp:
        snaps = _merge_inputs(conf)
        merged = merge_metrics(snaps)
        metrics.inc("fleet.merges")
        metrics.set_gauge("fleet.processes", len(merged["processes"]))
        sp.set(processes=len(merged["processes"]))
        return merged


def render_fleet_prometheus(conf) -> str:
    """The merged Prometheus text exposition: every process's series
    with a ``process="<id>"`` label (scrapers aggregate; the label is
    what answers "WHICH server is slow").  Served by
    ``MetricsScrapeServer(fleet=True)``."""
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.trace import span

    def prom(name: str) -> str:
        return "hyperspace_" + name.replace(".", "_").replace("-", "_")

    help_for = metrics.help_lookup()
    with span("fleet.merge") as sp:
        snaps = _merge_inputs(conf)
        metrics.inc("fleet.merges")
        metrics.set_gauge("fleet.processes", len(snaps))
        sp.set(processes=len(snaps))
        lines: List[str] = []
        typed_of = {str(s.get("process", "")): (s.get("metrics") or {})
                    for s in snaps}
        headed: set = set()

        def head(name: str, kind: str) -> None:
            if name in headed:
                return
            headed.add(name)
            doc = help_for(name)
            if doc:
                lines.append(f"# HELP {prom(name)} {doc}")
            lines.append(f"# TYPE {prom(name)} {kind}")

        for proc in sorted(typed_of):
            typed = typed_of[proc]
            label = f'process="{proc}"'
            for name, v in sorted((typed.get("counters") or {}).items()):
                head(name, "counter")
                lines.append(f"{prom(name)}{{{label}}} {float(v):g}")
            for name, v in sorted((typed.get("gauges") or {}).items()):
                head(name, "gauge")
                lines.append(f"{prom(name)}{{{label}}} {float(v):g}")
            for name, h in sorted((typed.get("histograms")
                                   or {}).items()):
                if not isinstance(h, dict):
                    continue
                head(name, "histogram")
                cumulative = 0
                buckets = h.get("buckets") or {}
                exemplars = h.get("exemplars") or {}
                for i, bound in enumerate(_bucket_order(buckets)):
                    cumulative += int(buckets.get(bound, 0) or 0)
                    line = (f'{prom(name)}_bucket{{{label},'
                            f'le="{_le(bound)}"}} {cumulative}')
                    ex = exemplars.get(str(i))
                    if isinstance(ex, (list, tuple)) and len(ex) == 2:
                        line += (f' # {{trace_id="{ex[0]}"}} '
                                 f'{float(ex[1]):g}')
                    lines.append(line)
                lines.append(f"{prom(name)}_sum{{{label}}} "
                             f"{float(h.get('sum', 0.0) or 0.0):g}")
                lines.append(f"{prom(name)}_count{{{label}}} "
                             f"{int(h.get('count', 0) or 0)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _bucket_order(buckets: Dict[str, Any]) -> List[str]:
    """JSON round-trips bucket bounds as strings; render them in
    numeric order with ``+Inf`` last."""
    def sort_key(b: str) -> float:
        try:
            return float(b)
        except ValueError:
            return float("inf")

    return sorted(buckets, key=sort_key)


def _le(bound: str) -> str:
    try:
        return f"{float(bound):g}"
    except ValueError:
        return "+Inf"


# -- federated slow queries / trace resolution --------------------------------
def _fleet_records(conf) -> List[Dict[str, Any]]:
    """(record, process) union across the local ring, every published
    snapshot (stale included — a dead process's tail is exactly what an
    operator wants), and persisted diagnostics bundles; deduplicated by
    (trace_id, request_id, ts) since a process's own ring also rides
    its published snapshot."""
    from hyperspace_tpu.telemetry import flight_recorder

    own = process_identity()
    out: List[Dict[str, Any]] = []
    seen: set = set()

    def add(rec: Dict[str, Any], proc: str) -> None:
        key = (rec.get("trace_id"), rec.get("request_id"),
               round(float(rec.get("ts", 0.0) or 0.0), 3))
        if key in seen:
            return
        seen.add(key)
        out.append({**rec, "process": proc})

    for rec in flight_recorder.recorder().records():
        add(rec, own)
    for snap in live_snapshots(conf):
        proc = str(snap.get("process", ""))
        for rec in snap.get("records") or []:
            if isinstance(rec, dict):
                add(rec, proc)
    for bundle in flight_recorder.bundles(conf):
        proc = f"bundle-{bundle.get('pid', '?')}"
        for rec in bundle.get("records") or []:
            if isinstance(rec, dict):
                add(rec, proc)
    out.sort(key=lambda r: float(r.get("ts", 0.0) or 0.0))
    return out


def fleet_slow_queries_table(conf):
    """``slow_queries(fleet=True)``: the federated record union as an
    arrow table — the single-process columns plus ``process``."""
    import pyarrow as pa

    recs = _fleet_records(conf)
    return pa.table({
        "ts": pa.array([float(r.get("ts", 0.0) or 0.0) for r in recs],
                       type=pa.float64()),
        "process": pa.array([str(r.get("process", "")) for r in recs],
                            type=pa.string()),
        "traceId": pa.array([str(r.get("trace_id", "")) for r in recs],
                            type=pa.string()),
        "requestId": pa.array([str(r.get("request_id", ""))
                               for r in recs], type=pa.string()),
        "kind": pa.array([str(r.get("kind", "")) for r in recs],
                         type=pa.string()),
        "outcome": pa.array([str(r.get("outcome", "")) for r in recs],
                            type=pa.string()),
        "latencyMs": pa.array([float(r.get("latency_ms", 0.0) or 0.0)
                               for r in recs], type=pa.float64()),
        "slow": pa.array([bool(r.get("slow")) for r in recs],
                         type=pa.bool_()),
        "reason": pa.array([str(r.get("reason", "")) for r in recs],
                           type=pa.string()),
        "error": pa.array([str(r.get("error", "")) for r in recs],
                          type=pa.string()),
        "recordJson": pa.array([json.dumps(r, default=str)
                                for r in recs], type=pa.string()),
    })


def find_trace(conf, trace_id: str) -> Optional[Dict[str, Any]]:
    """``trace(id, fleet=True)``: resolve ``trace_id`` across the local
    ring first (cheapest), then every published snapshot, then the
    persisted diagnostics bundles; the returned record carries a
    ``process`` field naming where it ran.  None when nowhere."""
    from hyperspace_tpu.telemetry import flight_recorder

    tid = trace_id.lower()
    rec = flight_recorder.recorder().find(tid)
    if rec is not None:
        return {**rec, "process": process_identity()}
    best: Optional[Dict[str, Any]] = None
    for snap in live_snapshots(conf):
        for r in snap.get("records") or []:
            if isinstance(r, dict) and r.get("trace_id") == tid:
                best = {**r, "process": str(snap.get("process", ""))}
    if best is not None:
        return best
    for bundle in flight_recorder.bundles(conf):
        for r in bundle.get("records") or []:
            if isinstance(r, dict) and r.get("trace_id") == tid:
                best = {**r,
                        "process": f"bundle-{bundle.get('pid', '?')}"}
    return best


# -- cluster doctor checks ----------------------------------------------------
def fleet_checks(session) -> List[Any]:
    """The fleet-level doctor checks (``doctor(fleet=True)``), each
    guarded like the local ones — a blind check is a warn, never a
    crash.  The worst of these grades ``health.fleet.status``."""
    from hyperspace_tpu.telemetry.doctor import _guarded

    conf = session.conf
    return [
        _guarded("fleet.heartbeats",
                 lambda: _check_heartbeats(conf)),
        _guarded("fleet.daemons", lambda: _check_daemons(conf)),
        _guarded("fleet.serving", lambda: _check_fleet_serving(conf)),
        _guarded("fleet.skew", lambda: _check_fleet_skew(conf)),
        _guarded("fleet.build_claims",
                 lambda: _check_build_claims(conf)),
        _guarded("fleet.alerts", lambda: _check_fleet_alerts(session)),
    ]


def _check_fleet_alerts(session):
    """A FIRING SLO alert anywhere in the fleet grades the cluster —
    the page the engine already decided to send (telemetry/alerts.py
    owns the grading so the check and the engine cannot drift)."""
    from hyperspace_tpu.telemetry.alerts import fleet_alert_check

    return fleet_alert_check(session)


def _check_heartbeats(conf):
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.doctor import DoctorCheck

    snaps = live_snapshots(conf)
    cutoff = stale_after_s(conf)
    fresh = [s for s in snaps if s["age_s"] <= cutoff]
    stale = {str(s.get("process", "")): round(s["age_s"], 1)
             for s in snaps if s["age_s"] > cutoff}
    metrics.set_gauge("fleet.processes", len(fresh))
    if not snaps:
        return DoctorCheck(
            "fleet.heartbeats", "ok",
            "no fleet heartbeats published (enable "
            "hyperspace.fleet.telemetry.enabled per process)", {})
    if stale:
        return DoctorCheck(
            "fleet.heartbeats", "crit",
            f"{len(stale)}/{len(snaps)} process(es) stale past "
            f"{cutoff:.1f}s — dead or hung; their last published state "
            f"is still readable via fleet_status()",
            {"stale": stale, "fresh": len(fresh)})
    return DoctorCheck(
        "fleet.heartbeats", "ok",
        f"{len(fresh)} process(es) publishing fresh heartbeats",
        {"fresh": len(fresh)})


def _check_daemons(conf):
    from hyperspace_tpu.lifecycle import lease as _lease
    from hyperspace_tpu.telemetry.doctor import DoctorCheck

    fresh = fresh_snapshots(conf)
    daemons = [str(s.get("process", "")) for s in fresh
               if s.get("role") == "daemon"]
    rec = _lease.status(conf)
    if rec is None:
        # No lease record: pre-lease behavior — concurrent maintainers
        # are uncoordinated, flag them.
        if len(daemons) > 1:
            return DoctorCheck(
                "fleet.daemons", "warn",
                f"{len(daemons)} processes report the lifecycle-daemon "
                f"role with no maintenance lease — concurrent "
                f"maintainers waste work rebasing on each other (set "
                f"hyperspace.lifecycle.lease.enabled=true to elect "
                f"one)", {"daemons": daemons})
        return DoctorCheck("fleet.daemons", "ok",
                           f"{len(daemons)} lifecycle daemon(s) in the "
                           f"fleet", {"daemons": daemons})
    holder = str(rec.get("holder", ""))
    epoch = int(rec.get("epoch", 0) or 0)
    live = {str(s.get("process", "")) for s in fresh}
    data = {"holder": holder, "epoch": epoch,
            "lease_fresh": bool(rec.get("fresh")), "daemons": daemons}
    if rec.get("fresh"):
        if not live:
            # Nobody heartbeats (fleet telemetry off or all clients):
            # the lease alone proves single-execution; nothing to
            # cross-check against.
            return DoctorCheck(
                "fleet.daemons", "ok",
                f"maintenance lease epoch {epoch} held by {holder}; no "
                f"fleet heartbeats to cross-check", data)
        if holder in live:
            standbys = max(0, len(daemons) - 1)
            return DoctorCheck(
                "fleet.daemons", "ok",
                f"maintenance lease epoch {epoch} held by live process "
                f"{holder} ({standbys} standby daemon(s))", data)
        return DoctorCheck(
            "fleet.daemons", "crit",
            f"maintenance lease epoch {epoch} held by {holder}, which "
            f"publishes no live heartbeat — the holder died holding "
            f"the lease; takeover happens when it expires "
            f"(ttl {_lease.ttl_s(conf):.0f}s)", data)
    if daemons:
        return DoctorCheck(
            "fleet.daemons", "warn",
            f"maintenance lease epoch {epoch} expired with "
            f"{len(daemons)} candidate daemon(s) — takeover pending "
            f"next poll", data)
    return DoctorCheck(
        "fleet.daemons", "ok",
        f"maintenance lease epoch {epoch} expired and no daemons "
        f"running", data)


def _check_fleet_serving(conf):
    from hyperspace_tpu.telemetry.doctor import DoctorCheck, _slo_burn

    merged = merge_metrics(fresh_snapshots(conf))
    requests = float(merged["counters"].get("serve.requests", 0.0))
    shed = float(merged["counters"].get("serve.shed", 0.0))
    if requests <= 0:
        return DoctorCheck("fleet.serving", "ok",
                           "no served traffic across the fleet", {})
    shed_ratio = shed / requests
    warn_ratio = float(getattr(conf, "doctor_shed_warn_ratio", 0.05))
    slo_ms = float(getattr(conf, "doctor_latency_slo_ms", 1000.0))
    burn = _slo_burn(merged["histograms"].get("serve.latency_ms"),
                     slo_ms)
    data = {"requests": int(requests),
            "shed_ratio": round(shed_ratio, 4),
            "slo_ms": slo_ms, "slo_burn": round(burn, 4),
            "processes": len(merged["processes"])}
    if (warn_ratio > 0 and shed_ratio >= 5 * warn_ratio) or burn >= 0.5:
        return DoctorCheck(
            "fleet.serving", "crit",
            f"fleet overloaded: aggregate shed ratio {shed_ratio:.2f}, "
            f"SLO burn {burn:.2f}", data)
    if (warn_ratio > 0 and shed_ratio >= warn_ratio) or burn >= 0.1:
        return DoctorCheck(
            "fleet.serving", "warn",
            f"aggregate shed ratio {shed_ratio:.2f}, SLO burn "
            f"{burn:.2f}", data)
    return DoctorCheck(
        "fleet.serving", "ok",
        f"{int(requests)} requests fleet-wide, shed ratio "
        f"{shed_ratio:.2f}, SLO burn {burn:.2f}", data)


def skew_ratio(values: List[float]) -> float:
    """max/median over attributed kernel-ms totals — the straggler
    grade, 0.0 when there is nothing meaningful to compare (fewer than
    two lanes, or totals under the noise floor)."""
    import statistics

    vals = [float(v) for v in values if v is not None]
    if len(vals) < 2:
        return 0.0
    med = statistics.median(vals)
    mx = max(vals)
    if med <= 0 or mx - med < SKEW_FLOOR_MS:
        return 0.0
    return mx / med


def _check_fleet_skew(conf):
    from hyperspace_tpu.telemetry.doctor import DoctorCheck

    warn_at = float(getattr(conf, "doctor_device_skew_warn", 4.0))
    per_process: Dict[str, float] = {}
    per_device: Dict[str, float] = {}
    for snap in fresh_snapshots(conf):
        proc = str(snap.get("process", ""))
        dev_map = snap.get("device_kernel_ms") or {}
        total = 0.0
        for dev, ms in dev_map.items():
            try:
                ms = float(ms)
            except (TypeError, ValueError):
                continue
            total += ms
            per_device[str(dev)] = per_device.get(str(dev), 0.0) + ms
        if total > 0:
            per_process[proc] = total
    proc_ratio = skew_ratio(list(per_process.values()))
    dev_ratio = skew_ratio(list(per_device.values()))
    data = {"per_process_ms": {k: round(v, 1)
                               for k, v in per_process.items()},
            "per_device_ms": {k: round(v, 1)
                              for k, v in per_device.items()},
            "process_ratio": round(proc_ratio, 2),
            "device_ratio": round(dev_ratio, 2)}
    if warn_at > 0 and (proc_ratio >= warn_at or dev_ratio >= warn_at):
        which = "process" if proc_ratio >= warn_at else "device"
        return DoctorCheck(
            "fleet.skew", "warn",
            f"kernel-ms skew across the fleet: max/median per-{which} "
            f"ratio {max(proc_ratio, dev_ratio):.1f} >= {warn_at:g} — "
            f"a straggler {which}", data)
    return DoctorCheck("fleet.skew", "ok",
                       "no cross-process or cross-device kernel-ms "
                       "skew", data)


def _check_build_claims(conf):
    """Leftover multi-host build claims (parallel/multihost_build.py)
    graded against the heartbeats (docs/21): an EXPIRED claim with no
    live holder is routine crash debris — any claimant reclaims it and
    the next build reaps the dead coordinator's scratch (warn); a FRESH
    claim whose holder publishes no fresh heartbeat is a dead or hung
    host still fencing the item — the build stalls until the claim TTL
    runs out (crit).  Read-only like every fleet check (the doctor verb
    serves inline while the admission queue sheds, so no store writes
    here); the JOURNALED trail comes from the claim protocol itself —
    the coordinator records every expired-claim sighting and WorkClaims
    records every reclaim/fence, so post-mortems see what doctor saw."""
    from hyperspace_tpu.parallel.multihost_build import scan_build_claims
    from hyperspace_tpu.telemetry.doctor import DoctorCheck

    claims = scan_build_claims(conf)
    if not claims:
        return DoctorCheck("fleet.build_claims", "ok",
                           "no leftover multi-host build claims", {})
    fresh = {str(s.get("process", "")) for s in fresh_snapshots(conf)}
    now = time.time()
    expired_orphans, fresh_dead = [], []
    for rec in claims:
        live = str(rec.get("holder", "")) in fresh
        if float(rec.get("expires_at", 0.0)) < now:
            if not live:
                expired_orphans.append(rec)
        elif fresh and not live:
            # Only gradeable when SOMEBODY heartbeats: with fleet
            # telemetry off there is nothing to cross-check a live
            # claim against, like fleet.daemons' lease-only case.
            fresh_dead.append(rec)

    def brief(recs):
        return [{"build": r.get("build_id"), "item": r.get("item"),
                 "holder": r.get("holder")} for r in recs]

    data = {"pending": len(claims),
            "expired_no_heartbeat": brief(expired_orphans),
            "fresh_dead_holder": brief(fresh_dead)}
    if fresh_dead:
        check = DoctorCheck(
            "fleet.build_claims", "crit",
            f"{len(fresh_dead)} fresh build claim(s) held by "
            f"process(es) with no fresh heartbeat — a dead or hung "
            f"host is fencing work; the build stalls until the claim "
            f"TTL expires", data)
    elif expired_orphans:
        check = DoctorCheck(
            "fleet.build_claims", "warn",
            f"{len(expired_orphans)} expired build claim(s) with no "
            f"live holder — crash debris; survivors (or the next "
            f"build) reclaim them after the TTL", data)
    else:
        check = DoctorCheck(
            "fleet.build_claims", "ok",
            f"{len(claims)} in-flight build claim(s), every holder "
            f"heartbeating", data)
    return check


def deregister_process(conf) -> None:
    """Remove this process's heartbeat (graceful exit); fault-quiet,
    never raises."""
    from hyperspace_tpu.io import faults

    try:
        with faults.quiet():
            _store(conf).delete(_KEY_PREFIX + process_identity())
    except Exception:  # noqa: BLE001 — best-effort cleanup
        pass


def clear(conf) -> None:
    """Wipe published heartbeats (tests)."""
    from hyperspace_tpu.io import faults

    with faults.quiet():
        store = _store(conf)
        for key in store.list_keys():
            store.delete(key)
