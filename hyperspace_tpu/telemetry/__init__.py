from hyperspace_tpu.telemetry.events import (
    AppInfo,
    HyperspaceEvent,
    CreateActionEvent,
    DeleteActionEvent,
    RestoreActionEvent,
    VacuumActionEvent,
    CancelActionEvent,
    RefreshActionEvent,
    OptimizeActionEvent,
    HyperspaceIndexUsageEvent,
    EventLogger,
    NoOpEventLogger,
    CollectingEventLogger,
    emit_event,
    get_event_logger,
    set_event_logger,
)
from hyperspace_tpu.telemetry.build_report import (
    BuildReport,
)
from hyperspace_tpu.telemetry.metrics import (
    MetricsRegistry,
)
from hyperspace_tpu.telemetry.report import (
    QueryRunReport,
)
from hyperspace_tpu.telemetry.trace import (
    CollectingTraceSink,
    JsonlTraceSink,
    Span,
    TraceSink,
    current_span,
    disable_tracing,
    enable_tracing,
    profiler_trace,
    span,
    tracing_enabled,
)
