"""Process-wide metrics registry: named counters, gauges, and histograms.

The operational counterpart of the per-query trace (telemetry/trace.py):
where a trace explains ONE query, the registry aggregates ACROSS queries
and actions — how many transient-IO retries fired this process, how many
CAS conflicts the op-log absorbed, how often queries degraded to the
source scan.  The shape follows the Prometheus client-library contract
(counters only go up, gauges are set, histograms bucket observations)
without the dependency: a snapshot dict for programmatic consumers
(``Hyperspace.metrics()``) and a text exposition dump for scraping or a
log line (``render_prometheus``).

Design constraints, in order:

  - **lock-safe**: instrumentation points run on executor worker threads,
    interop server threads, and the user's thread concurrently; every
    mutation takes the registry lock (one uncontended lock acquire per
    increment — far below the cost of the file-level IO operations the
    instrumented sites perform).
  - **bounded**: metric names come from a fixed catalog in code
    (docs/16-observability.md), never from user data, and the registry
    enforces a hard cap anyway so a buggy caller interpolating paths
    into names cannot grow it without bound.  Histograms keep fixed
    log-scale buckets plus count/sum/min/max — O(1) per observation,
    O(buckets) memory.
  - **resettable**: ``reset()`` zeroes everything (tests; a bench section
    isolating its own deltas).

Disabled-cost note: there is no enable switch — an increment is a dict
update under a lock, cheap enough to leave always-on at the file/action
granularity the engine instruments (never per row).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

# Hard cap on distinct metric names: the in-code catalog is ~dozens; hitting
# this means a caller is interpolating unbounded data into names.
_MAX_SERIES = 4096

# Histogram bucket upper bounds (milliseconds-oriented log scale; also fine
# for counts).  Fixed for every histogram: cross-metric comparability beats
# per-metric tuning here, and the bound keeps memory O(1).
_BUCKETS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
            1000.0, 2500.0, 5000.0, 10000.0, float("inf"))


class _Histogram:
    __slots__ = ("count", "sum", "min", "max", "buckets", "exemplars")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.buckets = [0] * len(_BUCKETS)
        # Per-bucket exemplar: (trace_id, value) of the most recent
        # RETAINED observation landing in that bucket — the link from a
        # p99 bucket to a flight-recorder trace id (docs/16).
        self.exemplars: Dict[int, tuple] = {}

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        for i, bound in enumerate(_BUCKETS):
            if value <= bound:
                self.buckets[i] += 1
                if exemplar:
                    self.exemplars[i] = (exemplar, value)
                break

    def snapshot(self) -> Dict[str, object]:
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": self.min,
            "max": self.max,
            "mean": round(self.sum / self.count, 6) if self.count else None,
            "buckets": {("+Inf" if b == float("inf") else b): n
                        for b, n in zip(_BUCKETS, self.buckets)},
        }


class MetricsRegistry:
    """Thread-safe registry of counters, gauges, and histograms."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, _Histogram] = {}

    def _room(self) -> bool:
        return (len(self._counters) + len(self._gauges)
                + len(self._histograms)) < _MAX_SERIES

    def inc(self, name: str, value: float = 1.0) -> None:
        """Increment counter ``name`` (created at 0 on first use)."""
        with self._lock:
            if name in self._counters or self._room():
                self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            if name in self._gauges or self._room():
                self._gauges[name] = float(value)

    def observe(self, name: str, value: float,
                exemplar: Optional[str] = None) -> None:
        """Record one observation into histogram ``name``.  ``exemplar``
        (a flight-recorder trace id) is remembered per bucket and
        rendered in the text exposition, linking a latency bucket to the
        retained trace that landed there."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                if not self._room():
                    return
                h = self._histograms[name] = _Histogram()
            h.observe(float(value), exemplar)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()

    def typed_snapshot(self) -> Dict[str, Dict[str, object]]:
        """Point-in-time snapshot SPLIT by series kind — the shape fleet
        federation needs: merging counters by sum and gauges per-process
        (telemetry/fleet.py) is only possible when the reader can tell
        them apart, which the flat :meth:`snapshot` cannot.  Histogram
        dicts additionally carry ``exemplars`` (bucket index →
        ``(trace_id, value)``) so the merged fleet exposition keeps its
        p99→trace links."""
        with self._lock:
            out: Dict[str, Dict[str, object]] = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {},
            }
            for name, h in self._histograms.items():
                snap = h.snapshot()
                snap["exemplars"] = {str(i): list(ex)
                                     for i, ex in h.exemplars.items()}
                out["histograms"][name] = snap
            return out

    def snapshot(self) -> Dict[str, object]:
        """Point-in-time dict of every series, plus the derived ratios the
        catalog promises (``cache.device.hit_ratio``)."""
        with self._lock:
            out: Dict[str, object] = {}
            out.update(sorted(self._counters.items()))
            out.update(sorted(self._gauges.items()))
            for name, h in sorted(self._histograms.items()):
                out[name] = h.snapshot()
            hits = self._counters.get("cache.device.hits", 0.0)
            misses = self._counters.get("cache.device.misses", 0.0)
            if hits + misses > 0:
                out["cache.device.hit_ratio"] = round(
                    hits / (hits + misses), 4)
            return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (names dotted→underscored, histograms
        as ``_bucket``/``_sum``/``_count`` series with ``le`` labels).
        ``# HELP`` lines come from the docs/16 metric catalog — parsed by
        the lint registry (the same single source the telemetry-catalog
        rule enforces), so the exposition and the docs cannot drift.
        Histogram buckets carry OpenMetrics-style exemplars linking them
        to retained flight-recorder trace ids."""
        def prom(name: str) -> str:
            return "hyperspace_" + name.replace(".", "_").replace("-", "_")

        help_for = _catalog_help()
        lines: List[str] = []

        def head(name: str, kind: str) -> None:
            doc = help_for(name)
            if doc:
                lines.append(f"# HELP {prom(name)} {doc}")
            lines.append(f"# TYPE {prom(name)} {kind}")

        with self._lock:
            for name, v in sorted(self._counters.items()):
                head(name, "counter")
                lines.append(f"{prom(name)} {v:g}")
            for name, v in sorted(self._gauges.items()):
                head(name, "gauge")
                lines.append(f"{prom(name)} {v:g}")
            for name, h in sorted(self._histograms.items()):
                head(name, "histogram")
                cumulative = 0
                for i, (bound, n) in enumerate(zip(_BUCKETS, h.buckets)):
                    cumulative += n
                    le = "+Inf" if bound == float("inf") else f"{bound:g}"
                    line = f'{prom(name)}_bucket{{le="{le}"}} {cumulative}'
                    ex = h.exemplars.get(i)
                    if ex is not None:
                        line += (f' # {{trace_id="{ex[0]}"}} '
                                 f'{ex[1]:g}')
                    lines.append(line)
                lines.append(f"{prom(name)}_sum {h.sum:g}")
                lines.append(f"{prom(name)}_count {h.count}")
        return "\n".join(lines) + ("\n" if lines else "")


# Lazily loaded docs/16 catalog help (name-pattern -> text), shared by
# every render.  The lint parser reads the checked-out docs; an installed
# package without docs/ renders without HELP lines, never fails.
_HELP_ENTRIES = None


def _catalog_help():
    """A ``name -> help-or-None`` lookup over the docs/16 metric catalog
    (placeholder rows like ``rule.<slug>.applied`` match concrete
    names)."""
    global _HELP_ENTRIES
    if _HELP_ENTRIES is None:
        try:
            from hyperspace_tpu.lint.catalog import metric_help_entries

            _HELP_ENTRIES = metric_help_entries()
        except Exception:  # noqa: BLE001 — docs absent: no HELP lines
            _HELP_ENTRIES = []

    def lookup(name: str) -> Optional[str]:
        try:
            from hyperspace_tpu.lint.catalog import name_matches_entry

            for entry, doc in _HELP_ENTRIES:
                if name_matches_entry(name, entry):
                    return doc
        except Exception:  # noqa: BLE001
            pass
        return None

    return lookup


def help_lookup():
    """Public handle on the docs/16 HELP lookup (the fleet exposition in
    telemetry/fleet.py renders the same catalog text per process)."""
    return _catalog_help()


# One registry per process: the subsystems it observes (device cache, IO
# pool, op-log stores) are process-level resources themselves.
_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def inc(name: str, value: float = 1.0) -> None:
    _REGISTRY.inc(name, value)


def set_gauge(name: str, value: float) -> None:
    _REGISTRY.set_gauge(name, value)


def observe(name: str, value: float, exemplar: Optional[str] = None) -> None:
    _REGISTRY.observe(name, value, exemplar)


def snapshot() -> Dict[str, object]:
    return _REGISTRY.snapshot()


def reset() -> None:
    _REGISTRY.reset()
