"""The persistent perf ledger: a durable, diffable record of every run.

BENCH_r05 (rc=124, headline lost) showed the perf trajectory living only
in the mind of whoever read the last bench log.  This module gives every
action — and every bench section — a compact structured record appended
through the PR 2 :class:`~hyperspace_tpu.io.log_store.LogStore` seam
under ``<systemPath>/_hyperspace_perf``, so the same code works over
:class:`PosixLogStore` and :class:`EmulatedObjectStore`, survives
restarts, and is readable by ``Hyperspace.perf_history()`` / the interop
``perf_history`` verb / ``bench.py --compare auto``.

Record shape (one flat JSON object per key):

  - ``kind``: ``"action"`` or ``"bench"``
  - ``name``: action class + index, or bench section name
  - ``ts`` / ``wall_s`` / ``outcome``
  - ``phases_s`` + the byte counters (action records: the BuildReport
    serialization; bench records: the section's scalar metrics)
  - ``fingerprint``: host, platform, jax/pyarrow versions, and the
    build-relevant conf knobs — so a diff across records can tell a real
    regression from a changed environment.

Keys are ``r-<epoch_ms>-<pid>-<seq>`` — they sort chronologically and
``put_if_absent`` arbitrates collisions.  The ledger is bounded
(``hyperspace.system.perf.ledger.maxEntries``): appends beyond the cap
delete the oldest records.

Cost/safety contract: appends run inside ``faults.quiet()`` (diagnostic
IO must never consume an injected-fault budget aimed at the system under
test) and NEVER raise — a ledger failure must not cost an action its
commit.  ``hyperspace.system.perf.ledger.enabled`` (default on) turns
the whole thing off.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

PERF_DIR = "_hyperspace_perf"
RECORD_VERSION = 1

_seq_lock = threading.Lock()
_seq = 0


def perf_root(conf) -> str:
    from hyperspace_tpu.index.path_resolver import PathResolver

    return os.path.join(PathResolver(conf).system_path, PERF_DIR)


def store_for(conf, root: Optional[str] = None):
    """The ledger store: backend class from
    ``hyperspace.index.logStoreClass`` (the workload/quarantine managers'
    exact construction), rooted at the perf dir."""
    from hyperspace_tpu.exceptions import HyperspaceError
    from hyperspace_tpu.io.log_store import LogStore
    from hyperspace_tpu.utils.reflection import load_class

    cls = load_class(conf.log_store_class, LogStore, HyperspaceError)
    return cls(root if root is not None else perf_root(conf),
               stale_list_s=float(getattr(
                   conf, "object_store_stale_list_ms", 0.0)) / 1000.0)


def enabled(conf) -> bool:
    return bool(getattr(conf, "perf_ledger_enabled", True))


def fingerprint(conf) -> Dict[str, Any]:
    """Environment + build-relevant conf, for diffing runs apples to
    apples.  Never raises; missing pieces are simply absent."""
    fp: Dict[str, Any] = {}
    try:
        import platform
        import sys

        fp["host"] = platform.node()
        fp["python"] = platform.python_version()
        jax = sys.modules.get("jax")
        if jax is not None:
            fp["jax"] = getattr(jax, "__version__", "")
            try:
                fp["platform"] = jax.devices()[0].platform
            except Exception:  # noqa: BLE001 — backend probe can fail
                pass
        import pyarrow

        fp["pyarrow"] = pyarrow.__version__
    except Exception:  # noqa: BLE001
        pass
    for knob in ("num_buckets", "device_batch_rows", "parallel_build",
                 "index_file_compression", "index_max_rows_per_file"):
        try:
            fp[knob] = getattr(conf, knob)
        except Exception:  # noqa: BLE001
            pass
    return fp


def _next_key() -> str:
    global _seq
    with _seq_lock:
        _seq += 1
        seq = _seq
    return f"r-{int(time.time() * 1000):013d}-{os.getpid()}-{seq:05d}"


def append(conf, record: Dict[str, Any]) -> Optional[str]:
    """Append one record; returns its key, or None when disabled/failed.
    Never raises (see module docstring); InjectedCrash cannot originate
    here — the whole append runs fault-quiet."""
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.telemetry import metrics

    if not enabled(conf):
        return None
    try:
        with faults.quiet():
            store = store_for(conf)
            rec = {"v": RECORD_VERSION, "ts": time.time(), **record}
            payload = json.dumps(rec, default=str).encode("utf-8")
            key = None
            for _ in range(4):
                key = _next_key()
                if store.put_if_absent(key, payload):
                    break
            else:
                metrics.inc("perf.ledger.errors")
                return None
            cap = int(getattr(conf, "perf_ledger_max_entries", 2048))
            if cap > 0:
                keys = store.list_keys()
                if len(keys) > cap:
                    for old in sorted(keys)[:len(keys) - cap]:
                        store.delete(old)
            metrics.inc("perf.ledger.appends")
            return key
    except Exception:  # noqa: BLE001 — diagnostic IO never fails callers
        metrics.inc("perf.ledger.errors")
        return None


def records(conf, root: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every parseable ledger record, oldest first.  Torn/unparseable
    records are skipped — the ledger is advisory data."""
    from hyperspace_tpu.io import faults

    out: List[Dict[str, Any]] = []
    try:
        with faults.quiet():
            store = store_for(conf, root)
            for key in sorted(store.list_keys()):
                try:
                    rec = json.loads(store.read(key).decode("utf-8"))
                except (FileNotFoundError, ValueError, UnicodeDecodeError):
                    continue
                if not isinstance(rec, dict):
                    continue
                rec["key"] = key
                out.append(rec)
    except Exception:  # noqa: BLE001 — an unreadable ledger reads empty
        pass
    return out


def filtered_records(conf, root: Optional[str] = None,
                     index: Optional[str] = None,
                     section: Optional[str] = None,
                     limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Ledger records with the ``perf_history`` ergonomics filters
    applied: ``index`` keeps action records for that index (the
    ``Action(index)`` naming or the serialized ``index`` field),
    ``section`` keeps bench records for that section name, ``limit``
    keeps the most recent N after filtering."""
    out = records(conf, root)
    if index:
        out = [r for r in out
               if r.get("index") == index
               or str(r.get("name", "")).endswith(f"({index})")]
    if section:
        out = [r for r in out
               if r.get("kind") == "bench"
               and r.get("name") == section]
    if limit is not None and limit >= 0:
        out = out[-int(limit):] if limit else []
    return out


def history_table(conf, root: Optional[str] = None,
                  index: Optional[str] = None,
                  section: Optional[str] = None,
                  limit: Optional[int] = None):
    """The ledger as an arrow table (one row per record) — the shape
    ``Hyperspace.perf_history()`` and the interop ``perf_history`` verb
    return, both of which pass the ``index``/``section``/``limit``
    filters straight through (callers used to re-filter raw records by
    hand).  Structured sub-objects ride as JSON strings so the schema
    stays flat and stable."""
    import pyarrow as pa

    rows = {"key": [], "kind": [], "name": [], "ts": [], "wallSeconds": [],
            "outcome": [], "phasesJson": [], "bytesWritten": [],
            "spillBytes": [], "recordJson": []}
    for rec in filtered_records(conf, root, index=index, section=section,
                                limit=limit):
        rows["key"].append(rec.get("key", ""))
        rows["kind"].append(str(rec.get("kind", "")))
        rows["name"].append(str(rec.get("name", "")))
        rows["ts"].append(float(rec.get("ts", 0.0)))
        rows["wallSeconds"].append(float(rec.get("wall_s", 0.0) or 0.0))
        rows["outcome"].append(str(rec.get("outcome", "")))
        rows["phasesJson"].append(json.dumps(rec.get("phases_s", {})))
        rows["bytesWritten"].append(int(rec.get("bytes_written", 0) or 0))
        rows["spillBytes"].append(int(rec.get("spill_bytes", 0) or 0))
        rows["recordJson"].append(json.dumps(rec, default=str))
    return pa.table({
        "key": pa.array(rows["key"], type=pa.string()),
        "kind": pa.array(rows["kind"], type=pa.string()),
        "name": pa.array(rows["name"], type=pa.string()),
        "ts": pa.array(rows["ts"], type=pa.float64()),
        "wallSeconds": pa.array(rows["wallSeconds"], type=pa.float64()),
        "outcome": pa.array(rows["outcome"], type=pa.string()),
        "phasesJson": pa.array(rows["phasesJson"], type=pa.string()),
        "bytesWritten": pa.array(rows["bytesWritten"], type=pa.int64()),
        "spillBytes": pa.array(rows["spillBytes"], type=pa.int64()),
        "recordJson": pa.array(rows["recordJson"], type=pa.string()),
    })


def clear(conf) -> None:
    """Wipe the ledger (tests)."""
    from hyperspace_tpu.io import faults

    with faults.quiet():
        store = store_for(conf)
        for key in store.list_keys():
            store.delete(key)
