"""Structured telemetry events, one per lifecycle action and per rule
application.

Reference contract: telemetry/HyperspaceEvent.scala:28-156 (event hierarchy:
AppInfo, CRUD events with index name + message, HyperspaceIndexUsageEvent
carrying the rewritten plan) and telemetry/HyperspaceEventLogging.scala:30-68
(pluggable logger, default no-op).  Instead of reflective class loading we
take a logger instance; ``CollectingEventLogger`` is the test double
(TestUtils.scala:93-109's MockEventLogger analog).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class AppInfo:
    """Originating app info (HyperspaceEvent.scala:28-34)."""

    sparkUser: str = ""
    appId: str = ""
    appName: str = "hyperspace_tpu"


@dataclasses.dataclass
class HyperspaceEvent:
    app_info: AppInfo = dataclasses.field(default_factory=AppInfo)
    timestamp_ms: int = dataclasses.field(default_factory=lambda: int(time.time() * 1000))
    message: str = ""

    @property
    def kind(self) -> str:
        return type(self).__name__


@dataclasses.dataclass
class _IndexActionEvent(HyperspaceEvent):
    index_name: str = ""
    state: str = ""  # "" while running, final state or "FAILURE: ..." at end


class CreateActionEvent(_IndexActionEvent):
    pass


class DeleteActionEvent(_IndexActionEvent):
    pass


class RestoreActionEvent(_IndexActionEvent):
    pass


class VacuumActionEvent(_IndexActionEvent):
    pass


class CancelActionEvent(_IndexActionEvent):
    pass


class RefreshActionEvent(_IndexActionEvent):
    pass


class OptimizeActionEvent(_IndexActionEvent):
    pass


@dataclasses.dataclass
class IndexDegradedEvent(HyperspaceEvent):
    """An index was SKIPPED at query time because its operation log is
    unreadable, torn past recovery, or the backing store is erroring —
    the query fell back to the source scan instead of raising
    (``hyperspace.system.degraded.fallbackToSource``).  The Hyperspace
    contract: a broken index may stop accelerating a query, never break
    it."""

    index_name: str = ""
    reason: str = ""


@dataclasses.dataclass
class IndexScrubEvent(HyperspaceEvent):
    """One ``verify_index`` pass over an index's data files
    (actions/verify.py): how many files were checked in which mode
    (``quick`` = stat-level, ``full`` = re-read + re-hash) and how many
    were flagged (and quarantined).  ``flagged == 0`` is the healthy
    heartbeat a scrub cron watches for."""

    index_name: str = ""
    mode: str = ""
    files_checked: int = 0
    files_flagged: int = 0


@dataclasses.dataclass
class HyperspaceIndexUsageEvent(HyperspaceEvent):
    """Emitted when a rule rewrites a query to use indexes
    (HyperspaceEvent.scala:150-156)."""

    index_names: List[str] = dataclasses.field(default_factory=list)
    plan_before: str = ""
    plan_after: str = ""


class EventLogger:
    def log_event(self, event: HyperspaceEvent) -> None:
        raise NotImplementedError


class NoOpEventLogger(EventLogger):
    def log_event(self, event: HyperspaceEvent) -> None:
        pass


class CollectingEventLogger(EventLogger):
    """Buffers events for assertions (MockEventLogger analog)."""

    def __init__(self) -> None:
        self.events: List[HyperspaceEvent] = []

    def log_event(self, event: HyperspaceEvent) -> None:
        self.events.append(event)

    def reset(self) -> None:
        self.events.clear()


_logger: EventLogger = NoOpEventLogger()
_logger_explicit = False  # set_event_logger installed a logger
_conf_applied = False     # a conf key already resolved a logger


def get_event_logger() -> EventLogger:
    return _logger


def emit_event(event: HyperspaceEvent) -> None:
    """The canonical emission path: hand ``event`` to the installed logger
    AND to the observability layer (telemetry/report.py), which folds it
    into the active query's run report and the process metrics registry.
    Sites call this instead of ``get_event_logger().log_event`` so the
    event taxonomy feeds metrics from exactly one mapping."""
    _logger.log_event(event)
    from hyperspace_tpu.telemetry import report

    report.observe_event(event)


def set_event_logger(logger: Optional[EventLogger]) -> None:
    """Install a logger programmatically — this wins over the conf key;
    passing ``NoOpEventLogger()`` is an explicit opt-out.  ``None`` resets
    to the default state (conf resolution applies again)."""
    global _logger, _logger_explicit, _conf_applied
    if logger is None:
        _logger = NoOpEventLogger()
        _logger_explicit = False
        _conf_applied = False
    else:
        _logger = logger
        _logger_explicit = True


# Named registry + dotted-path loading (the reflective
# spark.hyperspace.eventLoggerClass conf, HyperspaceEventLogging.scala:42-64).
LOGGER_REGISTRY: Dict[str, type] = {
    "": NoOpEventLogger,
    "NoOpEventLogger": NoOpEventLogger,
    "CollectingEventLogger": CollectingEventLogger,
}


def resolve_event_logger(name: str) -> EventLogger:
    """Instantiate a logger by registered name or ``module:Class`` /
    ``module.Class`` dotted path.  Raises ValueError (with context) for
    anything that does not resolve to an EventLogger subclass."""
    cls = LOGGER_REGISTRY.get(name)
    if cls is None:
        from hyperspace_tpu.utils.reflection import load_class

        try:
            cls = load_class(name, EventLogger, ValueError)
        except ValueError as e:
            raise ValueError(f"Unknown event logger: {name!r} ({e})") from e
    return cls()


def apply_conf_event_logger(name: str) -> None:
    """Install the conf-selected logger unless the application already
    called set_event_logger — the explicit act wins even when it installed
    a NoOp (an opt-out), matching the reference's first-resolution-wins
    singleton (HyperspaceEventLogging.scala:42-64)."""
    global _logger, _conf_applied
    if not name or _logger_explicit or _conf_applied:
        return  # first resolution wins; explicit set always wins
    _logger = resolve_event_logger(name)  # not via set_event_logger: conf
    # application must stay overridable by a later explicit set.
    _conf_applied = True
