"""The request flight recorder: a bounded, always-on ring of completed
request records with tail-based retention.

PR 7 made the serving path survive overload; this module makes it
*explainable after the fact*.  Spans live only for the life of a call,
the run report is overwritten per connection, and metrics aggregate away
the one request an operator is asked about — so "what happened to
request X at 14:02" was unanswerable the moment the socket closed.  The
recorder keeps the interesting tail the way production tracers do
(Dapper-style tail-based sampling, OpenTelemetry tail samplers): every
completed request is *offered*; slow (conf
``hyperspace.serving.flightRecorder.slowMs``), error, deadline-expired,
and shed requests are ALWAYS retained, healthy ones sampled 1-in-N
(``healthySampleN``), and the ring is bounded (``maxRecords``) with
healthy records evicted before interesting ones.

One record is a flat dict:

  - ``trace_id`` / ``request_id``: the wire-propagated trace context
    (interop/query.py mints/adopts; the same id the client error echoed)
  - ``kind``: ``sql`` / ``spec`` / ``local`` / ``maintenance`` (a
    lifecycle-daemon action) / ``unknown``
  - ``outcome``: ``OK`` or a wire error code (``BUSY`` / ``DEADLINE`` /
    ``BADREQ`` / ``FAILED``); local queries use the run report's
    ``ok`` / ``degraded`` / ``error``
  - ``latency_ms`` / ``queue_wait_ms`` / ``ts`` / ``slow`` / ``reason``
  - ``plan_fingerprint``: the plan-cache key when one was computed
  - ``spans``: the ``serve.request`` → ``query.collect`` → ``exec.*``
    span tree (tracing on), ``report``: the full QueryRunReport dict

Serialization cost is paid only for RETAINED records — the offer
decision is a few conf reads and a counter, so the healthy fast path
stays flat (bench ``flight_recorder`` section gates < 3% on the serving
workload).

Persistence: :func:`dump_diagnostics` (called by ``QueryServer.drain``
— so SIGTERM via ``handle_sigterm=True`` dumps — and by
``Hyperspace.dump_diagnostics()``) writes the ring plus a metrics
snapshot and the recent perf-ledger tail as ONE diagnostics bundle
through the PR 2 LogStore seam under
``<systemPath>/_hyperspace_diagnostics`` — both backends, readable
after restart via :func:`bundles`, bounded by ``maxBundles``.  Dumps run
inside ``faults.quiet()`` and never raise: diagnostics IO must neither
fail a drain nor consume an armed fault counter.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

FLIGHT_DIR = "_hyperspace_diagnostics"
BUNDLE_VERSION = 1
# How many trailing perf-ledger records ride along in a bundle.
PERF_TAIL = 32

_seq_lock = threading.Lock()
_seq = 0


def _conf_int(conf, attr: str, default: int) -> int:
    try:
        return int(getattr(conf, attr, default))
    except (TypeError, ValueError):
        return default


class FlightRecorder:
    """Lock-safe bounded ring of completed request records."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []
        self._healthy_seen = 0

    # -- retention ----------------------------------------------------------
    def offer(self, conf, outcome: str, latency_ms: float
              ) -> Optional[str]:
        """Retention decision for one completed request: the reason it
        will be kept (``error`` / ``slow`` / ``sample``), or None for a
        healthy request outside the sample.  Cheap by design — callers
        serialize span trees / reports only on a non-None answer."""
        if not bool(getattr(conf, "flight_recorder_enabled", True)):
            return None
        if outcome not in ("OK", "ok"):
            return "error"  # errors, deadlines, and sheds: always kept
        slow_ms = float(getattr(conf, "flight_recorder_slow_ms", 1000.0))
        if slow_ms > 0 and latency_ms >= slow_ms:
            return "slow"
        sample_n = _conf_int(conf, "flight_recorder_healthy_sample_n", 16)
        if sample_n <= 0:
            return None
        with self._lock:
            self._healthy_seen += 1
            if self._healthy_seen % sample_n == 1 or sample_n == 1:
                return "sample"
        return None

    def record(self, conf, *, kind: str, outcome: str, latency_ms: float,
               trace_id: str, request_id: str,
               queue_wait_ms: Optional[float] = None, error: str = "",
               span=None, report=None) -> bool:
        """Offer one completed request; returns True when it was
        retained.  ``span`` is the finished root
        :class:`~hyperspace_tpu.telemetry.trace.Span` (or None),
        ``report`` the finished QueryRunReport (or None) — serialized
        here, only for retained records.  Never raises."""
        from hyperspace_tpu.telemetry import metrics

        try:
            metrics.inc("flight.recorded")
            reason = self.offer(conf, outcome, latency_ms)
            if reason is None:
                return False
            slow_ms = float(getattr(conf, "flight_recorder_slow_ms",
                                    1000.0))
            rec: Dict[str, Any] = {
                "ts": time.time(),
                "trace_id": trace_id,
                "request_id": request_id,
                "kind": kind,
                "outcome": outcome,
                "error": error,
                "latency_ms": round(float(latency_ms), 3),
                "queue_wait_ms": (None if queue_wait_ms is None
                                  else round(float(queue_wait_ms), 3)),
                "slow": bool(slow_ms > 0 and latency_ms >= slow_ms),
                "reason": reason,
                "plan_fingerprint": _plan_fingerprint(report),
                # Attributed device-kernel ms (timeline seams; 0.0 when
                # the timeline was off or nothing ran on device): the
                # device-bound vs queue-bound discriminator for tails —
                # compare against queue_wait_ms and latency_ms.
                "device_ms": _device_ms(report),
                "spans": span.to_dict() if span is not None else None,
                "report": report.to_dict() if report is not None else None,
            }
            cap = max(1, _conf_int(conf, "flight_recorder_max_records",
                                   256))
            with self._lock:
                self._records.append(rec)
                while len(self._records) > cap:
                    self._evict_one_locked()
                metrics.set_gauge("flight.ring_size", len(self._records))
            metrics.inc("flight.retained")
            return True
        except Exception:  # noqa: BLE001 — a diagnostics failure must
            return False   # never fail the request it describes

    def _evict_one_locked(self) -> None:
        """Drop the oldest HEALTHY-sampled record; only when none is left
        does an interesting (error/slow) record age out."""
        from hyperspace_tpu.telemetry import metrics

        for i, rec in enumerate(self._records):
            if rec.get("reason") == "sample":
                del self._records[i]
                metrics.inc("flight.evicted.healthy")
                return
        del self._records[0]

    # -- reads --------------------------------------------------------------
    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._records]

    def find(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """The most recent retained record for ``trace_id`` (records of
        one trace share the id; latest wins), or None."""
        with self._lock:
            for rec in reversed(self._records):
                if rec.get("trace_id") == trace_id:
                    return dict(rec)
        return None

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._healthy_seen = 0


# One recorder per process, like the metrics registry: the serving layer
# and local collects it observes are process-level resources.
_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def record(conf, **kwargs) -> bool:
    return _RECORDER.record(conf, **kwargs)


def reset() -> None:
    _RECORDER.reset()


def _device_ms(report) -> float:
    """Attributed device-kernel milliseconds of the run (the timeline
    seams record ``kernel`` decisions into the report)."""
    if report is None:
        return 0.0
    from hyperspace_tpu.telemetry.timeline import device_ms_summary

    return device_ms_summary(report)


def _plan_fingerprint(report) -> str:
    """The plan-cache key recorded into the run report (dataset.collect),
    if one was computed for this query."""
    if report is None:
        return ""
    try:
        for d in report.decisions:
            if d.get("kind") == "plan_cache" and d.get("fingerprint"):
                return str(d["fingerprint"])
    except Exception:  # noqa: BLE001 — a foreign report shape reads empty
        pass
    return ""


def record_local(conf, rep) -> None:
    """Feed one LOCAL ``Dataset.collect`` into the recorder (the serving
    handler records served queries itself, with wire context and queue
    timings — ``Dataset.collect`` calls this only outside a request
    scope).  Mints a trace id so ``slow_queries()`` / the ``trace`` verb
    can address the record.  Never raises."""
    try:
        from hyperspace_tpu.interop.query import mint_trace_id

        _RECORDER.record(
            conf, kind="local",
            outcome=getattr(rep, "outcome", "ok"),
            latency_ms=float(getattr(rep, "duration_ms", 0.0)),
            trace_id=mint_trace_id(), request_id=mint_trace_id(),
            span=getattr(rep, "root_span", None), report=rep)
    except Exception:  # noqa: BLE001 — diagnostics never fail a query
        pass


# ---------------------------------------------------------------------------
# Slow-query surfacing
# ---------------------------------------------------------------------------
def slow_queries_table(conf=None):
    """The retained ring as an arrow table, oldest first — the shape
    ``Hyperspace.slow_queries()`` and the interop ``slow_queries`` verb
    return.  Structured payloads (span tree, run report) ride in
    ``recordJson`` so the schema stays flat."""
    import pyarrow as pa

    recs = _RECORDER.records()
    return pa.table({
        "ts": pa.array([float(r.get("ts", 0.0)) for r in recs],
                       type=pa.float64()),
        "traceId": pa.array([str(r.get("trace_id", "")) for r in recs],
                            type=pa.string()),
        "requestId": pa.array([str(r.get("request_id", ""))
                               for r in recs], type=pa.string()),
        "kind": pa.array([str(r.get("kind", "")) for r in recs],
                         type=pa.string()),
        "outcome": pa.array([str(r.get("outcome", "")) for r in recs],
                            type=pa.string()),
        "latencyMs": pa.array([float(r.get("latency_ms", 0.0))
                               for r in recs], type=pa.float64()),
        "queueWaitMs": pa.array([r.get("queue_wait_ms") for r in recs],
                                type=pa.float64()),
        "deviceMs": pa.array([float(r.get("device_ms", 0.0) or 0.0)
                              for r in recs], type=pa.float64()),
        "slow": pa.array([bool(r.get("slow")) for r in recs],
                         type=pa.bool_()),
        "reason": pa.array([str(r.get("reason", "")) for r in recs],
                           type=pa.string()),
        "error": pa.array([str(r.get("error", "")) for r in recs],
                          type=pa.string()),
        "recordJson": pa.array([json.dumps(r, default=str) for r in recs],
                               type=pa.string()),
    })


# ---------------------------------------------------------------------------
# Diagnostics bundles (the LogStore seam)
# ---------------------------------------------------------------------------
def flight_root(conf) -> str:
    from hyperspace_tpu.index.path_resolver import PathResolver

    return os.path.join(PathResolver(conf).system_path, FLIGHT_DIR)


def diagnostics_bundle(conf) -> Dict[str, Any]:
    """The live diagnostics bundle: the retained ring, a metrics
    snapshot, and the perf-ledger tail — what ``dump_diagnostics``
    persists and ``Hyperspace.diagnostics()`` returns."""
    from hyperspace_tpu.telemetry import metrics, perf_ledger

    try:
        perf_tail = perf_ledger.records(conf)[-PERF_TAIL:]
    except Exception:  # noqa: BLE001 — an unreadable ledger reads empty
        perf_tail = []
    return {
        "v": BUNDLE_VERSION,
        "ts": time.time(),
        "pid": os.getpid(),
        "records": _RECORDER.records(),
        "metrics": metrics.snapshot(),
        "perf_tail": perf_tail,
    }


def _next_bundle_key() -> str:
    global _seq
    with _seq_lock:
        _seq += 1
        seq = _seq
    return f"b-{int(time.time() * 1000):013d}-{os.getpid()}-{seq:05d}"


def dump_diagnostics(conf) -> Optional[str]:
    """Persist the current bundle; returns its key, or None when the
    recorder is disabled / the dump failed.  Never raises, and runs
    fault-quiet (a drain's diagnostics dump must not consume an armed
    fault counter or die to an injected crash)."""
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.perf_ledger import store_for
    from hyperspace_tpu.telemetry.trace import span

    if not bool(getattr(conf, "flight_recorder_enabled", True)):
        return None
    try:
        with faults.quiet(), span("flight.dump"):
            store = store_for(conf, flight_root(conf))
            payload = json.dumps(diagnostics_bundle(conf),
                                 default=str).encode("utf-8")
            key = None
            for _ in range(4):
                key = _next_bundle_key()
                if store.put_if_absent(key, payload):
                    break
            else:
                metrics.inc("flight.dump.errors")
                return None
            cap = max(1, _conf_int(conf, "flight_recorder_max_bundles", 8))
            keys = store.list_keys()
            if len(keys) > cap:
                for old in sorted(keys)[:len(keys) - cap]:
                    store.delete(old)
            metrics.inc("flight.dump.bundles")
            return key
    except Exception:  # noqa: BLE001 — diagnostics IO never fails callers
        metrics.inc("flight.dump.errors")
        return None


def bundles(conf) -> List[Dict[str, Any]]:
    """Every parseable persisted bundle, oldest first (``key`` attached).
    Torn/unparseable bundles are skipped — diagnostics are advisory."""
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.telemetry.perf_ledger import store_for

    out: List[Dict[str, Any]] = []
    try:
        with faults.quiet():
            store = store_for(conf, flight_root(conf))
            for key in sorted(store.list_keys()):
                try:
                    rec = json.loads(store.read(key).decode("utf-8"))
                except (FileNotFoundError, ValueError,
                        UnicodeDecodeError):
                    continue
                if not isinstance(rec, dict):
                    continue
                rec["key"] = key
                out.append(rec)
    except Exception:  # noqa: BLE001 — unreadable diagnostics read empty
        pass
    return out


def clear_bundles(conf) -> None:
    """Wipe persisted bundles (tests)."""
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.telemetry.perf_ledger import store_for

    with faults.quiet():
        store = store_for(conf, flight_root(conf))
        for key in store.list_keys():
            store.delete(key)
