"""Mesh-sharded grouped aggregation: bucket-owned groups, no merge pass.

The single-device kernel (ops/aggregate.py) lexsorts rows by group key
and segment-reduces.  The sharded form partitions ROWS BY GROUP-KEY
BUCKET — device ``d`` owns every group whose key hashes to a bucket with
``bucket % n_devices == d`` (the same mod ownership as the sharded build
route, computed with the bit-identical host hash mirror
``ops.hash.bucket_ids_np``) — so a group's rows land WHOLLY on one
device.  That is the property that makes the distributed aggregate
exact: every reduction (sum/min/max/mean/count) runs over the complete
group on its owner, there is no partial-aggregate merge tree, and mean
is an ordinary per-group division, not a weighted recombination.

Each device then runs the SAME ``_group_sort`` + ``_segment_reduce``
programs as the single-device kernel under ``shard_map`` (two host syncs:
per-device group counts, then the capacity-padded reduction), and the
host gather seam pulls per-group outputs through attributed
``sync_guard.pull`` sites.  Groups come back in ascending key order —
the single-device kernel's contract — via one host lexsort over the
group keys' order words.

Partitioning keeps each device's rows in ORIGINAL order, and the
per-device stable sort keeps each group's rows in original order — the
same per-group accumulation sequence as the single-device kernel, so
integer results are bit-equal and float results differ at most by the
platform's reduction-order latitude inside one segment.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from hyperspace_tpu.ops.aggregate import AGG_OPS, _group_sort, _segment_reduce
from hyperspace_tpu.ops.hash import bucket_ids_np
from hyperspace_tpu.parallel.mesh import (
    SHARD_AXIS,
    make_shard_and_gather_fns,
    match_partition_rules,
)
from hyperspace_tpu.utils.compat import enable_x64 as _enable_x64
from hyperspace_tpu.utils.shapes import round_up_pow2


@functools.partial(jax.jit, static_argnames=("n_key_cols", "mesh"))
def _count_program(key_words, n_valid, *, n_key_cols, mesh):
    def body(kw, nv):
        cols = tuple(kw[:, 2 * k:2 * k + 2] for k in range(n_key_cols))
        _perm, _boundaries, n_groups = _group_sort(cols, nv[0])
        return n_groups[None]

    spec = P(SHARD_AXIS)
    return _shard_map(body, mesh=mesh, in_specs=(spec, spec),
                      out_specs=spec)(key_words, n_valid)


@functools.partial(
    jax.jit, static_argnames=("n_key_cols", "ops", "capacity", "mesh"))
def _reduce_program(key_words, n_valid, value_cols, *, n_key_cols, ops,
                    capacity, mesh):
    def body(kw, nv, vc):
        cols = tuple(kw[:, 2 * k:2 * k + 2] for k in range(n_key_cols))
        perm, boundaries, n_groups = _group_sort(cols, nv[0])
        out = _segment_reduce(perm, boundaries, nv[0], vc,
                              ops=ops, capacity=capacity)
        return out + (n_groups[None],)

    spec = P(SHARD_AXIS)
    n_out = 2 + len(ops) + 1
    return _shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, tuple(spec for _ in value_cols)),
        out_specs=tuple(spec for _ in range(n_out)),
    )(key_words, n_valid, value_cols)


def _scatter_to_shards(col: np.ndarray, positions: np.ndarray,
                       total: int) -> np.ndarray:
    out = np.zeros((total,) + col.shape[1:], dtype=col.dtype)
    out[positions] = col
    return out


def mesh_grouped_aggregate(
    key_words: Sequence[np.ndarray],
    value_cols: Sequence[np.ndarray],
    ops: Sequence[str],
    mesh,
    pad_to: int = 0,
) -> Tuple[np.ndarray, np.ndarray, List[np.ndarray]]:
    """Sharded grouped aggregation over ``mesh``.

    Same contract as ``ops.aggregate.grouped_aggregate`` — per group in
    ascending key order: the index of its first row in the ORIGINAL
    order, the row count, and one result array per aggregate.  Inputs
    must be HOST arrays (device-resident columns keep the single-device
    kernel; sharded placement is its own layout).
    """
    from hyperspace_tpu.telemetry import metrics, timeline
    from hyperspace_tpu.telemetry.trace import span
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    for op in ops:
        if op not in AGG_OPS:
            raise ValueError(f"Unsupported device aggregate {op!r}")
    ensure_persistent_xla_cache()
    key_words = [np.asarray(w, dtype=np.uint32) for w in key_words]
    value_cols = [np.asarray(v) for v in value_cols]
    n = int(key_words[0].shape[0])
    n_devices = int(mesh.devices.size)
    if n == 0:
        return (np.empty(0, np.int32), np.empty(0, np.int32),
                [np.empty(0) for _ in ops])

    # Mod bucket ownership over the key words (bit-identical host hash
    # mirror): a group's rows all carry the same words, so they share an
    # owner and no group ever splits across devices.
    owner = bucket_ids_np(key_words, n_devices)
    part_perm = np.argsort(owner, kind="stable")
    dev_counts = np.bincount(owner, minlength=n_devices).astype(np.int32)
    lmax = max(int(dev_counts.max()), 1)
    if pad_to and pad_to > 0:
        quantum = max(1, -(-int(pad_to) // n_devices))
        lmax = -(-lmax // quantum) * quantum
    total = lmax * n_devices
    owner_sorted = owner[part_perm]
    starts = np.searchsorted(owner_sorted, np.arange(n_devices), "left")
    rank = np.arange(n, dtype=np.int64) - starts[owner_sorted]
    positions = owner_sorted.astype(np.int64) * lmax + rank
    offsets = starts  # original-row lookup per device below

    with span("exec.mesh.agg", devices=n_devices, rows=n):
        names = ("key_words", "value_cols", "n_valid", "counts")
        specs = match_partition_rules(names)
        shard_fns, gather_fns = make_shard_and_gather_fns(
            mesh, specs, site="mesh.agg")
        kw_plane = _scatter_to_shards(
            np.concatenate(key_words, axis=1)[part_perm], positions, total)
        kw_sharded = shard_fns["key_words"](kw_plane)
        nv_sharded = shard_fns["n_valid"](dev_counts)
        t0 = timeline.kernel_begin()
        if t0 is not None:
            timeline.record_transfer("h2d", int(kw_plane.nbytes) + sum(
                int(v.nbytes) for v in value_cols))
        counts_per_dev = gather_fns["counts"](_count_program(
            kw_sharded, nv_sharded, n_key_cols=len(key_words),
            mesh=mesh)).reshape(-1)
        g_max = int(counts_per_dev.max()) if counts_per_dev.size else 0
        g_total = int(counts_per_dev.sum())
        if g_total == 0:
            timeline.kernel_end("mesh_aggregate", t0, kw_sharded,
                                devices=list(mesh.devices.flat))
            return (np.empty(0, np.int32), np.empty(0, np.int32),
                    [np.empty(0) for _ in ops])
        capacity = round_up_pow2(g_max)
        with _enable_x64():
            # x64 scope: int64/float64 value planes must keep full width
            # through the shard placement AND the reduction program.
            vc_sharded = tuple(
                shard_fns["value_cols"](
                    _scatter_to_shards(v[part_perm], positions, total))
                for v in value_cols)
            out = _reduce_program(
                kw_sharded, nv_sharded, vc_sharded,
                n_key_cols=len(key_words), ops=tuple(ops),
                capacity=capacity, mesh=mesh)
        timeline.kernel_end("mesh_aggregate", t0, out,
                            devices=list(mesh.devices.flat))
        # Host gather seam: one attributed pull per output plane.
        from hyperspace_tpu.execution import sync_guard

        first_local = sync_guard.pull(out[0], "mesh.agg.first_rows")
        counts_g = sync_guard.pull(out[1], "mesh.agg.counts")
        results_g = [sync_guard.pull(r, "mesh.agg.results")
                     for r in out[2:-1]]
        n_groups = sync_guard.pull(out[-1], "mesh.agg.groups").reshape(-1)
        metrics.set_gauge("exec.mesh.devices", n_devices)
        metrics.inc("exec.mesh.gather.pulls", 3 + len(results_g))

    # Per-device valid prefixes -> original row ids -> one global
    # ascending-key order (the single-device kernel's output contract).
    first_parts, count_parts = [], []
    result_parts: List[List[np.ndarray]] = [[] for _ in ops]
    for d in range(n_devices):
        g_d = int(n_groups[d])
        if g_d == 0:
            continue
        lo, hi = d * capacity, d * capacity + g_d
        local_first = first_local[lo:hi].astype(np.int64)
        first_parts.append(part_perm[offsets[d] + local_first])
        count_parts.append(counts_g[lo:hi])
        for i in range(len(ops)):
            result_parts[i].append(results_g[i][lo:hi])
    first_rows = np.concatenate(first_parts)
    counts = np.concatenate(count_parts)
    results = [np.concatenate(parts) for parts in result_parts]
    sort_keys = []
    for w in reversed(key_words):
        fw = w[first_rows]
        sort_keys.append(fw[:, 1])
        sort_keys.append(fw[:, 0])
    order = np.lexsort(tuple(sort_keys))
    return (first_rows[order].astype(np.int32), counts[order],
            [r[order] for r in results])
