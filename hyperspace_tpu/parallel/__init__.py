"""Distributed data plane: mesh construction, the all_to_all bucket shuffle,
and the zero-communication co-partitioned join.

This package replaces the reference's Spark-cluster distribution substrate
(driver-planned shuffles over the TCP block manager, SURVEY.md §2.4) with
``jax.sharding.Mesh`` + ``shard_map`` + XLA collectives riding ICI/DCN.
"""

from hyperspace_tpu.parallel.aggregate import mesh_grouped_aggregate
from hyperspace_tpu.parallel.build import distributed_bucket_sort_permutation
from hyperspace_tpu.parallel.filter import eval_predicate_on_mesh
from hyperspace_tpu.parallel.join import (
    copartitioned_join,
    copartitioned_join_ragged,
)
from hyperspace_tpu.parallel.mesh import (
    SHARD_AXIS,
    active_mesh,
    build_mesh,
    make_shard_and_gather_fns,
    match_partition_rules,
)
from hyperspace_tpu.parallel.sharded_build import (
    bucket_group_bounds,
    mesh_route_partition,
)
from hyperspace_tpu.parallel.multihost import (
    DCN_AXIS,
    ICI_AXIS,
    build_mesh_2d,
    hierarchical_bucket_shuffle,
    initialize_distributed,
)
from hyperspace_tpu.parallel.shuffle import ShuffleResult, bucket_shuffle

__all__ = [
    "SHARD_AXIS",
    "DCN_AXIS",
    "ICI_AXIS",
    "active_mesh",
    "build_mesh",
    "build_mesh_2d",
    "bucket_shuffle",
    "hierarchical_bucket_shuffle",
    "initialize_distributed",
    "bucket_group_bounds",
    "match_partition_rules",
    "make_shard_and_gather_fns",
    "mesh_grouped_aggregate",
    "mesh_route_partition",
    "ShuffleResult",
    "distributed_bucket_sort_permutation",
    "eval_predicate_on_mesh",
    "copartitioned_join",
    "copartitioned_join_ragged",
]
