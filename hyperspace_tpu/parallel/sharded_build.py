"""Mesh-sharded fused route+partition: the external build's per-chunk
pass, scaled horizontally.

Single device, one spill chunk runs ``ops/hash.route_partition`` — hash,
then one stable lexsort by (bucket, keys).  Over a mesh the same chunk
becomes: rows data-parallel over the ``shard`` axis → per-device hash →
ONE ``lax.all_to_all`` delivering every row to its owning device (device
``d`` OWNS every bucket with ``bucket_id % n_devices == d`` — the
embarrassingly-parallel ownership ROADMAP item 1 names) → per-device
stable lexsort of the owned rows → the HOST GATHER SEAM: one attributed
``sync_guard.pull`` per device per chunk, after which a host counting
merge by bucket reassembles the global ``(bucket_ids, perm)``.

The result is BIT-IDENTICAL to ``route_partition_np`` (and therefore to
the single-device kernel): bucket assignment shares ``_bucket_ids_impl``,
each device's sort keys on (validity, bucket, order words, GLOBAL row
id) exactly like the flat shuffle (``sort_received``), and a bucket
lives on exactly ONE device — so a stable host sort by bucket over the
concatenated per-device streams reproduces the global
(bucket, keys, original row) order with no cross-device tie to break.
Layout can never depend on how many devices routed the chunk, which is
what lets ``actions/create._BucketSpill`` feed the per-device runs
straight into the streaming bucket-group finalize unchanged.

Inputs are placed under ``NamedSharding`` by the rule-driven shard fns
(``parallel/mesh.match_partition_rules`` + ``make_shard_and_gather_fns``)
— placement policy lives in the rule table, not here.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.4.35 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from hyperspace_tpu.execution import sync_guard
from hyperspace_tpu.io.columnar import join_words64
from hyperspace_tpu.ops.hash import _bucket_ids_impl, use_pallas
from hyperspace_tpu.parallel.mesh import (
    SHARD_AXIS,
    make_shard_and_gather_fns,
    match_partition_rules,
)
from hyperspace_tpu.parallel.shuffle import (
    make_row_records,
    marshal_shuffle_inputs,
    scatter_to_buffer,
    sort_received,
)


def bucket_group_bounds(num_buckets: int, groups: int) -> list:
    """Contiguous bucket-range cuts shared by every ownership layer:
    group (or host) ``g`` owns buckets ``bounds[g] <= b < bounds[g+1]``.
    ``actions/create._BucketSpill`` cuts its spill/finalize groups with
    this, and ``parallel/multihost_build`` claims the SAME ranges
    cross-host — one contract, so a group finalized on any host is the
    byte-identical unit a single process would have produced."""
    return [-(-g * num_buckets // groups) for g in range(groups + 1)]


def _route_body(num_buckets: int, num_devices: int, capacity: int,
                n_key_cols: int, n_order_cols: int, pallas: bool,
                hash_words, order_words, row_words, valid):
    """Per-device body under shard_map.  All inputs are the LOCAL shard:
    hash_words (L, 2K), order_words (L, 2K'), row_words (L, 2),
    valid (L,) int32.  Ownership is MOD, not range: dest = bucket %
    num_devices."""
    word_cols = tuple(hash_words[:, 2 * k:2 * k + 2]
                      for k in range(n_key_cols))
    bucket = _bucket_ids_impl(word_cols, num_buckets, pallas)
    dest = bucket % jnp.int32(num_devices)
    dest = jnp.where(valid.astype(bool), dest, num_devices)  # drop padding
    L = hash_words.shape[0]
    payload = jnp.zeros((L, 0), jnp.uint32)
    record = make_row_records(hash_words, order_words, row_words, payload,
                              bucket)
    send, overflow = scatter_to_buffer(record, dest, num_devices, capacity)
    recv = jax.lax.all_to_all(send, SHARD_AXIS, split_axis=0, concat_axis=0,
                              tiled=True)
    out, count = sort_received(recv, n_order_cols)
    return out, count[None], overflow[None]


@functools.partial(
    jax.jit,
    static_argnames=("num_buckets", "num_devices", "capacity", "n_key_cols",
                     "n_order_cols", "mesh", "pallas"))
def _route_program(hash_words, order_words, row_words, valid, *,
                   num_buckets, num_devices, capacity, n_key_cols,
                   n_order_cols, mesh, pallas):
    body = functools.partial(_route_body, num_buckets, num_devices,
                             capacity, n_key_cols, n_order_cols, pallas)
    spec = P(SHARD_AXIS)
    return _shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, spec, spec),
    )(hash_words, order_words, row_words, valid)


def mesh_route_partition(
    word_cols: Sequence[np.ndarray],
    order_words: Sequence[np.ndarray],
    num_buckets: int,
    mesh,
    pad_to: int = 0,
    slack: float = 1.5,
) -> Tuple[np.ndarray, np.ndarray]:
    """Sharded fused route+partition for one spill chunk over ``mesh``.

    Same contract as ``ops.hash.route_partition`` / ``route_partition_np``
    — ``(bucket_ids, perm)`` host int32 arrays, ``perm`` ordering the
    chunk's rows by (bucket, *keys) with original-row tie order, sorted
    within bucket when ``order_words`` is non-empty, grouped-only
    otherwise — and bit-identical output (tests/test_parallel_mesh.py
    holds it to that).  ``pad_to`` quantizes the per-device shard length
    so chunks of different sizes share one compiled program.
    """
    from hyperspace_tpu.telemetry import metrics, timeline
    from hyperspace_tpu.telemetry.trace import span
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    ensure_persistent_xla_cache()
    n = int(word_cols[0].shape[0])
    n_devices = int(mesh.devices.size)
    if n == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    n_key_cols = len(word_cols)
    n_order_cols = len(order_words)
    hw, ow, rw, _pl, valid, local = marshal_shuffle_inputs(
        word_cols, order_words if n_order_cols
        else [np.zeros((n, 0), np.uint32)],
        None, n_devices, pad_to)
    if not n_order_cols:
        ow = np.zeros((hw.shape[0], 0), np.uint32)

    with span("exec.mesh.route", devices=n_devices, rows=n):
        # Rule-driven placement: the table, not this call site, owns the
        # specs; the gather fns are the attributed host seam for the
        # whole-array outputs (per-device shards pull individually below).
        in_names = ("hash_words", "order_words", "row_words", "valid")
        specs = match_partition_rules(in_names + ("counts",))
        shard_fns, gather_fns = make_shard_and_gather_fns(
            mesh, specs, site="mesh.route")
        arrays = dict(zip(in_names, (hw, ow, rw, valid)))
        sharded = {k: shard_fns[k](v) for k, v in arrays.items()}

        capacity = max(16, int(-(-local * slack // n_devices)))
        capacity = min(local, -(-capacity // 8) * 8)
        t0 = timeline.kernel_begin()
        if t0 is not None:
            timeline.record_transfer("h2d", sum(
                int(a.nbytes) for a in arrays.values()))
        while True:
            out, counts, overflow = _route_program(
                sharded["hash_words"], sharded["order_words"],
                sharded["row_words"], sharded["valid"],
                num_buckets=num_buckets, num_devices=n_devices,
                capacity=capacity, n_key_cols=n_key_cols,
                n_order_cols=n_order_cols, mesh=mesh, pallas=use_pallas())
            overflow_total = int(sync_guard.scalar(
                jnp.sum(overflow), "mesh.route.overflow"))
            if overflow_total == 0:
                break
            if capacity >= local:  # cannot grow further; unreachable
                raise RuntimeError(
                    "mesh_route_partition: capacity overflow at maximum")
            capacity = min(local, capacity * 2)
        timeline.kernel_end("mesh_route", t0, out,
                            devices=list(mesh.devices.flat))
        counts_np = gather_fns["counts"](counts).reshape(-1)
        # THE host gather seam: one attributed pull per device per chunk,
        # each pulling only that device's resident shard (no cross-device
        # re-layout before the d2h hop).
        rows_per_device = n_devices * capacity
        by_start = {
            (s.index[0].start or 0): s.data
            for s in out.addressable_shards}
        bucket_parts, rowid_parts = [], []
        pulls = 0
        for d in range(n_devices):
            shard = by_start.get(d * rows_per_device)
            if shard is None:  # non-addressable (multi-host): skip ours
                continue
            rows = sync_guard.pull(
                shard, f"mesh.route.gather.d{d}")[:int(counts_np[d])]
            pulls += 1
            bucket_parts.append(rows[:, 1].astype(np.int32))
            rowid_parts.append(
                join_words64(rows[:, 2], rows[:, 3]).astype(np.int64))
        metrics.inc("exec.mesh.gather.pulls", pulls)
        metrics.inc("exec.mesh.route.chunks")
        metrics.set_gauge("exec.mesh.devices", n_devices)

    # Host counting merge: a bucket lives on exactly one device, so a
    # STABLE sort by bucket over the device-order concatenation is the
    # full global (bucket, keys, original row) order.
    bucket_all = np.concatenate(bucket_parts) if bucket_parts \
        else np.empty(0, np.int32)
    rowid_all = np.concatenate(rowid_parts) if rowid_parts \
        else np.empty(0, np.int64)
    order = np.argsort(bucket_all, kind="stable")
    perm = rowid_all[order].astype(np.int32)
    buckets_sorted = bucket_all[order]
    bucket_ids = np.empty(n, dtype=np.int32)
    bucket_ids[perm] = buckets_sorted
    return bucket_ids, perm
