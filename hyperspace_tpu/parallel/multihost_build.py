"""Fault-tolerant multi-host index build: cross-host bucket ownership,
crash-recoverable work claims, exactly-once commit.

PR 14's mesh made the build data-parallel inside ONE process; this module
takes the same bucket-ownership contract cross-host over the only seam
every host already shares — the index tree's filesystem/object store.  N
subprocess hosts cooperate on one build with no live collective between
them, because a collective is exactly what a SIGKILLed participant
poisons: rows move between hosts as spill files, and coordination moves
through :class:`~hyperspace_tpu.lifecycle.lease.WorkClaims` — the
maintenance lease's TTL + epoch-fencing CAS protocol, one claim per work
item.  (``jax.distributed`` over gloo/DCN remains the collective path
for healthy pods — ``parallel/multihost.py``; this module is the one
that survives losing a host.)

The work items mirror the single-process pipeline's two phases
(``actions/create.py`` ``_BucketSpill``), so the bytes cannot diverge:

  - ``chunk-<n>``: route one DETERMINISTIC slice of the global row
    stream (the same ``device_batch_rows`` boundaries ``_stream_build``
    cuts) through the same fused route kernel, landing one Arrow IPC
    run file per (chunk, bucket group) in the shared spill dir — writes
    are temp + atomic rename, so a half-written run is never visible.
  - ``group-<g>``: once every chunk claim is done, merge one bucket
    group's runs in chunk order (ties = global row order, exactly like
    ``_finish_group``), sort each bucket, and parquet-encode into the
    holder's OWN staging directory; the claim's done record carries the
    staged manifest (file names + per-file sha256).

Failure story:

  - a SIGKILLed/SIGSTOPped host's claims expire after ``claimTtlS``; a
    survivor reclaims (epoch bump) and redoes exactly those items.
    Re-finalizing a group is idempotent — byte-identical files — so it
    does not matter which attempt wins, only that exactly one does.
  - a fenced zombie (SIGCONT after takeover) loses the done-record CAS,
    journals ``claim.fence``, and deletes its own staged files.
  - the coordinator (the CreateAction itself) validates the union —
    every group covered by a done claim whose staged files exist and
    hash to their manifest — promotes the winning files into the next
    ``v__=N`` dir, and then the ordinary action commit at
    ``base_id + 2`` (``io/log_store.put_if_absent``) is the ONE
    transaction that publishes all of it or nothing.
  - build scratch lives under ``<systemPath>/_hyperspace_build/
    build-<pid>-<token>/``; a dead coordinator's whole dir is reaped at
    the next build start (the ``reap_orphan_spill_dirs`` idiom).

``telemetry/doctor.py`` grades leftover claims against the PR 15 fleet
heartbeats (hosts here publish them when fleet telemetry is on):
expired claim with no live heartbeat → the next build will reclaim it
(warn); FRESH claim whose holder is dead → the build stalls a TTL
(crit).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

BUILD_DIR = "_hyperspace_build"
PLAN_KEY = "plan"
_BUILD_DIR_PREFIX = "build-"
_MAX_GROUPS = 8  # must match _BucketSpill._MAX_GROUPS (same group cuts)


def armed(conf) -> bool:
    """True when createIndex should run through the claim pipeline.

    0 disables (the ordinary in-process build); 1 runs a single
    subprocess host through the SAME claim/stage/commit protocol —
    degenerate but useful as the bench baseline for the 1-vs-2-host
    scaling ratio (identical per-chunk overheads on both sides); >= 2
    is the real multi-host build."""
    return int(getattr(conf, "multihost_build_hosts", 0)) >= 1


def build_root(conf) -> str:
    from hyperspace_tpu.index.path_resolver import PathResolver

    return os.path.join(PathResolver(conf).system_path, BUILD_DIR)


def _store(conf, build_id: str):
    from hyperspace_tpu.telemetry.perf_ledger import store_for

    return store_for(conf, os.path.join(build_root(conf), build_id))


def reap_orphan_build_dirs(conf) -> int:
    """Remove build scratch dirs whose coordinating pid is provably dead
    (same contract as ``actions/create.reap_orphan_spill_dirs``: a
    SIGKILLed coordinator runs no cleanup, and its dir holds a routed
    copy of the source).  Returns the number reaped."""
    from hyperspace_tpu.actions.create import _pid_alive
    from hyperspace_tpu.io.files import remove_tree

    root = build_root(conf)
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    reaped = 0
    for name in names:
        if not name.startswith(_BUILD_DIR_PREFIX):
            continue
        pid_part = name[len(_BUILD_DIR_PREFIX):].split("-", 1)[0]
        if not pid_part.isdigit():
            continue
        pid = int(pid_part)
        if pid == os.getpid() or _pid_alive(pid):
            continue
        try:
            remove_tree(os.path.join(root, name), ignore_errors=True)
            reaped += 1
        except OSError:
            pass  # best-effort, like the spill reap
    return reaped


# -- the plan (written once by the coordinator, read by every host) ----------

def _group_bounds(num_buckets: int, groups: int) -> List[int]:
    # The shared ownership contract — identical cuts to
    # _BucketSpill._bounds, from the one function both layers call.
    from hyperspace_tpu.parallel.sharded_build import bucket_group_bounds

    return bucket_group_bounds(num_buckets, groups)


def _chunk_ranges(total_rows: int, batch_rows: int) -> List[List[int]]:
    """Global row-stream slices at ``device_batch_rows`` — the same
    boundaries ``_stream_build`` cuts, so single-process and multi-host
    runs route identical chunks and the merged tie order matches."""
    ranges = []
    start = 0
    while start < total_rows:
        ranges.append([start, min(start + batch_rows, total_rows)])
        start += batch_rows
    return ranges


def _code_column_names(columns, indexed, rel_schema, lineage) -> List[str]:
    """The ride-along sort-code column plan, from the relation schema
    (mirrors ``_BucketSpill._plan_code_columns``: () when any key is
    rank-mapped — chunk-local ranks don't merge across chunks)."""
    from hyperspace_tpu.actions.create import DATA_FILE_ID_COLUMN
    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.io.parquet import _dtype_from_string

    for c in indexed:
        if not columnar.is_numeric_type(
                _dtype_from_string(rel_schema.get(c, "string"))):
            return []
    taken = set(columns)
    if lineage:
        taken.add(DATA_FILE_ID_COLUMN)
    names = []
    for i in range(len(indexed)):
        name = f"__hs_sort{i}"
        while name in taken:
            name += "_"
        taken.add(name)
        names.append(name)
    return names


def make_plan(conf, build_id: str, index_name: str, relation, resolved,
              files, columns, lineage: bool, batch_rows: int) -> Dict:
    """The immutable build plan every host executes against.  Requires
    parquet sources (footer row counts define the chunk boundaries
    without a decode) and the lexicographic layout (the Z-order build
    is a global two-pass and does not hash-partition)."""
    import pyarrow.parquet as pq

    from hyperspace_tpu.exceptions import HyperspaceError

    if getattr(resolved, "layout", "lexicographic") == "zorder":
        raise HyperspaceError(
            "multihost build does not support the zorder layout (the "
            "global curve is a single two-pass build); unset "
            "hyperspace.index.build.multihost.hosts for this index")
    if relation.read_format != "parquet":
        raise HyperspaceError(
            f"multihost build requires parquet sources (footer row "
            f"counts plan the chunk claims); got "
            f"{relation.read_format!r}")
    file_rows = []
    for f in files:
        try:
            file_rows.append(pq.read_metadata(f.name).num_rows)
        except Exception as e:
            raise HyperspaceError(
                f"multihost build could not read the parquet footer of "
                f"{f.name}: {e}") from e
    total = sum(file_rows)
    num_buckets = int(conf.num_buckets)
    groups = min(_MAX_GROUPS, num_buckets)
    rel_schema = dict(relation.schema())
    return {
        "v": 1,
        "build_id": build_id,
        "index": index_name,
        "format": relation.read_format,
        "roots": list(relation.root_paths),
        "options": [list(kv) for kv in relation.options],
        "rel_schema": rel_schema,
        "files": [{"name": f.name, "id": f.id, "rows": r}
                  for f, r in zip(files, file_rows)],
        "columns": list(columns),
        "indexed": list(resolved.indexed_columns),
        "layout": getattr(resolved, "layout", "lexicographic"),
        "lineage": bool(lineage),
        "total_rows": total,
        "batch_rows": int(batch_rows),
        "num_buckets": num_buckets,
        "groups": groups,
        "bounds": _group_bounds(num_buckets, groups),
        "chunks": _chunk_ranges(total, int(batch_rows)),
        "code_cols": _code_column_names(
            columns, resolved.indexed_columns, rel_schema, lineage),
        "max_rows_per_file": int(conf.index_max_rows_per_file),
        "compression": conf.index_file_compression,
    }


def _chunk_items(plan: Dict) -> List[str]:
    return [f"chunk-{i:05d}" for i in range(len(plan["chunks"]))]


def _group_items(plan: Dict) -> List[str]:
    return [f"group-{g:03d}" for g in range(plan["groups"])]


def _scratch(conf, build_id: str) -> str:
    return os.path.join(build_root(conf), build_id)


# -- host side: route + finalize under claims --------------------------------

def _read_global_slice(plan: Dict, start: int, end: int,
                       cache: Dict) -> "pa.Table":
    """Rows [start, end) of the global stream (files in listing order,
    rows in file order) — the multihost mirror of ``_read_chunk`` +
    ``_stream_build``'s buffering, including schema-evolution nulls and
    the constant-per-file lineage column.  ``cache`` holds the last few
    decoded files (consecutive chunks usually share a file)."""
    import numpy as np
    import pyarrow as pa

    from hyperspace_tpu.actions.create import DATA_FILE_ID_COLUMN
    from hyperspace_tpu.io.parquet import _dtype_from_string, read_table

    columns = plan["columns"]
    options = {k: v for k, v in plan["options"]}
    parts = []
    offset = 0
    for frec in plan["files"]:
        rows = frec["rows"]
        lo, hi = max(start, offset), min(end, offset + rows)
        if lo < hi:
            t = cache.get(frec["name"])
            if t is None:
                t = read_table([frec["name"]], plan["format"], columns,
                               options, partition_roots=plan["roots"])
                missing = [c for c in columns if c not in t.column_names]
                for c in missing:
                    t = t.append_column(c, pa.nulls(
                        t.num_rows, type=_dtype_from_string(
                            plan["rel_schema"].get(c, "string"))))
                if plan["lineage"]:
                    fid = np.full(t.num_rows, frec["id"], dtype=np.int64)
                    t = t.append_column(DATA_FILE_ID_COLUMN, pa.array(fid))
                while len(cache) >= 2:
                    cache.pop(next(iter(cache)))
                cache[frec["name"]] = t
            parts.append(t.slice(lo - offset, hi - lo))
        offset += rows
        if offset >= end:
            break
    return pa.concat_tables(parts, promote_options="default")


def _route_table(conf, plan: Dict, table: "pa.Table"):
    """The fused route for one chunk — the same kernels and the same
    host-mirror threshold as ``_BucketSpill._route_chunk`` (mesh-less:
    each host is one device here; ownership crosses hosts via the
    bucket-group claims, not a collective), so bucket assignment and
    tie order are bit-identical to the single-process build."""
    import numpy as np
    import pyarrow as pa

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops.hash import route_partition, route_partition_np

    code_cols = plan["code_cols"]
    key_cols = plan["indexed"]
    word_cols = [np.asarray(columnar.to_hash_words(table.column(c)))
                 for c in key_cols]
    codes64 = [columnar.to_order_codes64(table.column(c))
               for c in key_cols] if code_cols else []
    num_buckets = plan["num_buckets"]
    if table.num_rows < conf.device_min_rows("build"):
        buckets, perm = route_partition_np(word_cols, codes64, num_buckets)
    else:
        buckets, perm = route_partition(
            word_cols, [columnar.split_words64(k) for k in codes64],
            num_buckets, pad_to=max(1, int(conf.device_batch_rows)))
    buckets = np.asarray(buckets)
    perm = np.asarray(perm)
    sorted_buckets = buckets[perm]
    routed = table.take(pa.array(perm))
    for i, name in enumerate(code_cols):
        routed = routed.append_column(name, pa.array(codes64[i][perm]))
    starts = np.searchsorted(sorted_buckets, np.arange(num_buckets), "left")
    ends = np.searchsorted(sorted_buckets, np.arange(num_buckets), "right")
    return routed, starts, ends


def _route_one_chunk(conf, plan: Dict, scratch: str, chunk_no: int,
                     cache: Dict) -> Dict:
    """Process one ``chunk-<n>`` claim: read the slice, route it, land
    one run file per touched bucket group (temp + atomic rename — a
    crash mid-write is never visible), and return the claim result:
    which buckets each group's run holds, in batch order."""
    from hyperspace_tpu.actions.create import _write_chunk_file
    from hyperspace_tpu.io import faults

    start, end = plan["chunks"][chunk_no]
    table = _read_global_slice(plan, start, end, cache)
    routed, starts, ends = _route_table(conf, plan, table)
    spill = os.path.join(scratch, "spill")
    groups: Dict[str, List[int]] = {}
    for gid in range(plan["groups"]):
        b0, b1 = plan["bounds"][gid], plan["bounds"][gid + 1]
        present = [b for b in range(b0, b1) if ends[b] > starts[b]]
        if not present:
            continue
        path = os.path.join(spill, f"chunk-{chunk_no:05d}-g{gid:03d}.arrow")
        tmp = f"{path}.tmp-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        _write_chunk_file(
            routed, tmp,
            [(int(starts[b]), int(ends[b] - starts[b])) for b in present])
        faults.atomic_replace(tmp, path, "data.write")
        groups[str(gid)] = present
    schema = {} if chunk_no else {
        name: str(t) for name, t in
        zip(table.column_names, table.schema.types)}
    result = {"rows": table.num_rows, "groups": groups}
    if schema:
        result["schema"] = schema
    return result


def _finalize_group(conf, plan: Dict, scratch: str, gid: int,
                    chunk_results: List[Dict], staged_dir: str) -> Dict:
    """Process one ``group-<g>`` claim: merge the group's runs in chunk
    order, sort each bucket (ride-along codes or the host re-derive —
    the ``_finish_group`` logic), parquet-encode into ``staged_dir``
    (holder-private), and return the staged manifest with per-file
    sha256 — what the coordinator validates before promoting."""
    import pyarrow as pa

    from hyperspace_tpu.io.parquet import (
        sort_permutation_from_codes,
        sort_permutation_host,
        write_bucket_run,
    )

    spill = os.path.join(scratch, "spill")
    b0, b1 = plan["bounds"][gid], plan["bounds"][gid + 1]
    # bucket -> [(chunk_no, path, batch_idx)] in chunk order = tie order.
    runs: Dict[int, List[Tuple[int, str, int]]] = {}
    paths = []
    for chunk_no, res in enumerate(chunk_results):
        present = res["groups"].get(str(gid))
        if not present:
            continue
        path = os.path.join(spill, f"chunk-{chunk_no:05d}-g{gid:03d}.arrow")
        paths.append(path)
        for bi, b in enumerate(present):
            runs.setdefault(b, []).append((chunk_no, path, bi))
    os.makedirs(staged_dir, exist_ok=True)
    code_cols = plan["code_cols"]
    manifest: List[Dict[str, Any]] = []
    readers = {}
    handles = []
    rows_total = 0
    try:
        for p in paths:
            mm = pa.memory_map(p, "rb")
            handles.append(mm)
            readers[p] = pa.ipc.open_file(mm)
        for b in sorted(runs):
            batches = [readers[p].get_batch(bi)
                       for _no, p, bi in sorted(runs[b])]
            btable = pa.Table.from_batches(batches)
            if code_cols:
                perm = sort_permutation_from_codes(btable, code_cols)
                btable = btable.take(pa.array(perm)).drop_columns(
                    list(code_cols))
            else:
                perm = sort_permutation_host(btable, plan["indexed"],
                                             plan["layout"])
                btable = btable.take(pa.array(perm))
            written = write_bucket_run(
                btable, b, staged_dir, plan["max_rows_per_file"],
                compression=plan["compression"])
            rows_total += btable.num_rows
            for p in written:
                manifest.append({
                    "name": os.path.basename(p),
                    "bucket": b,
                    "sha256": _sha256_file(p),
                })
    finally:
        for mm in handles:
            try:
                mm.close()
            except OSError:
                pass
    return {"dir": os.path.relpath(staged_dir, scratch),
            "files": manifest, "rows": rows_total}


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _heartbeat(conf) -> None:
    from hyperspace_tpu.telemetry import fleet

    if fleet.enabled(conf):
        fleet.publish_once(conf)


def run_host(conf, build_id: str, owner: Optional[str] = None) -> int:
    """One build host's main loop: claim-route every chunk, then
    claim-finalize every bucket group, reclaiming expired items as they
    appear.  Every expensive output is committed through the claim CAS
    — a fenced attempt deletes its own staged files and moves on.
    Returns the number of items this host completed."""
    from hyperspace_tpu.io.files import remove_tree
    from hyperspace_tpu.lifecycle.lease import WorkClaims
    from hyperspace_tpu.telemetry import fleet

    owner = owner or fleet.process_identity()
    store = _store(conf, build_id)
    plan = json.loads(store.read(PLAN_KEY).decode("utf-8"))
    scratch = _scratch(conf, build_id)
    claims = WorkClaims(
        store, conf, owner=owner,
        ttl_s=float(getattr(conf, "multihost_build_claim_ttl_s", 10.0)),
        index=plan["index"])
    poll_s = max(0.005,
                 float(getattr(conf, "multihost_build_poll_s", 0.05)))
    completed = 0
    cache: Dict[str, Any] = {}
    _heartbeat(conf)

    def drive(items, process) -> int:
        """Claim/process items until every one is done; returns how
        many THIS host completed."""
        done_here = 0
        while True:
            progress = False
            remaining = False
            for item in items:
                rec, _gen = claims.get(item)
                if rec is not None and rec.get("done"):
                    continue
                claim = claims.try_claim(item)
                if claim is None:
                    remaining = True
                    continue
                outputs = process(item, claim)
                # The margin stand-down: if our TTL ran out (or nearly
                # — store-RTT margin) while processing, renew first; a
                # lost renew means the item was reclaimed and our
                # output is the zombie's.
                committed = False
                if claims.holds(claim) or claims.renew(claim):
                    committed = claims.complete(claim, outputs["result"])
                if committed:
                    done_here += 1
                else:
                    for orphan in outputs.get("discard", ()):
                        remove_tree(orphan, ignore_errors=True)
                    remaining = True
                progress = True
                _heartbeat(conf)
            if not remaining:
                return done_here
            if not progress:
                time.sleep(poll_s)
                _heartbeat(conf)

    def route(item, claim) -> Dict:
        chunk_no = int(item.split("-")[1])
        result = _route_one_chunk(conf, plan, scratch, chunk_no, cache)
        return {"result": result}  # runs are shared + deterministic:
        # a fenced duplicate wrote identical bytes, nothing to discard

    completed += drive(_chunk_items(plan), route)
    cache.clear()
    chunk_results = [claims.result(it) for it in _chunk_items(plan)]

    def finalize(item, claim) -> Dict:
        gid = int(item.split("-")[1])
        staged = os.path.join(
            scratch, "staged",
            _safe_name(owner), f"g{gid:03d}-e{claim['epoch']:03d}")
        result = _finalize_group(conf, plan, scratch, gid, chunk_results,
                                 staged)
        return {"result": result, "discard": [staged]}

    completed += drive(_group_items(plan), finalize)
    _heartbeat(conf)
    return completed


def _safe_name(owner: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "_" for c in owner)


def host_main() -> None:
    """Subprocess entry: spec from ``HS_MULTIHOST_SPEC`` (system path,
    build id, conf field overrides)."""
    spec = json.loads(os.environ["HS_MULTIHOST_SPEC"])
    from hyperspace_tpu.config import HyperspaceConf

    conf = HyperspaceConf()
    conf.system_path = spec["system_path"]
    for field, value in spec.get("conf", {}).items():
        setattr(conf, field, value)
    run_host(conf, spec["build_id"], owner=spec.get("owner"))


_WORKER_CONF_FIELDS = (
    "num_buckets", "device_batch_rows", "index_max_rows_per_file",
    "index_file_compression", "log_store_class",
    "object_store_stale_list_ms", "multihost_build_claim_ttl_s",
    "multihost_build_poll_s", "fleet_telemetry_enabled",
    "fleet_publish_interval_s", "lineage_enabled",
)


def spawn_hosts(conf, build_id: str, n: int) -> List[subprocess.Popen]:
    """Spawn ``n`` build-host subprocesses against one plan.  Each
    inherits the environment (JAX_PLATFORMS etc.) plus the spec; the
    host-vs-device route threshold is resolved HERE so every host (and
    any host that later reclaims) routes through the same path."""
    import hyperspace_tpu

    overrides = {f: getattr(conf, f) for f in _WORKER_CONF_FIELDS
                 if hasattr(conf, f)}
    overrides["device_build_min_rows"] = conf.device_min_rows("build")
    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.abspath(hyperspace_tpu.__file__)))
    procs = []
    for i in range(n):
        env = dict(os.environ)
        env["HS_MULTIHOST_SPEC"] = json.dumps({
            "system_path": conf.system_path,
            "build_id": build_id,
            "conf": overrides,
            "owner": None,  # fleet.process_identity() of the subprocess
        })
        env.setdefault("JAX_PLATFORMS", "cpu")
        # The parent may import the package from its cwd; the child has
        # no cwd entry on sys.path, so pin the package's location.
        env["PYTHONPATH"] = pkg_parent + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        procs.append(subprocess.Popen(
            [sys.executable, "-c",
             "from hyperspace_tpu.parallel.multihost_build import "
             "host_main; host_main()"],
            env=env))
    return procs


# -- coordinator side (runs inside the CreateAction) -------------------------

def _poll_done(claims, items) -> int:
    done = 0
    for item in items:
        rec, _gen = claims.get(item)
        if rec is not None and rec.get("done"):
            done += 1
    return done


def _claim_span(claims, items) -> float:
    """Phase wall-clock from the claim records: first acquire to last
    complete across the items' done records.  Excludes subprocess
    interpreter spin-up, which is what makes the bench's scaling gate
    honest."""
    first, last = None, None
    for item in items:
        rec, _gen = claims.get(item)
        if rec is None or not rec.get("done"):
            continue
        acq = float(rec.get("acquired_at", 0.0))
        fin = float(rec.get("completed_at", 0.0))
        if acq and (first is None or acq < first):
            first = acq
        if fin and (last is None or fin > last):
            last = fin
    if first is None or last is None:
        return 0.0
    return max(0.0, last - first)


def run_multihost_build(action, files, columns, relation, resolved,
                        lineage: bool, batch_rows: int) -> None:
    """The coordinator: plan, spawn the hosts, wait on the claim table,
    validate + promote the union, and leave the normal action commit at
    ``base_id + 2`` as the single exactly-once transaction.  Called
    from ``CreateActionBase._build_index_data`` when
    ``hyperspace.index.build.multihost.hosts >= 2``."""
    import time as _time

    from hyperspace_tpu.exceptions import HyperspaceError
    from hyperspace_tpu.io.files import remove_tree
    from hyperspace_tpu.lifecycle import journal
    from hyperspace_tpu.lifecycle.lease import WorkClaims
    from hyperspace_tpu.telemetry import fleet, metrics

    conf = action.conf
    reap_orphan_build_dirs(conf)
    n_hosts = int(conf.multihost_build_hosts)
    build_id = f"{_BUILD_DIR_PREFIX}{os.getpid()}-{uuid.uuid4().hex[:8]}"
    plan = make_plan(conf, build_id, action.index_name, relation, resolved,
                     files, columns, lineage, batch_rows)
    scratch = _scratch(conf, build_id)
    os.makedirs(os.path.join(scratch, "spill"), exist_ok=True)
    store = _store(conf, build_id)
    store.put_if_absent(PLAN_KEY,
                        json.dumps(plan).encode("utf-8"))
    claims = WorkClaims(
        store, conf, owner=f"coordinator-{fleet.process_identity()}",
        ttl_s=float(conf.multihost_build_claim_ttl_s),
        index=action.index_name)
    poll_s = max(0.005, float(conf.multihost_build_poll_s))
    deadline = _time.monotonic() + \
        max(1.0, float(conf.multihost_build_deadline_s))
    chunk_items, group_items = _chunk_items(plan), _group_items(plan)
    procs = spawn_hosts(conf, build_id, n_hosts)
    t_spawn = _time.perf_counter()
    route_wall = finalize_wall = 0.0
    try:
        # Phase 1 barrier: every chunk routed.  The coordinator only
        # WATCHES — claims expire and survivors reclaim on their own;
        # it fails loudly when nobody is left to make progress.
        expired_logged = set()
        for items, phase in ((chunk_items, "route"),
                             (group_items, "finalize")):
            while _poll_done(claims, items) < len(items):
                if _time.monotonic() > deadline:
                    raise HyperspaceError(
                        f"multihost build {build_id}: {phase} phase "
                        f"missed the deadline "
                        f"({conf.multihost_build_deadline_s}s) with "
                        f"{len(items) - _poll_done(claims, items)} "
                        f"items pending")
                if all(p.poll() is not None for p in procs):
                    raise HyperspaceError(
                        f"multihost build {build_id}: every host exited "
                        f"(codes {[p.returncode for p in procs]}) with "
                        f"{phase} items pending")
                # Straggler visibility: an expired, un-reclaimed claim
                # means a host died or stalled — count it for the
                # doctor's fleet check rather than hanging silently,
                # and journal each sighting ONCE per claim epoch (the
                # doctor check itself stays read-only; this record is
                # what its non-ok grades point post-mortems at).
                now = time.time()
                for item in items:
                    rec, _g = claims.get(item)
                    if rec is not None and not rec.get("done") and \
                            float(rec.get("expires_at", 0)) < now:
                        metrics.inc("build.claims.expired_seen")
                        key = (item, int(rec.get("epoch", 0)))
                        if key not in expired_logged:
                            expired_logged.add(key)
                            journal.append(conf, {
                                "decision": "claim",
                                "index": action.index_name,
                                "mode": "expired", "outcome": "observed",
                                "reason": f"{phase} claim expired "
                                          f"un-reclaimed — straggler or "
                                          f"crash; a survivor reclaims "
                                          f"after the TTL",
                                "holder": str(rec.get("holder", "")),
                                "epoch": int(rec.get("epoch", 0)),
                                "item": item,
                            })
                time.sleep(poll_s)
        # Phase wall-clock from the claim records themselves (first
        # acquire -> last complete): what the work actually took,
        # independent of the ~seconds of subprocess interpreter spin-up
        # — the number the bench's near-2x gate is honest against.
        route_wall = _claim_span(claims, chunk_items)
        finalize_wall = _claim_span(claims, group_items)
        total_wall = _time.perf_counter() - t_spawn
        for p in procs:
            try:
                p.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                p.kill()  # a SIGSTOPped zombie; its claims already lost
                p.wait()
        _commit_staged(action, plan, claims, scratch, resolved)
        journal.append(conf, {
            "decision": "claim", "index": action.index_name,
            "mode": "commit", "outcome": "done",
            "reason": f"{len(group_items)} groups / {len(chunk_items)} "
                      f"chunks over {n_hosts} hosts",
            "holder": claims.owner, "epoch": 0, "item": build_id,
        })
        report = action.build_report
        report.properties.update(
            multihost_hosts=n_hosts,
            multihost_chunks=len(chunk_items),
            multihost_groups=len(group_items),
            multihost_route_wall_s=round(route_wall, 4),
            multihost_finalize_wall_s=round(finalize_wall, 4),
            multihost_total_wall_s=round(total_wall, 4))
        action._phase("mh_route_s", route_wall)
        action._phase("mh_finalize_s", finalize_wall)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        remove_tree(scratch, ignore_errors=True)


def _commit_staged(action, plan: Dict, claims, scratch: str,
                   resolved) -> None:
    """Validate the union of staged manifests — every group done, every
    staged file present and hashing to its manifest, row accounting
    exact — then promote the winners into the next ``v__=N`` dir.  Any
    gap aborts BEFORE the version dir exists: the union commits or
    nothing does."""
    from hyperspace_tpu.exceptions import HyperspaceError
    from hyperspace_tpu.io import faults

    manifests = {}
    rows = 0
    for item in _group_items(plan):
        res = claims.result(item)
        if res is None:
            raise HyperspaceError(
                f"multihost build: {item} has no completed claim")
        gid = int(item.split("-")[1])
        b0, b1 = plan["bounds"][gid], plan["bounds"][gid + 1]
        for frec in res["files"]:
            if not (b0 <= frec["bucket"] < b1):
                raise HyperspaceError(
                    f"multihost build: {item} staged bucket "
                    f"{frec['bucket']} outside its range "
                    f"[{b0}, {b1})")
            staged = os.path.join(scratch, res["dir"], frec["name"])
            if not os.path.exists(staged):
                raise HyperspaceError(
                    f"multihost build: staged file missing: {staged}")
            if _sha256_file(staged) != frec["sha256"]:
                raise HyperspaceError(
                    f"multihost build: staged file {staged} does not "
                    f"match its manifest sha256")
        rows += int(res.get("rows", 0))
        manifests[item] = res
    if rows != plan["total_rows"]:
        raise HyperspaceError(
            f"multihost build: staged {rows} rows for "
            f"{plan['total_rows']} source rows — refusing to commit a "
            f"torn index")
    schema = next((claims.result(it).get("schema")
                   for it in _chunk_items(plan)
                   if claims.result(it) and claims.result(it).get("schema")),
                  None)
    version = action.data_manager.get_next_version()
    out_dir = action.data_manager.version_path(version)
    os.makedirs(out_dir, exist_ok=True)
    for item, res in manifests.items():
        for frec in res["files"]:
            src = os.path.join(scratch, res["dir"], frec["name"])
            faults.atomic_replace(
                src, os.path.join(out_dir, frec["name"]), "data.write")
    action._write_index_file_sketch(out_dir, resolved)
    action._written_version = version
    if schema:
        action._index_schema = dict(schema)


# -- doctor seam -------------------------------------------------------------

def scan_build_claims(conf) -> List[Dict[str, Any]]:
    """Every pending (not done) claim record across every build scratch
    dir under this tree, each annotated with its build id — what
    ``telemetry/fleet._check_build_claims`` grades against the fleet
    heartbeats.  Never raises."""
    from hyperspace_tpu.lifecycle.lease import WorkClaims, _parse

    out: List[Dict[str, Any]] = []
    root = build_root(conf)
    try:
        builds = sorted(os.listdir(root))
    except OSError:
        return out
    for build_id in builds:
        if not build_id.startswith(_BUILD_DIR_PREFIX):
            continue
        try:
            store = _store(conf, build_id)
            for key in store.list_keys():
                if not key.startswith(WorkClaims.PREFIX):
                    continue
                payload, _gen = store.read_with_generation(key)
                rec = _parse(payload)
                if rec is None or rec.get("done"):
                    continue
                rec = dict(rec)
                rec["build_id"] = build_id
                out.append(rec)
        except Exception:  # noqa: BLE001 — a flaky store reads as empty
            continue
    return out
