"""Co-partitioned distributed equi-join: zero-communication by construction.

The point of the covering index's bucket layout (JoinIndexRule.scala:36-50):
when both join sides are bucketed by the join key with the same bucket
count, matching keys are guaranteed co-located, so the join runs per-bucket
with NO shuffle.  On the mesh the same invariant holds per-device — buckets
are range-partitioned identically on both sides (parallel/shuffle.py), so
``shard_map`` runs a purely local sorted join on every device and the only
"collective" is the host gathering match counts.

Like the single-chip join (ops/join.py) this is two-phase: count matches
(static-shape program #1), then materialize pairs with the max per-device
count as the static output capacity (program #2).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from hyperspace_tpu.execution import sync_guard
from hyperspace_tpu.parallel.mesh import SHARD_AXIS
from hyperspace_tpu.utils.compat import enable_x64 as _enable_x64
from hyperspace_tpu.utils.shapes import round_up_pow2


def _ranges_local(lk, lvalid, rk, rvalid):
    """Per-device match ranges of left keys in the sorted right keys.

    Padding slots are excluded by VALIDITY, not by a sentinel value — a
    sentinel (inf/intmax) would collide with real keys of that value and a
    valid NaN key would sort past it, letting padding slots leak into the
    match window.  Valid rows are lexsorted first; the tail is overwritten
    with the largest valid key so the array stays sorted, and both range
    ends are clamped to the valid count."""
    inv = jnp.uint32(1) - rvalid.astype(jnp.uint32)
    r_order = jnp.lexsort((rk, inv))  # primary: valid rows first
    rk_ord = rk[r_order]
    n_r = jnp.sum(rvalid, dtype=jnp.int32)
    max_valid = rk_ord[jnp.maximum(n_r - 1, 0)]
    positions = jnp.arange(rk.shape[0], dtype=jnp.int32)
    rk_sorted = jnp.where(positions < n_r, rk_ord, max_valid)
    lo = jnp.searchsorted(rk_sorted, lk, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(rk_sorted, lk, side="right").astype(jnp.int32)
    lo = jnp.minimum(lo, n_r)
    hi = jnp.where(lvalid.astype(bool), jnp.minimum(hi, n_r), lo)
    return lo, hi, r_order


@functools.partial(jax.jit, static_argnames=("mesh",))
def _count_program(lk, lvalid, rk, rvalid, *, mesh):
    def body(lk, lvalid, rk, rvalid):
        lo, hi, _ = _ranges_local(lk, lvalid, rk, rvalid)
        return jnp.sum(hi - lo, dtype=jnp.int32)[None]

    spec = P(SHARD_AXIS)
    return _shard_map(body, mesh=mesh, in_specs=(spec,) * 4,
                      out_specs=spec)(lk, lvalid, rk, rvalid)


@functools.partial(jax.jit, static_argnames=("capacity", "mesh"))
def _materialize_program(lk, lvalid, rk, rvalid, *, capacity, mesh):
    def body(lk, lvalid, rk, rvalid):
        lo, hi, r_order = _ranges_local(lk, lvalid, rk, rvalid)
        counts = hi - lo
        total = jnp.sum(counts, dtype=jnp.int32)
        left_idx = jnp.repeat(jnp.arange(lo.shape[0], dtype=jnp.int32), counts,
                              total_repeat_length=capacity)
        starts = jnp.cumsum(counts) - counts
        within = jnp.arange(capacity, dtype=jnp.int32) - jnp.repeat(
            starts.astype(jnp.int32), counts, total_repeat_length=capacity)
        right_pos = lo[left_idx] + within
        right_idx = r_order[jnp.clip(right_pos, 0, r_order.shape[0] - 1)]
        return (left_idx, right_idx.astype(jnp.int32), total[None])

    spec = P(SHARD_AXIS)
    return _shard_map(body, mesh=mesh, in_specs=(spec,) * 4,
                      out_specs=(spec, spec, spec))(lk, lvalid, rk, rvalid)


def copartitioned_join(
    left_keys: np.ndarray, right_keys: np.ndarray, mesh,
) -> Tuple[np.ndarray, np.ndarray]:
    """Inner equi-join of DENSE co-partitioned key shards.

    ``left_keys``/``right_keys`` are (D, L) / (D, R) arrays: row i of each
    holds device i's shard and EVERY slot is a real key (all slots join).
    For ragged shards with trailing padding use ``copartitioned_join_ragged``,
    which tracks per-slot validity.  Returns GLOBAL (left, right) index
    pairs into the flattened (D*L,) / (D*R,) arrays.
    """
    D, L = left_keys.shape
    _, R = right_keys.shape
    lk = np.ascontiguousarray(left_keys).reshape(D * L)
    rk = np.ascontiguousarray(right_keys).reshape(D * R)
    lvalid = np.ones(D * L, np.int32)
    rvalid = np.ones(D * R, np.int32)
    return _copartitioned_join_padded(lk, lvalid, rk, rvalid, D, L, R, mesh)


def copartitioned_join_ragged(
    left_shards, right_shards, mesh,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Join ragged per-device key shards (lists of 1-D arrays, one per mesh
    device).  Returns (device_ids, left_local, right_local): for each match,
    the owning device and the row positions within that device's input
    shards.  Keys on different devices never match — that's the
    co-partitioning invariant the bucket layout guarantees."""
    D = len(left_shards)
    Lmax = max(max((len(v) for v in left_shards), default=0), 1)
    Rmax = max(max((len(v) for v in right_shards), default=0), 1)
    lk = np.zeros((D, Lmax), dtype=np.asarray(left_shards[0]).dtype)
    rk = np.zeros((D, Rmax), dtype=np.asarray(right_shards[0]).dtype)
    lvalid = np.zeros((D, Lmax), np.int32)
    rvalid = np.zeros((D, Rmax), np.int32)
    for i in range(D):
        lk[i, :len(left_shards[i])] = left_shards[i]
        lvalid[i, :len(left_shards[i])] = 1
        rk[i, :len(right_shards[i])] = right_shards[i]
        rvalid[i, :len(right_shards[i])] = 1
    li, ri = _copartitioned_join_padded(
        lk.reshape(-1), lvalid.reshape(-1), rk.reshape(-1), rvalid.reshape(-1),
        D, Lmax, Rmax, mesh)
    return li // Lmax, li % Lmax, ri % Rmax


def _copartitioned_join_padded(lk, lvalid, rk, rvalid, D, L, R, mesh):
    from hyperspace_tpu.telemetry import timeline

    # Scoped x64: int64 join keys keep full width (see ops/join.py).
    t0 = timeline.kernel_begin()
    with _enable_x64():
        counts = sync_guard.pull(
            _count_program(lk, lvalid, rk, rvalid, mesh=mesh),
            "mesh_join.counts")
        capacity = int(counts.max()) if counts.size else 0
        if capacity == 0:
            timeline.kernel_end("mesh_join", t0, None,
                                devices=list(mesh.devices.flat))
            return np.empty(0, np.int64), np.empty(0, np.int64)
        capacity = round_up_pow2(capacity)
        li, ri, totals = _materialize_program(
            lk, lvalid, rk, rvalid, capacity=capacity, mesh=mesh)
    timeline.kernel_end("mesh_join", t0, (li, ri, totals),
                        devices=list(mesh.devices.flat))
    li = sync_guard.pull(li, "mesh_join.li").reshape(D, capacity)
    ri = sync_guard.pull(ri, "mesh_join.ri").reshape(D, capacity)
    totals = sync_guard.pull(totals, "mesh_join.totals").reshape(D)
    out_l, out_r = [], []
    for d in range(D):
        t = int(totals[d])
        out_l.append(li[d, :t].astype(np.int64) + d * L)
        out_r.append(ri[d, :t].astype(np.int64) + d * R)
    return np.concatenate(out_l), np.concatenate(out_r)
