"""Distributed covering-index build over the device mesh.

Single chip, the build is one fused kernel (ops/sort.bucket_sort_permutation).
Across a mesh it becomes: shard rows over devices → hash → all_to_all bucket
shuffle → per-device lexsort (parallel/shuffle.py) — the direct analog of
Spark's scan + hash-shuffle + per-task sort (actions/CreateActionBase.scala:
124-142), with ICI in place of the TCP shuffle service (SURVEY.md §2.4).

The host-facing contract matches the single-chip kernel: a (bucket_ids,
perm) pair feeding ``io.parquet.write_bucketed``, so the action layer is
agnostic to how many chips did the work.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np
import pyarrow as pa

from hyperspace_tpu.io import columnar
from hyperspace_tpu.parallel.shuffle import bucket_shuffle


def distributed_bucket_sort_permutation(
    table: pa.Table,
    indexed_columns: Sequence[str],
    num_buckets: int,
    mesh,
    slack: float = 1.5,
    pad_to: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """(bucket_ids, perm) for ``table`` computed over ``mesh``.

    Equivalent ordering contract to ``ops.sort.bucket_sort_permutation``:
    ``perm`` orders rows by (bucket, indexed columns) and ``bucket_ids``
    are per-row (pre-permutation) bucket assignments.  ``pad_to`` quantizes
    the per-device shard length so different dataset sizes share one
    compiled program (same knob as the single-chip kernel).

    Z-order builds never come here: their permutation is the host argsort
    of the precomputed Morton codes (actions/create._write_table_bucketed)
    — a hash shuffle would fragment the curve into per-partition samples.
    """
    hash_words = [columnar.to_hash_words(table.column(c)) for c in indexed_columns]
    order_words = [columnar.to_order_words(table.column(c))
                   for c in indexed_columns]
    result, _ = bucket_shuffle(hash_words, order_words, num_buckets, mesh,
                               slack=slack, pad_local_to=pad_to)
    n = table.num_rows
    bucket_ids = np.empty(n, dtype=np.int32)
    bucket_ids[result.perm] = result.buckets_sorted
    return bucket_ids, result.perm
