"""Distributed bucket shuffle: capacity-padded all_to_all over the mesh.

This is the TPU-native re-expression of the reference's cluster-wide hash
shuffle (``repartition(numBuckets, indexedCols)``,
actions/CreateActionBase.scala:131-132; Spark moves rows executor→executor
over TCP).  Here every device:

  1. hashes its local rows to buckets (same uint32 kernel as single-chip,
     ops/hash.py) and maps each bucket to its owning device — buckets are
     RANGE-partitioned over the mesh so each device emits a contiguous,
     sorted run of buckets for the writer,
  2. scatters rows into a fixed-capacity send buffer laid out as
     ``(n_devices * capacity, words)`` — the MoE-dispatch pattern: XLA needs
     static shapes, so per-destination space is padded to ``capacity`` and
     overflow is *counted* rather than sent (the host retries with doubled
     capacity — see ``bucket_shuffle``),
  3. exchanges buffers with ONE ``lax.all_to_all`` riding ICI,
  4. lexsorts its received rows by (bucket, order words) — after which every
     device holds its buckets' rows fully sorted, ready for the bucketed
     Parquet writer.

Everything on device is uint32 words (hash words, monotone order words,
row-id words), so one compiled program serves any key schema — and no x64
emulation is involved on TPU.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.4.35 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from hyperspace_tpu.execution import sync_guard
from hyperspace_tpu.io.columnar import join_words64, split_words64
from hyperspace_tpu.ops.hash import _bucket_ids_impl, use_pallas
from hyperspace_tpu.parallel.mesh import SHARD_AXIS


class ShuffleResult(NamedTuple):
    """Host-side view of a completed shuffle.

    ``perm``/``buckets_sorted`` follow the same contract as the single-chip
    ``bucket_sort_permutation``: ``perm`` lists original row indices in
    (bucket, key) order; ``buckets_sorted[i]`` is the bucket of row
    ``perm[i]``.  ``device_row_counts[d]`` says how many of those rows were
    produced (and are held) by mesh device ``d`` — the writer uses it to
    emit per-device file groups without re-partitioning.
    """

    perm: np.ndarray
    buckets_sorted: np.ndarray
    device_row_counts: np.ndarray
    capacity: int


def scatter_to_buffer(record, dest, n_dest: int, capacity: int):
    """Pack ``record`` rows into an ``(n_dest * capacity)`` send buffer by
    destination (the MoE-dispatch pattern: static shapes, overflow COUNTED
    rather than sent).  ``dest == n_dest`` drops the row (padding).
    Shared by the flat and hierarchical shuffle kernels — both must pack
    identically for their outputs to be bit-identical."""
    n = record.shape[0]
    order = jnp.argsort(dest, stable=True)
    sorted_dest = dest[order]
    rank = jnp.arange(n, dtype=jnp.int32) - jnp.searchsorted(
        sorted_dest, sorted_dest, side="left").astype(jnp.int32)
    in_window = (rank < capacity) & (sorted_dest < n_dest)
    overflow = jnp.sum((rank >= capacity) & (sorted_dest < n_dest),
                       dtype=jnp.int32)
    slot = jnp.where(in_window, sorted_dest * capacity + rank,
                     n_dest * capacity)
    send = jnp.zeros((n_dest * capacity, record.shape[1]), jnp.uint32)
    send = send.at[slot].set(record[order], mode="drop")
    return send, overflow


def make_row_records(hash_words, order_words, row_words, payload, bucket):
    """The on-wire row record both kernels route:
    [flag, bucket, row_hi, row_lo, order words..., payload...]."""
    L = hash_words.shape[0]
    return jnp.concatenate([
        jnp.ones((L, 1), jnp.uint32),
        bucket.astype(jnp.uint32)[:, None],
        row_words,
        order_words,
        payload,
    ], axis=1)


def sort_received(recv, n_key_cols: int):
    """Per-device final order: valid first, then (bucket, order words),
    with the GLOBAL ROW ID as the final tiebreak — arrival order in the
    receive buffer depends on the traffic pattern, so without it equal
    keys would order differently across topologies (flat vs hierarchical
    shuffle); with it, ties come out in original row order, matching the
    single-chip kernel's stable sort exactly.  Returns (sorted rows,
    valid count)."""
    flag = recv[:, 0]
    rbucket = recv[:, 1]
    keys: List[jnp.ndarray] = [recv[:, 3], recv[:, 2]]  # row lo, hi
    for k in reversed(range(n_key_cols)):
        keys.append(recv[:, 4 + 2 * k + 1])  # lo
        keys.append(recv[:, 4 + 2 * k])      # hi
    keys.append(rbucket)
    keys.append(jnp.uint32(1) - flag)        # primary: invalid rows last
    perm = jnp.lexsort(tuple(keys))
    return recv[perm], jnp.sum(flag, dtype=jnp.int32)


def _route_kernel(num_buckets: int, num_devices: int, capacity: int,
                  n_key_cols: int, pallas: bool,
                  hash_words, order_words, row_words, payload, valid):
    """Per-device body run under shard_map.  All inputs are the LOCAL shard:
    hash_words (L, 2K), order_words (L, 2K), row_words (L, 2), payload
    (L, E), valid (L,) int32."""
    word_cols = tuple(hash_words[:, 2 * k:2 * k + 2] for k in range(n_key_cols))
    bucket = _bucket_ids_impl(word_cols, num_buckets, pallas)
    buckets_per_device = -(-num_buckets // num_devices)  # ceil
    dest = bucket // buckets_per_device
    dest = jnp.where(valid.astype(bool), dest, num_devices)  # sentinel: drop
    record = make_row_records(hash_words, order_words, row_words, payload,
                              bucket)
    send, overflow = scatter_to_buffer(record, dest, num_devices, capacity)
    recv = jax.lax.all_to_all(send, SHARD_AXIS, split_axis=0, concat_axis=0,
                              tiled=True)
    out, count = sort_received(recv, n_key_cols)
    return out, count[None], overflow[None]


@functools.partial(
    jax.jit,
    static_argnames=("num_buckets", "num_devices", "capacity", "n_key_cols",
                     "mesh", "pallas"))
def _shuffle_program(hash_words, order_words, row_words, payload, valid, *,
                     num_buckets, num_devices, capacity, n_key_cols, mesh,
                     pallas):
    # ``pallas`` is part of the jit cache key so HYPERSPACE_TPU_PALLAS flips
    # between calls retrace instead of silently reusing the old kernel path.
    body = functools.partial(_route_kernel, num_buckets, num_devices,
                             capacity, n_key_cols, pallas)
    spec = P(SHARD_AXIS)
    return _shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec),
        out_specs=(spec, spec, spec),
    )(hash_words, order_words, row_words, payload, valid)


def bucket_shuffle(
    hash_words: Sequence[np.ndarray],
    order_words: Sequence[np.ndarray],
    num_buckets: int,
    mesh,
    payload_words: Optional[np.ndarray] = None,
    capacity: Optional[int] = None,
    slack: float = 1.5,
    pad_local_to: int = 0,
) -> Tuple[ShuffleResult, Optional[np.ndarray]]:
    """Run the distributed shuffle for ``n`` global rows.

    Args:
      hash_words: per key column (n, 2) uint32 arrays (columnar.to_hash_words).
      order_words: per key column (n, 2) uint32 arrays (columnar.to_order_words).
      num_buckets: bucket count (range-partitioned over mesh devices).
      mesh: 1-D mesh from parallel.mesh.build_mesh.
      payload_words: optional (n, E) uint32 extra words routed with each row
        (numeric column data for all-device pipelines).
      capacity: per-(src,dst) row capacity; None = balanced estimate with
        ``slack`` headroom, doubled on overflow until the shuffle fits.
      pad_local_to: when > 0, round the per-device shard length up to the
        next multiple so builds of different dataset sizes share one
        compiled program (the ``valid`` mask drops the padding) — the same
        capacity-padding contract as the single-chip kernel's ``pad_to``.

    Returns:
      (ShuffleResult, routed_payload) — routed_payload is (n, E) uint32 in
      ``perm`` order (None when no payload was given).
    """
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    ensure_persistent_xla_cache()
    n = hash_words[0].shape[0]
    n_devices = mesh.devices.size
    if n == 0:
        # Zero-row build (empty source): nothing to route.
        return empty_shuffle_result(n_devices, payload_words)
    n_key_cols = len(hash_words)
    hw, ow, rw, pl, valid, local = marshal_shuffle_inputs(
        hash_words, order_words, payload_words, n_devices, pad_local_to)

    if capacity is None:
        capacity = max(16, int(-(-local * slack // n_devices)))
    capacity = min(local, -(-capacity // 8) * 8)  # align, never beyond local

    while True:
        out, counts, overflow = _shuffle_program(
            hw, ow, rw, pl, valid,
            num_buckets=num_buckets, num_devices=n_devices, capacity=capacity,
            n_key_cols=n_key_cols, mesh=mesh, pallas=use_pallas())
        overflow_total = int(sync_guard.scalar(
            jnp.sum(overflow), "shuffle.overflow"))
        if overflow_total == 0:
            break
        if capacity >= local:  # cannot grow further; should be unreachable
            raise RuntimeError("bucket_shuffle: capacity overflow at maximum")
        capacity = min(local, capacity * 2)

    counts = sync_guard.pull(counts, "shuffle.counts").reshape(-1)
    perm, buckets_sorted, routed_payload = unpack_shuffle_output(
        sync_guard.pull(out, "shuffle.routed"), counts,
        n_devices, n_devices * capacity,
        n_key_cols, payload_words is not None)
    result = ShuffleResult(perm=perm, buckets_sorted=buckets_sorted,
                           device_row_counts=counts, capacity=capacity)
    return result, routed_payload


def empty_shuffle_result(n_devices: int, payload_words):
    return ShuffleResult(
        perm=np.empty(0, np.int64),
        buckets_sorted=np.empty(0, np.int32),
        device_row_counts=np.zeros(n_devices, np.int32),
        capacity=0,
    ), (np.empty((0, payload_words.shape[1]), np.uint32)
        if payload_words is not None else None)


def marshal_shuffle_inputs(hash_words, order_words, payload_words,
                           n_devices: int, pad_local_to: int):
    """Host-side input marshalling shared by the flat and hierarchical
    shuffles: concatenated uint32 word planes, global row-id words, the
    padded validity mask, and the per-device shard length."""
    n = hash_words[0].shape[0]
    local = -(-n // n_devices)  # rows per device, ceil
    if pad_local_to and pad_local_to > 0:
        quantum = max(1, -(-pad_local_to // n_devices))
        local = -(-local // quantum) * quantum
    padded = local * n_devices

    def pad(a: np.ndarray) -> np.ndarray:
        if a.shape[0] == padded:
            return a
        width = ((0, padded - a.shape[0]),) + ((0, 0),) * (a.ndim - 1)
        return np.pad(a, width)

    hw = pad(np.concatenate([np.asarray(w, np.uint32)
                             for w in hash_words], axis=1))
    ow = pad(np.concatenate([np.asarray(w, np.uint32)
                             for w in order_words], axis=1))
    rw = split_words64(np.arange(padded, dtype=np.uint64))
    pl = pad(np.asarray(payload_words, np.uint32)) \
        if payload_words is not None else np.zeros((padded, 0), np.uint32)
    valid = pad(np.ones(n, dtype=np.int32))
    return hw, ow, rw, pl, valid, local


def unpack_shuffle_output(out, counts, n_devices: int, rows_per_device: int,
                          n_key_cols: int, has_payload: bool):
    """Host-side output unpacking shared by both shuffles: per-device
    valid prefixes concatenate into (perm, buckets_sorted, payload)."""
    per_dev = out.reshape(n_devices, rows_per_device, -1)
    perm_parts, bucket_parts, payload_parts = [], [], []
    for d in range(n_devices):
        c = int(counts[d])
        rows = per_dev[d, :c]
        perm_parts.append(join_words64(rows[:, 2], rows[:, 3]).astype(np.int64))
        bucket_parts.append(rows[:, 1].astype(np.int32))
        if has_payload:
            payload_parts.append(rows[:, 4 + 2 * n_key_cols:])
    perm = np.concatenate(perm_parts) if perm_parts else np.empty(0, np.int64)
    buckets_sorted = np.concatenate(bucket_parts) if bucket_parts else \
        np.empty(0, np.int32)
    payload = np.concatenate(payload_parts) if has_payload else None
    return perm, buckets_sorted, payload
