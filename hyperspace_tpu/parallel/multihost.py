"""Multi-slice / multi-host distribution: DCN-aware mesh + hierarchical shuffle.

The reference's cluster story is Spark's shuffle service over TCP — flat:
every executor pair exchanges directly (SURVEY.md §2.4).  TPU pods are NOT
flat: chips within a slice talk over ICI (high bandwidth, low latency);
slices talk over DCN (data-center network — an order of magnitude slower).
A flat all_to_all over S slices x P chips issues S*P-1 messages per chip,
most of them over DCN.

``hierarchical_bucket_shuffle`` runs the bucket shuffle in TWO stages over
a 2-axis mesh ``("dcn", "ici")``:

  1. all_to_all over the DCN axis only: each chip sends every row straight
     to the row's DESTINATION SLICE (at its own intra-slice position) —
     S-1 large messages per chip on the slow link, each row crossing DCN
     exactly once;
  2. all_to_all over the ICI axis inside the destination slice: rows fan
     out to their final owner chip — P-1 messages on the fast link;
  3. the same per-device lexsort as the flat shuffle.

Bucket ownership is identical to the flat shuffle's (range partition over
the flattened (slice, chip) order), so the result is BIT-IDENTICAL to
``parallel.shuffle.bucket_shuffle`` on the same devices — only the traffic
pattern changes.  Capacity is padded per stage (the MoE-dispatch pattern)
with overflow counted and retried, like the flat path.

This collective path assumes every participant stays alive: a SIGKILLed
host poisons the all_to_all and wedges the survivors.  The
crash-TOLERANT cross-host build is ``parallel/multihost_build.py`` —
the same bucket-ownership contract (``sharded_build.bucket_group_bounds``)
executed through crash-recoverable work claims over the LogStore seam,
where losing a host costs one claim TTL, not the build.  Use this
module's collectives for healthy-pod throughput; use the claim build
when partial failure is in scope.

On real multi-host pods, call ``initialize_distributed()`` first (one
process per host; jax.distributed wires the DCN coordinator), then
``build_mesh_2d(n_slices, chips_per_slice)``.  Single-host validation uses
the same code over virtual CPU devices (tests/test_parallel.py runs 2x4
and 4x2 meshes); tests/test_multiprocess.py additionally wires TWO OS
processes through ``initialize_distributed`` over CPU and runs the
two-stage all_to_all pattern across the process boundary.  Remaining
pod-only gap: ``hierarchical_bucket_shuffle`` takes process-local numpy
inputs, so multi-process runs must feed each host its own shard (the
natural pod usage); the single entry point has not been driven end-to-end
across processes in this environment.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from hyperspace_tpu.execution import sync_guard
from hyperspace_tpu.ops.hash import _bucket_ids_impl, use_pallas
from hyperspace_tpu.parallel.shuffle import (
    ShuffleResult,
    empty_shuffle_result,
    make_row_records,
    marshal_shuffle_inputs,
    scatter_to_buffer,
    sort_received,
    unpack_shuffle_output,
)

DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Wire the multi-host runtime (one call per host process, before any
    other jax use).  With no arguments jax auto-detects the TPU pod
    environment; explicit arguments serve CPU/GPU clusters."""
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def build_mesh_2d(n_slices: int, chips_per_slice: Optional[int] = None,
                  devices: Optional[Sequence] = None) -> Mesh:
    """A 2-axis ``(dcn, ici)`` mesh: axis 0 crosses slices, axis 1 stays
    within one.  ``jax.devices()`` enumerates the full pod; flattened
    (slice-major) order matches the 1-axis mesh's device order, so bucket
    ownership agrees with the flat shuffle."""
    if devices is None:
        devices = jax.devices()
    if chips_per_slice is None:
        if len(devices) % n_slices:
            raise ValueError(
                f"{len(devices)} devices do not split into {n_slices} slices")
        chips_per_slice = len(devices) // n_slices
    devices = np.asarray(devices[:n_slices * chips_per_slice]).reshape(
        n_slices, chips_per_slice)
    return Mesh(devices, (DCN_AXIS, ICI_AXIS))


def _hier_kernel(num_buckets: int, S: int, Pn: int, cap_dcn: int,
                 cap_ici: int, n_key_cols: int, pallas: bool,
                 hash_words, order_words, row_words, payload, valid):
    """Per-device body under shard_map over the (dcn, ici) mesh.  Inputs
    are the LOCAL shard (L rows).  Record layout, scatter packing, and
    the final sort are SHARED with the flat kernel (parallel/shuffle.py)
    — that sharing is what makes the two shuffles bit-identical."""
    word_cols = tuple(hash_words[:, 2 * k:2 * k + 2]
                      for k in range(n_key_cols))
    bucket = _bucket_ids_impl(word_cols, num_buckets, pallas)
    n_devices = S * Pn
    buckets_per_device = -(-num_buckets // n_devices)
    owner = bucket // buckets_per_device           # global device id
    dest_slice = owner // Pn
    record = make_row_records(hash_words, order_words, row_words, payload,
                              bucket)

    # Stage 1 — DCN: rows go to their destination SLICE (at this chip's
    # own intra-slice position).  One row crosses DCN exactly once.
    d1 = jnp.where(valid.astype(bool), dest_slice, S)
    send1, over1 = scatter_to_buffer(record, d1, S, cap_dcn)
    recv1 = jax.lax.all_to_all(send1, DCN_AXIS, split_axis=0, concat_axis=0,
                               tiled=True)

    # Stage 2 — ICI: within the destination slice, rows fan out to their
    # final chip (recomputed from the bucket carried in the record).
    flag1 = recv1[:, 0]
    owner1 = recv1[:, 1].astype(jnp.int32) // buckets_per_device
    d2 = jnp.where(flag1.astype(bool), owner1 % Pn, Pn)
    send2, over2 = scatter_to_buffer(recv1, d2, Pn, cap_ici)
    recv2 = jax.lax.all_to_all(send2, ICI_AXIS, split_axis=0, concat_axis=0,
                               tiled=True)

    out, count = sort_received(recv2, n_key_cols)
    return out, count[None], jnp.stack([over1, over2])[None]


@functools.partial(
    jax.jit,
    static_argnames=("num_buckets", "n_slices", "per_slice", "cap_dcn",
                     "cap_ici", "n_key_cols", "mesh", "pallas"))
def _hier_program(hash_words, order_words, row_words, payload, valid, *,
                  num_buckets, n_slices, per_slice, cap_dcn, cap_ici,
                  n_key_cols, mesh, pallas):
    body = functools.partial(_hier_kernel, num_buckets, n_slices, per_slice,
                             cap_dcn, cap_ici, n_key_cols, pallas)
    spec = P((DCN_AXIS, ICI_AXIS))
    return _shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec),
        out_specs=(spec, spec, spec),
    )(hash_words, order_words, row_words, payload, valid)


def hierarchical_bucket_shuffle(
    hash_words: Sequence[np.ndarray],
    order_words: Sequence[np.ndarray],
    num_buckets: int,
    mesh: Mesh,
    payload_words: Optional[np.ndarray] = None,
    slack: float = 1.5,
    pad_local_to: int = 0,
) -> Tuple[ShuffleResult, Optional[np.ndarray]]:
    """Two-stage bucket shuffle over a ``build_mesh_2d`` mesh.  Same
    arguments and same ``ShuffleResult`` contract as
    ``parallel.shuffle.bucket_shuffle`` — and the same OUTPUT: bucket
    ownership uses the flattened device order, so flat and hierarchical
    runs on the same devices produce identical perms/buckets/counts."""
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    ensure_persistent_xla_cache()
    if tuple(mesh.axis_names) != (DCN_AXIS, ICI_AXIS):
        raise ValueError(
            f"hierarchical shuffle needs a (dcn, ici) mesh, got "
            f"{mesh.axis_names}")
    S, Pn = mesh.devices.shape
    n_devices = S * Pn
    n = hash_words[0].shape[0]
    if n == 0:
        return empty_shuffle_result(n_devices, payload_words)
    n_key_cols = len(hash_words)
    hw, ow, rw, pl, valid, local = marshal_shuffle_inputs(
        hash_words, order_words, payload_words, n_devices, pad_local_to)

    # Stage capacities: DCN buffers hold one device's rows for one SLICE
    # (balanced ~local/S); ICI buffers hold one device's staged rows for
    # one final chip (staged total is up to S*cap_dcn, split P ways).
    cap_dcn = max(16, int(-(-local * slack // S)))
    cap_dcn = min(local, -(-cap_dcn // 8) * 8)
    cap_ici = max(16, int(-(-S * cap_dcn * slack // Pn)))
    cap_ici = min(S * cap_dcn, -(-cap_ici // 8) * 8)

    while True:
        out, counts, overflows = _hier_program(
            hw, ow, rw, pl, valid,
            num_buckets=num_buckets, n_slices=S, per_slice=Pn,
            cap_dcn=cap_dcn, cap_ici=cap_ici, n_key_cols=n_key_cols,
            mesh=mesh, pallas=use_pallas())
        over = sync_guard.pull(
            overflows, "shuffle.overflows").reshape(n_devices, 2).sum(axis=0)
        if over[0] == 0 and over[1] == 0:
            break
        grew = False
        if over[0] and cap_dcn < local:
            cap_dcn = min(local, cap_dcn * 2)
            grew = True
        if (over[1] or over[0]) and cap_ici < S * cap_dcn:
            # A DCN overflow changes the staged volume too.
            cap_ici = min(S * cap_dcn, cap_ici * 2)
            grew = True
        if not grew:
            raise RuntimeError(
                "hierarchical_bucket_shuffle: capacity overflow at maximum")

    counts = sync_guard.pull(counts, "shuffle.counts").reshape(-1)
    perm, buckets_sorted, routed_payload = unpack_shuffle_output(
        sync_guard.pull(out, "shuffle.routed"), counts, n_devices,
        Pn * cap_ici, n_key_cols, payload_words is not None)
    return ShuffleResult(perm=perm, buckets_sorted=buckets_sorted,
                         device_row_counts=counts,
                         capacity=cap_ici), routed_payload
