"""Mesh-sharded predicate evaluation: the distributed scan/filter path.

Reference analog: Spark evaluates predicates inside each executor's task
over its file split (SURVEY.md §2.4 "predicate-pushdown kernel").  Here
the predicate is one elementwise XLA program (ops/filter.compile_predicate)
whose inputs are sharded row-wise over the device mesh; GSPMD partitions
the program with ZERO collectives — each device scans 1/N of the rows in
its own HBM and only the boolean mask returns to host.

Scope: the mesh spans THIS process's addressable devices
(``jax.local_devices()``) — the filter input is a host-resident arrow
batch, which a single process owns; sharding it across other hosts'
devices is not addressable.  Multi-host scans parallelize one level up,
by giving each host its own file split.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as np

from hyperspace_tpu.execution import sync_guard
from hyperspace_tpu.parallel.mesh import SHARD_AXIS, build_mesh
from hyperspace_tpu.utils.compat import enable_x64 as _enable_x64


def eval_predicate_on_mesh(fn: Callable, columns: Sequence[np.ndarray],
                           literals: List[float], mesh=None) -> np.ndarray:
    """Boolean mask for ``fn(columns, literals)`` with ``columns`` sharded
    row-wise over ``mesh`` (this process's devices by default).  Rows are
    padded up to a device multiple — only the LAST shard is copied for the
    pad; every other shard transfers zero-copy views — and the pad is
    sliced off the mask.  x64 is scoped here so int64 columns keep full
    width regardless of the caller."""
    import jax

    with _enable_x64():
        from jax.sharding import NamedSharding, PartitionSpec

        if mesh is None:
            mesh = build_mesh(devices=jax.local_devices())
        devices = list(mesh.devices.flat)
        n_dev = len(devices)
        n = int(columns[0].shape[0])
        shard_rows = -(-n // n_dev)
        sharding = NamedSharding(mesh, PartitionSpec(SHARD_AXIS))
        sharded = []
        for c in columns:
            c = np.asarray(c)
            parts = []
            for i, dev in enumerate(devices):
                piece = c[i * shard_rows:min(n, (i + 1) * shard_rows)]
                if piece.shape[0] < shard_rows:
                    piece = np.concatenate(
                        [piece, np.zeros(shard_rows - piece.shape[0],
                                         dtype=c.dtype)])
                parts.append(jax.device_put(piece, dev))
            sharded.append(jax.make_array_from_single_device_arrays(
                (shard_rows * n_dev,), sharding, parts))
        mask = fn(sharded, literals)
        return sync_guard.pull(mask, "mesh_filter.mask")[:n]
