"""Device-mesh construction for the distributed data plane.

The reference's distribution substrate is the Spark cluster (driver plans,
executors shuffle over TCP — SURVEY.md §2.4); ours is a
``jax.sharding.Mesh`` whose collectives ride ICI within a slice and DCN
across slices.  One axis name is used throughout the engine:

  - ``"shard"`` — the data axis.  Rows are sharded over it during the build
    scan; buckets are range-partitioned over it after the shuffle, and index
    shards stay aligned to it so the bucketed join needs no communication.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def build_mesh(n_devices: Optional[int] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` visible devices (all by
    default).  Multi-host: ``jax.devices()`` already enumerates the full
    slice, so the same call scales from one chip to a pod."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))
