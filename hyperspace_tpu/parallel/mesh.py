"""Device-mesh construction and the rule-driven sharding layer.

The reference's distribution substrate is the Spark cluster (driver plans,
executors shuffle over TCP — SURVEY.md §2.4); ours is a
``jax.sharding.Mesh`` whose collectives ride ICI within a slice and DCN
across slices.  One axis name is used throughout the engine:

  - ``"shard"`` — the data axis.  Rows are sharded over it during the build
    scan; buckets are MOD-partitioned over it after routing (device ``d``
    owns every bucket with ``bucket_id % n_devices == d``), and index
    shards stay aligned to it so the bucketed join needs no communication.

Three layers sit on top of the bare mesh:

  - **the rule table** (:data:`PARTITION_RULES` +
    :func:`match_partition_rules`): array NAMES map to
    ``PartitionSpec``s by regex, the ``match_partition_rules`` idiom of
    pjit training stacks — one reviewable place that says "hash words
    shard row-wise, counts are per-device, everything else replicates"
    instead of specs scattered through every kernel wrapper.
  - **shard/gather fns** (:func:`make_shard_and_gather_fns`): per named
    array, a shard fn that places a host array onto the mesh under
    ``NamedSharding`` and a gather fn that pulls it back through the
    attributed ``sync_guard.pull`` seam — the host gather seam every
    mesh kernel funnels its outputs through, so d2h traffic stays
    visible to the sync guard and the ``exec.transfer.d2h.bytes``
    metric.
  - **the conf gate** (:func:`active_mesh`):
    ``hyperspace.parallel.mesh.enabled`` — ``auto`` (the default) builds
    the mesh when >1 local device is visible, ``off`` pins every caller
    to the bit-equal single-device path, ``maxDevices`` caps the span.
    Callers treat ``None`` as "no mesh": the sharded paths are never
    half-taken.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

SHARD_AXIS = "shard"

# Name-pattern -> PartitionSpec, first match wins (SNIPPETS [2]/[3]'s
# ``match_partition_rules`` shape).  Row-wise data planes shard over the
# data axis; per-device scalars (counts, overflow flags) are one slot per
# device, which on a 1-D mesh is the same row sharding; everything else
# replicates.
PARTITION_RULES: Tuple[Tuple[str, P], ...] = (
    (r"^(hash|order|key|row)_words$", P(SHARD_AXIS)),
    (r"^(payload|valid|codes|values|value_cols)$", P(SHARD_AXIS)),
    (r"^(routed|records|recv|mask|perm|boundaries)$", P(SHARD_AXIS)),
    (r"^(counts|overflow|totals|n_groups|n_valid)$", P(SHARD_AXIS)),
    (r".", P()),  # replicate by default (literals, thresholds)
)


def match_partition_rules(names: Sequence[str],
                          rules: Sequence[Tuple[str, P]] = PARTITION_RULES,
                          ) -> Dict[str, P]:
    """PartitionSpec per array name, first matching rule wins.

    Unlike the training-stack original there is no pytree walk — the
    engine's kernels take flat, named word planes — but the contract is
    the same: every name MUST match a rule (the catch-all replicate rule
    makes silence impossible only because it is last and explicit), and
    the table, not the call site, owns the placement decision.
    """
    out: Dict[str, P] = {}
    for name in names:
        for pattern, spec in rules:
            if re.search(pattern, name) is not None:
                out[name] = spec
                break
        else:
            raise ValueError(f"No partition rule matches array {name!r}")
    return out


def make_shard_and_gather_fns(mesh: Mesh,
                              specs: Dict[str, P],
                              site: str = "mesh"):
    """(shard_fns, gather_fns) keyed like ``specs``.

    ``shard_fns[name](host_array)`` places the array onto ``mesh`` under
    ``NamedSharding(mesh, specs[name])`` (the caller pads the sharded
    axis to a device multiple first — ``marshal_shuffle_inputs`` already
    guarantees that for the word planes).  ``gather_fns[name](jax_array)``
    is the HOST GATHER SEAM: one attributed ``sync_guard.pull`` per
    array, site-named ``<site>.<name>`` so the d2h transfer is
    guard-legal and metric-counted.
    """
    from hyperspace_tpu.execution import sync_guard

    def make_shard_fn(spec: P):
        sharding = NamedSharding(mesh, spec)

        def shard_fn(x):
            return jax.device_put(x, sharding)

        return shard_fn

    def make_gather_fn(name: str):
        def gather_fn(x):
            return sync_guard.pull(x, f"{site}.{name}")

        return gather_fn

    shard_fns = {name: make_shard_fn(spec) for name, spec in specs.items()}
    gather_fns = {name: make_gather_fn(name) for name in specs}
    return shard_fns, gather_fns


def build_mesh(n_devices: Optional[int] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` visible devices (all by
    default).  Multi-host: ``jax.devices()`` already enumerates the full
    slice, so the same call scales from one chip to a pod."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def mesh_mode(conf) -> str:
    """Validated ``hyperspace.parallel.mesh.enabled`` value."""
    mode = str(getattr(conf, "mesh_enabled", "auto")).lower()
    if mode in ("true", "on"):
        return "on"
    if mode in ("false", "off"):
        return "off"
    if mode != "auto":
        from hyperspace_tpu.exceptions import HyperspaceError

        raise HyperspaceError(
            f"Invalid {mode!r} for hyperspace.parallel.mesh.enabled; "
            f"expected 'auto', 'on', or 'off'")
    return mode


def active_mesh(conf=None) -> Optional[Mesh]:
    """The engine mesh per conf, or None when the sharded paths must not
    run (mesh off, or fewer than 2 devices — a 1-device mesh has nothing
    to shard and the single-device kernels are the bit-equal reference).

    The mesh spans THIS process's addressable devices
    (``jax.local_devices()``): every sharded kernel's inputs are
    host-resident arrays, which only local devices can be fed from.
    ``maxDevices`` (> 0) caps the span.
    """
    mode = mesh_mode(conf) if conf is not None else "auto"
    if mode == "off":
        return None
    devices = list(jax.local_devices())
    cap = int(getattr(conf, "mesh_max_devices", 0) or 0) \
        if conf is not None else 0
    if cap > 0:
        devices = devices[:cap]
    if len(devices) < 2:
        return None
    return Mesh(np.asarray(devices), (SHARD_AXIS,))
