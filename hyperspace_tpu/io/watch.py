"""Push-based source change detection: the watch seam (docs/19).

The lifecycle daemon (PR 10) polls: it sleeps
``hyperspace.lifecycle.intervalS`` between cycles, so measured
staleness is bounded by the poll interval no matter how fast
``detect_changes`` is.  This module turns source mutations into WAKE
events so the daemon runs its next cycle when something actually
changed and staleness is bounded by event latency instead.

Three backends behind one :class:`SourceWatcher` interface
(``hyperspace.system.watch.mode``):

  - ``inotify`` — Linux kernel file notification via ctypes on libc
    (no dependency).  Watches each source root's CHANGE DIRECTORY:
    ``_delta_log`` for Delta tables, ``metadata`` for Iceberg tables
    (their commit protocols funnel every mutation through one
    directory), the root itself for plain file dirs.
  - ``store`` — object-store notification, emulated over the PR 2
    LogStore seam: writers call :func:`publish` after a commit, which
    appends a marker under ``<systemPath>/_hyperspace_watch``; the
    watcher polls that TINY store (a bounded key list, not the
    source tree) and emits an event per unseen marker.  This is the
    shape S3/GCS bucket notifications take when the source lives in
    an object store and inotify has nothing to watch.
  - ``poll`` — stat-level fingerprint of each change directory every
    ``pollIntervalS``; the universal fallback.

``mode="auto"`` picks inotify when the kernel offers it, else store.
Events are DEBOUNCED (``debounceMs``): a burst of commits coalesces
into one wake, so a hot writer cannot hot-loop the daemon.  Every
backend degrades to a no-event watcher rather than raising — losing
push detection must never cost more than falling back to the
interval poll the daemon still runs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

WATCH_DIR = "_hyperspace_watch"
_MARKER_CAP = 256  # notification-bus bound: oldest markers pruned

# inotify constants (linux/inotify.h; stable ABI across architectures).
_IN_MODIFY = 0x00000002
_IN_CLOSE_WRITE = 0x00000008
_IN_MOVED_FROM = 0x00000040
_IN_MOVED_TO = 0x00000080
_IN_CREATE = 0x00000100
_IN_DELETE = 0x00000200
_IN_MASK = (_IN_MODIFY | _IN_CLOSE_WRITE | _IN_MOVED_FROM
            | _IN_MOVED_TO | _IN_CREATE | _IN_DELETE)
_IN_NONBLOCK = 0o4000  # == O_NONBLOCK on Linux


@dataclasses.dataclass(frozen=True)
class WatchEvent:
    """One observed source mutation: which root, what the backend saw."""

    root: str
    detail: str = ""
    ts: float = 0.0


def change_dir(root: str) -> str:
    """The directory a source's mutations funnel through: a lake
    table's commit log when present, the root itself otherwise."""
    for sub in ("_delta_log", "metadata"):
        p = os.path.join(root, sub)
        if os.path.isdir(p):
            return p
    return root


# ---------------------------------------------------------------------------
# The store notification bus (object-store notification, emulated)
# ---------------------------------------------------------------------------
def watch_store_root(conf) -> str:
    from hyperspace_tpu.index.path_resolver import PathResolver

    return os.path.join(PathResolver(conf).system_path, WATCH_DIR)


def _store(conf):
    from hyperspace_tpu.telemetry.perf_ledger import store_for

    return store_for(conf, watch_store_root(conf))


_seq_lock = threading.Lock()
_seq = 0


def _next_key() -> str:
    global _seq
    with _seq_lock:
        _seq += 1
        seq = _seq
    return f"w-{int(time.time() * 1000):013d}-{os.getpid()}-{seq:05d}"


def publish(conf, root: str, detail: str = "") -> Optional[str]:
    """Publish one change marker for ``root`` on the notification bus;
    returns its key, or None on failure.  Never raises and runs
    fault-quiet (same contract as the lifecycle journal: losing a
    notification costs one poll interval, not a commit)."""
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.telemetry import metrics

    try:
        with faults.quiet():
            store = _store(conf)
            payload = json.dumps({
                "root": os.path.abspath(root), "detail": detail,
                "ts": time.time()}).encode("utf-8")
            key = None
            for _ in range(4):
                key = _next_key()
                if store.put_if_absent(key, payload):
                    break
            else:
                return None
            keys = store.list_keys()
            if len(keys) > _MARKER_CAP:
                for old in sorted(keys)[:len(keys) - _MARKER_CAP]:
                    store.delete(old)
            metrics.inc("lifecycle.watch.publishes")
            return key
    except Exception:  # noqa: BLE001 — the bus is advisory
        return None


# ---------------------------------------------------------------------------
# Backends
# ---------------------------------------------------------------------------
class _PollBackend:
    """Stat-level fingerprints of each change directory."""

    name = "poll"

    def __init__(self, roots: Sequence[str]) -> None:
        self._roots = list(roots)
        self._prints: Dict[str, tuple] = {
            r: self._fingerprint(r) for r in self._roots}

    @staticmethod
    def _fingerprint(root: str) -> tuple:
        d = change_dir(root)
        try:
            with os.scandir(d) as it:
                entries = tuple(sorted(
                    (e.name, e.stat(follow_symlinks=False).st_size,
                     e.stat(follow_symlinks=False).st_mtime_ns)
                    for e in it))
        except OSError:
            entries = ()
        return entries

    def collect(self) -> List[WatchEvent]:
        out: List[WatchEvent] = []
        for root in self._roots:
            fp = self._fingerprint(root)
            if fp != self._prints[root]:
                self._prints[root] = fp
                out.append(WatchEvent(root, "poll: listing changed",
                                      time.time()))
        return out

    def close(self) -> None:
        pass


class _StoreBackend:
    """Unseen markers on the notification bus → events."""

    name = "store"

    def __init__(self, conf, roots: Sequence[str]) -> None:
        self._conf = conf
        self._roots = {os.path.abspath(r) for r in roots}
        self._seen = set(self._list())

    def _list(self) -> List[str]:
        from hyperspace_tpu.io import faults

        try:
            with faults.quiet():
                return _store(self._conf).list_keys()
        except Exception:  # noqa: BLE001 — an unreadable bus reads empty
            return []

    def collect(self) -> List[WatchEvent]:
        from hyperspace_tpu.io import faults

        out: List[WatchEvent] = []
        for key in sorted(self._list()):
            if key in self._seen:
                continue
            self._seen.add(key)
            root, detail, ts = "", "", time.time()
            try:
                with faults.quiet():
                    rec = json.loads(
                        _store(self._conf).read(key).decode("utf-8"))
                root = str(rec.get("root", ""))
                detail = str(rec.get("detail", ""))
                ts = float(rec.get("ts", ts))
            except Exception:  # noqa: BLE001 — a torn marker still wakes
                pass
            # No roots configured = wake on any marker; otherwise only
            # markers for a watched root count.
            if not self._roots or not root or root in self._roots:
                out.append(WatchEvent(root, detail or f"marker {key}", ts))
        return out

    def close(self) -> None:
        pass


class _InotifyBackend:
    """Linux inotify via ctypes; raises OSError when unavailable so
    the watcher can fall back."""

    name = "inotify"

    def __init__(self, roots: Sequence[str]) -> None:
        import ctypes
        import ctypes.util

        libc_name = ctypes.util.find_library("c") or "libc.so.6"
        libc = ctypes.CDLL(libc_name, use_errno=True)
        for fn in ("inotify_init1", "inotify_add_watch"):
            if not hasattr(libc, fn):
                raise OSError(f"libc lacks {fn}")
        self._libc = libc
        fd = libc.inotify_init1(_IN_NONBLOCK)
        if fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")
        self._fd = fd
        self._wd_to_root: Dict[int, str] = {}
        try:
            for root in roots:
                d = change_dir(root)
                wd = libc.inotify_add_watch(
                    fd, os.fsencode(d), _IN_MASK)
                if wd < 0:
                    raise OSError(ctypes.get_errno(),
                                  f"inotify_add_watch({d}) failed")
                self._wd_to_root[wd] = root
        except OSError:
            os.close(fd)
            raise

    def collect(self) -> List[WatchEvent]:
        import select
        import struct

        try:
            readable, _, _ = select.select([self._fd], [], [], 0)
        except OSError:
            return []
        if not readable:
            return []
        try:
            buf = os.read(self._fd, 65536)
        except (BlockingIOError, OSError):
            return []
        out: List[WatchEvent] = []
        off, now = 0, time.time()
        while off + 16 <= len(buf):
            wd, mask, _cookie, name_len = struct.unpack_from("iIII", buf,
                                                             off)
            name = buf[off + 16: off + 16 + name_len].split(b"\0", 1)[0]
            off += 16 + name_len
            root = self._wd_to_root.get(wd)
            if root is not None:
                out.append(WatchEvent(
                    root, f"inotify {mask:#x} {os.fsdecode(name)}", now))
        return out

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# The watcher
# ---------------------------------------------------------------------------
class SourceWatcher:
    """One background thread multiplexing a watch backend into a wake
    :class:`threading.Event` the daemon sleeps on.

    ``collect → debounce → record + wake`` every
    ``hyperspace.system.watch.pollIntervalS`` (inotify pays only the
    zero-timeout select per tick; poll/store pay their small stat/list).
    Construction never raises: a backend that cannot initialize
    downgrades (inotify → poll) and the resolved mode is readable via
    :attr:`mode`.
    """

    def __init__(self, conf, roots: Sequence[str],
                 wake: Optional[threading.Event] = None,
                 mode: Optional[str] = None) -> None:
        self.conf = conf
        self.roots = [os.path.abspath(r) for r in roots]
        self.wake = wake if wake is not None else threading.Event()
        self._events: List[WatchEvent] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        requested = (mode or str(getattr(conf, "watch_mode", "auto"))
                     or "auto").lower()
        self._backend = self._make_backend(requested)

    def _make_backend(self, requested: str):
        if requested in ("inotify", "auto"):
            try:
                return _InotifyBackend(self.roots)
            except OSError:
                if requested == "inotify":
                    # Forced but unavailable: degrade to poll, never raise.
                    return _PollBackend(self.roots)
        if requested == "store" or requested == "auto":
            return _StoreBackend(self.conf, self.roots)
        return _PollBackend(self.roots)

    @property
    def mode(self) -> str:
        """The backend actually running (after auto/downgrade)."""
        return self._backend.name

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SourceWatcher":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="hs-source-watcher", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        self._backend.close()

    def drain(self) -> List[WatchEvent]:
        """Events observed since the last drain (consumes them)."""
        with self._lock:
            out, self._events = self._events, []
        return out

    # -- the watch loop ------------------------------------------------------
    def _run(self) -> None:
        from hyperspace_tpu.telemetry import metrics

        interval = max(0.01, float(getattr(self.conf,
                                           "watch_poll_interval_s", 0.5)))
        debounce_s = max(0.0, float(getattr(self.conf,
                                            "watch_debounce_ms", 50.0))
                         / 1000.0)
        while not self._stop.is_set():
            try:
                events = self._backend.collect()
                if events:
                    # Debounce: let the burst finish, sweep once more, then
                    # wake the daemon exactly once.
                    if debounce_s > 0:
                        self._stop.wait(debounce_s)
                        events.extend(self._backend.collect())
                    with self._lock:
                        self._events.extend(events)
                        del self._events[:-_MARKER_CAP]
                    metrics.inc("lifecycle.watch.events", len(events))
                    metrics.inc("lifecycle.watch.wakes")
                    self.wake.set()
            except Exception:  # noqa: BLE001 — a watcher tick must never
                # kill the thread; the daemon's interval poll still runs.
                metrics.inc("lifecycle.watch.errors")
            self._stop.wait(interval)
