"""Hive-style partition discovery: ``key=value`` path segments as columns.

Reference contract: partitioned relations are first-class — the relation
exposes a partition schema and base path (interfaces.scala:75-99,
DefaultFileBasedRelation.scala:73-86) and the hybrid-scan suites run over
partitioned datasets.  Spark materializes partition values from directory
names into columns; this module does the same for our reader.

Only segments BETWEEN a known root path and the file name are considered —
paths outside the roots (index ``v__=N`` version dirs, lake metadata) never
contribute columns.  Types are inferred per key over the whole file set:
int64 when every value parses as an integer, else string (Spark's inference
minus dates).  ``__HIVE_DEFAULT_PARTITION__`` decodes to null.
"""

from __future__ import annotations

import os
import urllib.parse
from typing import Dict, List, Optional, Sequence

HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


def _relative_segments(path: str, roots: Sequence[str]) -> List[str]:
    path = os.path.abspath(path)
    for root in roots:
        root = os.path.abspath(root).rstrip("/")
        if path.startswith(root + "/"):
            rel = path[len(root) + 1:]
            return rel.split("/")[:-1]  # directories only, not the file name
    return []


def partition_values(path: str, roots: Sequence[str]) -> Dict[str, Optional[str]]:
    """Raw (string-or-null) partition values parsed from ``path``."""
    out: Dict[str, Optional[str]] = {}
    for seg in _relative_segments(path, roots):
        if "=" not in seg:
            continue
        key, _, value = seg.partition("=")
        if not key:
            continue
        value = urllib.parse.unquote(value)
        out[key] = None if value == HIVE_NULL else value
    return out


def _infer_types(values_by_key: Dict[str, List[Optional[str]]]) -> Dict[str, str]:
    spec: Dict[str, str] = {}
    for k, vals in values_by_key.items():
        non_null = [v for v in vals if v is not None]

        def is_int(v: str) -> bool:
            try:
                int(v)
                return True
            except ValueError:
                return False

        spec[k] = "int64" if non_null and all(is_int(v) for v in non_null) \
            else "string"
    return spec


def partition_spec(paths: Sequence[str],
                   roots: Sequence[str]) -> Dict[str, str]:
    """Partition column -> arrow type string over the given file set.
    Empty when the layout is not partitioned."""
    values_by_key: Dict[str, List[Optional[str]]] = {}
    for p in paths:
        for k, v in partition_values(p, roots).items():
            values_by_key.setdefault(k, []).append(v)
    return _infer_types(values_by_key)


def partition_spec_for_roots(roots: Sequence[str]) -> Dict[str, str]:
    """Partition column -> arrow type inferred from the DIRECTORY tree under
    ``roots`` — independent of which file subset a caller happens to read,
    so every code path (full scans, hybrid-scan subsets, per-file build
    reads, sketches) resolves identical types.  A per-subset inference would
    let ``k=1`` read as int64 in one call and string (because ``k=x`` also
    exists) in another, and the concat of the two would fail or corrupt."""
    from hyperspace_tpu.io.files import expand_globs

    values_by_key: Dict[str, List[Optional[str]]] = {}

    def walk(d: str) -> None:
        try:
            entries = sorted(os.listdir(d))
        except OSError:
            return
        for name in entries:
            child = os.path.join(d, name)
            if not os.path.isdir(child) or os.path.islink(child):
                continue
            if "=" in name:
                key, _, value = name.partition("=")
                if key:
                    value = urllib.parse.unquote(value)
                    values_by_key.setdefault(key, []).append(
                        None if value == HIVE_NULL else value)
            walk(child)

    for root in expand_globs(roots):
        if os.path.isdir(root):
            walk(os.path.abspath(root))
    return _infer_types(values_by_key)


def typed_value(raw: Optional[str], arrow_type: str):
    if raw is None:
        return None
    return int(raw) if arrow_type == "int64" else raw


def attach_partition_columns(table, path: str, roots: Sequence[str],
                             spec: Dict[str, str],
                             columns: Optional[Sequence[str]] = None):
    """Append this file's partition values as constant columns (only those
    in ``columns`` when a projection was pushed down).  File columns win on
    a name clash — the data file is the source of truth."""
    import pyarrow as pa

    from hyperspace_tpu.io.parquet import _dtype_from_string

    raw = partition_values(path, roots)
    wanted = None if columns is None else {c for c in columns}
    for key, arrow_type in spec.items():
        if key in table.column_names:
            continue
        if wanted is not None and key not in wanted:
            continue
        value = typed_value(raw.get(key), arrow_type)
        table = table.append_column(
            key, pa.array([value] * table.num_rows,
                          type=_dtype_from_string(arrow_type)))
    return table
