"""Deterministic fault injection for the IO / op-log / action layers.

The operation log's crash-consistency story (numbered entries,
create-if-absent, atomic rename — IndexLogManager.scala:33-166) is easy
to assert by design and hard to trust without exercising it: partial
writes, interrupted renames, and transient IO errors are exactly the
failure envelope a lake indexing subsystem exists to survive (cf. Delta
Lake's optimistic log protocol and Spark's task-retry model).  This
module is the switchboard: IO primitives call :func:`check` /
:func:`write_payload` at named *sites*, and an installed
:class:`FaultPlan` decides whether the Nth call at that site fails — and
how.

Disabled is the default and costs one ``is None`` check per *file-level*
IO operation (never per row): the query hot path has zero sites, and the
op-log writes one small file per action.

Sites (grep for ``faults.check`` / ``faults.write_payload``):

========================  ====================================================
``log.write``             payload write of a numbered log entry
                          (IndexLogManager.write_log)
``log.rename``            the latestStable tmp → pointer atomic rename
                          (IndexLogManager.create_latest_stable_log)
``data.write``            an index data (parquet) file write
                          (io/parquet.write_bucket_run)
``action.commit``         between an action's op() and end() — work done,
                          final entry not yet committed (actions/base.run)
``io.list``               a directory/prefix listing (io/files.list_data_files,
                          list_dir — log discovery routes through the latter)
``io.delete``             a recursive index-data delete (io/files.remove_tree
                          — vacuumed versions, spill run directories)
``data.read``             a single source/index data-file read
                          (io/parquet.read_parquet_file and friends)
``store.put``             a LogStore conditional put (io/log_store.py;
                          ``torn`` COMMITS half the payload, then dies)
``store.read``            a LogStore point read / generation probe
``store.list``            a LogStore key listing
``store.delete``          a LogStore delete
``net.connect``           a client socket dial (interop/netfaults.connect)
``net.send``              a framed wire send — client request line or the
                          server's status+Arrow response
                          (interop/netfaults.send_all)
``net.recv``              a client read of the status line / Arrow stream
                          (interop/netfaults.before_recv)
``net.accept``            the server accept seam, BOTH io modes
                          (interop/netfaults.on_accept)
========================  ====================================================

Kinds:

========================  ====================================================
``enospc`` / ``eio``      raise ``OSError`` with that errno (transient from
                          the retry layer's point of view)
``torn``                  write only half the payload, then die
                          (:class:`InjectedCrash`) — models a power cut mid
                          write; the partial file STAYS on disk
``crash``                 die at the site before doing anything
``crash-before-rename``   die with the tmp file written, rename not done
``crash-after-rename``    perform the rename, then die
``bitrot``                flip bytes mid-file IN PLACE, keeping size AND
                          mtime — silent corruption only a content digest
                          can see (:func:`corrupt_file` sites:
                          ``data.write`` corrupts the file just written,
                          ``data.read`` corrupts it just before the read)
``truncate``              cut the file to half its size — a torn put the
                          store accepted; size changes, so even a quick
                          (stat-only) scrub catches it
``refused``               the peer answers RST to the dial
                          (``ConnectionRefusedError``) — server down or
                          port closed
``reset``                 the established connection dies mid-operation
                          (``ConnectionResetError``)
``black-hole``            the peer goes silent: the call hangs ``hang_s``
                          seconds, then times out — a partition or a
                          SIGSTOPped process, NOT a clean death
``slow``                  latency shaping: the call succeeds after an
                          injected ``latency_ms`` delay — a gray,
                          degraded-but-alive link
``torn-frame``            half the frame lands on the wire, then the
                          connection resets — the network edition of a
                          torn write; the reader sees a truncated Arrow
                          stream, never a parse success
========================  ====================================================

The network kinds fire only at ``net.*`` sites and only through
:func:`net` (the checkpoint :mod:`hyperspace_tpu.interop.netfaults`
calls); file/store kinds never fire at net sites and vice versa —
:class:`FaultPlan` rejects a mismatched pairing outright, because an
armed plan that can never fire is the silent-miss bug this module
exists to prevent.

The corruption kinds never raise: the write/read call itself SUCCEEDS
and the damage sits on disk for the integrity layer (io/integrity.py,
actions/verify.py) to detect — which is exactly the failure they model.
They fire only through :func:`corrupt_file`; :func:`check` and friends
skip them without consuming the plan's call counter, so ``at=N`` counts
only the calls that can actually fire the armed kind.

A crash is modeled as :class:`InjectedCrash`, a ``BaseException``:
``except Exception`` cleanup handlers — which a real ``kill -9`` would
never run — don't catch it, so the on-disk state the next process sees
is the honest post-crash state.  Cleanup code that would mask the
simulation (e.g. ``write_log``'s unlink-on-error) explicitly re-raises
it first.

Configured either directly (``faults.install(FaultPlan(...))``, what the
tests do) or via conf keys (``hyperspace.system.faultInjection.*``,
applied by ``HyperspaceSession``) so multi-process scenarios can arm the
injector through a child's session conf.
"""

from __future__ import annotations

import dataclasses
import errno
import threading
from typing import Optional

_KNOWN_KINDS = ("enospc", "eio", "torn", "crash", "crash-before-rename",
                "crash-after-rename", "bitrot", "truncate",
                "refused", "reset", "black-hole", "slow", "torn-frame")
# Kinds that damage file CONTENT instead of failing the call; they fire
# only through corrupt_file().
_CORRUPT_KINDS = ("bitrot", "truncate")
# Wire kinds: they fire only through net(), at net.* sites, and are
# INTERPRETED by interop/netfaults.py (this module just arbitrates
# whether the Nth call fires).
_NET_KINDS = ("refused", "reset", "black-hole", "slow", "torn-frame")

# The machine-readable site registry (the docstring table above is the
# prose version).  Every ``check``/``fire``/``write_payload``/
# ``corrupt_file``/``atomic_replace`` call site and every test's
# ``FaultPlan(site=...)`` must name one of these — enforced statically by
# ``hyperspace_tpu.lint`` (rule ``fault-site-registry``) and at runtime
# by :class:`FaultPlan`, because a typo'd site silently never fires.
SITES = (
    "log.write",
    "log.rename",
    "data.write",
    "data.read",
    "action.commit",
    "io.list",
    "io.delete",
    "store.put",
    "store.read",
    "store.list",
    "store.delete",
    "net.connect",
    "net.send",
    "net.recv",
    "net.accept",
)


class InjectedCrash(BaseException):
    """Simulated process death at a fault site.

    Deliberately NOT an ``Exception``: a crashed process runs no cleanup
    handlers, so ``except Exception`` blocks must not swallow this (the
    few ``except BaseException`` cleanup paths on the instrumented
    routes re-raise it explicitly before cleaning up).
    """


@dataclasses.dataclass
class FaultPlan:
    """One armed fault: fire ``count`` times starting at the ``at``-th
    call of ``site`` (1-based), with the given ``kind``."""

    site: str
    kind: str
    at: int = 1
    count: int = 1  # -1 = every matching call from ``at`` on
    # Wire-shaping knobs, read by interop/netfaults.py when the armed
    # kind is ``slow`` (added delay) / ``black-hole`` (hang duration
    # before the injected timeout).  Ignored by every other kind.
    latency_ms: float = 25.0
    hang_s: float = 0.25

    def __post_init__(self) -> None:
        if self.kind not in _KNOWN_KINDS:
            raise ValueError(
                f"Unknown fault kind {self.kind!r}; expected one of "
                f"{_KNOWN_KINDS}")
        if self.site not in SITES:
            raise ValueError(
                f"Unknown fault site {self.site!r}; expected one of "
                f"{SITES} (a typo'd site would silently never fire)")
        if (self.kind in _NET_KINDS) != self.site.startswith("net."):
            raise ValueError(
                f"Fault kind {self.kind!r} cannot fire at site "
                f"{self.site!r}: wire kinds {_NET_KINDS} pair only with "
                f"net.* sites (a mismatched plan would silently never "
                f"fire)")
        self._calls = 0
        self._fired = 0
        self._lock = threading.Lock()

    def _should_fire(self, site: str, corrupting: bool = False,
                     net: bool = False) -> bool:
        if site != self.site:
            return False
        if (self.kind in _CORRUPT_KINDS) != corrupting:
            # Mismatched call type (a corruption kind at a check() site or
            # vice versa): not merely "don't fire" — don't COUNT, so at=N
            # indexes only calls that could fire this kind.
            return False
        if (self.kind in _NET_KINDS) != net:
            # Same contract for the wire channel: net kinds fire only
            # through net(), and net() fires only net kinds.
            return False
        with self._lock:
            self._calls += 1
            if self._calls < self.at:
                return False
            if self.count >= 0 and self._fired >= self.count:
                return False
            self._fired += 1
            return True

    def _raise(self) -> None:
        if self.kind == "enospc":
            raise OSError(errno.ENOSPC, "injected: no space left on device")
        if self.kind == "eio":
            raise OSError(errno.EIO, "injected: input/output error")
        raise InjectedCrash(f"injected crash at {self.site}")


_PLAN: Optional[FaultPlan] = None

# Thread-local quiet flag: diagnostic IO (the perf ledger's appends,
# telemetry side-writes) must neither FIRE an armed fault nor CONSUME its
# call counter — a plan armed "eio at the 3rd store.put" targets the
# system under test, and an interleaved bookkeeping write shifting the
# count would silently retarget it.
_quiet_tls = threading.local()


class _QuietSection:
    def __enter__(self) -> "_QuietSection":
        self._prev = getattr(_quiet_tls, "depth", 0)
        _quiet_tls.depth = self._prev + 1
        return self

    def __exit__(self, *exc: object) -> bool:
        _quiet_tls.depth = self._prev
        return False


def quiet() -> _QuietSection:
    """Context manager: fault sites on this thread become free
    pass-throughs (no fire, no counting) for the duration."""
    return _QuietSection()


def _is_quiet() -> bool:
    return getattr(_quiet_tls, "depth", 0) > 0


def install(plan: Optional[FaultPlan]) -> None:
    """Arm ``plan`` process-globally (None disarms)."""
    global _PLAN
    _PLAN = plan


def clear() -> None:
    install(None)


def active() -> Optional[FaultPlan]:
    return _PLAN


def install_from_conf(conf) -> None:
    """Arm the injector from ``hyperspace.system.faultInjection.*`` conf
    keys (no-op unless enabled; called at session construction)."""
    if not getattr(conf, "fault_injection_enabled", False):
        return
    install(FaultPlan(site=conf.fault_injection_site,
                      kind=conf.fault_injection_kind,
                      at=int(conf.fault_injection_at),
                      count=int(conf.fault_injection_count),
                      latency_ms=float(getattr(
                          conf, "fault_injection_latency_ms", 25.0)),
                      hang_s=float(getattr(
                          conf, "fault_injection_hang_s", 0.25))))


def check(site: str) -> None:
    """Fault checkpoint: raises the armed fault when ``site`` matches and
    the call counter lines up; free (one None check) otherwise."""
    plan = _PLAN
    if plan is None or _is_quiet() or not plan._should_fire(site):
        return
    plan._raise()


def net(site: str) -> Optional[FaultPlan]:
    """Wire-fault checkpoint: returns the armed plan when a net kind
    fires at ``site`` (the caller — interop/netfaults.py — interprets
    the kind and its shaping knobs), None otherwise.  Never raises:
    socket seams decide HOW a wire fault manifests (which exception,
    which half of the frame lands) and this module only arbitrates
    WHETHER the Nth call fires."""
    plan = _PLAN
    if plan is None or _is_quiet() or not plan._should_fire(site, net=True):
        return None
    return plan


def fire(site: str) -> Optional[str]:
    """Like :func:`check`, but a ``torn`` fault RETURNS ``"torn"`` instead
    of raising, so backends whose commit is atomic (conditional-put
    stores) can decide for themselves what a torn upload leaves behind;
    every other kind raises here."""
    plan = _PLAN
    if plan is None or _is_quiet() or not plan._should_fire(site):
        return None
    if plan.kind == "torn":
        return "torn"
    plan._raise()
    return None  # unreachable; keeps the signature honest


def write_payload(f, data: bytes, site: str) -> None:
    """Write ``data`` to the open binary file ``f``, honoring faults at
    ``site``: ``enospc``/``eio`` fail before any byte lands (the OS
    rejected the write), ``torn`` persists exactly half the payload and
    then dies, ``crash`` dies before writing."""
    plan = _PLAN
    if plan is None or _is_quiet() or not plan._should_fire(site):
        f.write(data)
        return
    if plan.kind == "torn":
        f.write(data[:max(1, len(data) // 2)])
        f.flush()
        raise InjectedCrash(f"injected torn write at {site}")
    plan._raise()


def corrupt_file(site: str, path: str) -> None:
    """Corruption checkpoint for file-content fault kinds: ``bitrot``
    flips 8 bytes in the middle of ``path`` in place, restoring mtime so
    the damage is invisible to a stat (only a content digest or an actual
    decode sees it); ``truncate`` cuts the file to half its size (size
    changes — a stat-level scrub catches it).  The call at the SITE
    itself still succeeds: these model damage around an IO that worked."""
    import os

    plan = _PLAN
    if plan is None or _is_quiet() \
            or not plan._should_fire(site, corrupting=True):
        return
    st = os.stat(path)
    if plan.kind == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(1, st.st_size // 2))
        return
    with open(path, "r+b") as f:
        off = max(0, st.st_size // 2 - 4)
        f.seek(off)
        chunk = f.read(8)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))
        f.flush()
        os.fsync(f.fileno())
    # Bit-rot does not touch metadata: size is unchanged by the in-place
    # flip, and the pre-damage timestamps are restored.
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns))


def atomic_replace(tmp: str, dst: str, site: str) -> None:
    """``os.replace`` with faults at ``site``: ``crash-before-rename``
    dies leaving the tmp file behind and ``dst`` untouched;
    ``crash-after-rename`` dies with the rename durably done;
    ``enospc``/``eio`` fail the rename itself."""
    import os

    plan = _PLAN
    if plan is None or _is_quiet() or not plan._should_fire(site):
        os.replace(tmp, dst)
        return
    if plan.kind == "crash-after-rename":
        os.replace(tmp, dst)
        raise InjectedCrash(f"injected crash after rename at {site}")
    if plan.kind in ("crash", "crash-before-rename", "torn"):
        raise InjectedCrash(f"injected crash before rename at {site}")
    plan._raise()
