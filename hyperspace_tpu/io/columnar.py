"""Host↔device columnar bridge.

Converts arrow columns into the dtype-monomorphic device representations the
kernels consume:

  - ``to_hash_words``: any column → (n, 2) uint32 words for the bucket-hash
    kernel.  Numerics bitcast on the host (cheap views); strings/binary are
    hashed host-side with pandas' vectorized C hasher (stable across calls)
    because variable-length data can't live in XLA's static-shape world
    (SURVEY.md §7 hard parts: dictionary-encode strings host-side).
  - ``to_order_key``: any column → (n,) numeric order key for the sort
    kernel.  Strings become order-preserving dense ranks via np.unique.
  - ``to_device_numeric``: numeric column → host array for predicate/join
    kernels; None for non-numeric or nullable (those evaluate host-side).

Temporal columns are normalized through ONE helper (``_temporal_to_int64``)
everywhere — build, query, and literal paths must agree on the integer
domain (the column's own storage unit) or identical values would hash to
different buckets.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

# Sentinel hash words for NULL: all nulls land in one deterministic bucket.
_NULL_WORDS = (np.uint32(0x9E3779B9), np.uint32(0x7F4A7C15))


def _combine(column: "pa.ChunkedArray | pa.Array") -> pa.Array:
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    if pa.types.is_dictionary(column.type):
        column = column.cast(column.type.value_type)
    return column


def _null_mask(column: pa.Array) -> Optional[np.ndarray]:
    """Boolean mask of null positions, or None when the column has no nulls."""
    if column.null_count == 0:
        return None
    return np.asarray(pc.is_null(column).to_numpy(zero_copy_only=False), dtype=bool)


def _temporal_to_int64(column: pa.Array) -> pa.Array:
    """Temporal → int64 in the column's OWN storage unit (date32 stays days,
    timestamp[us] stays micros): unit-consistent for any one column type,
    which is all bucketing/ordering/compare need."""
    t = column.type
    if pa.types.is_date32(t) or pa.types.is_time32(t):
        return column.cast(pa.int32()).cast(pa.int64())
    return column.cast(pa.int64())


def _numeric_int64(column: pa.Array, fill_null_zero: bool) -> np.ndarray:
    """int/bool/temporal column → int64 numpy array in the native domain."""
    t = column.type
    if pa.types.is_temporal(t):
        column = _temporal_to_int64(column)
    elif pa.types.is_boolean(t) or not pa.types.is_int64(t):
        column = column.cast(pa.int64())
    if fill_null_zero and column.null_count > 0:
        column = pc.fill_null(column, 0)
    return column.to_numpy(zero_copy_only=False).astype(np.int64, copy=False)


def is_numeric_type(t: pa.DataType) -> bool:
    return (pa.types.is_integer(t) or pa.types.is_floating(t)
            or pa.types.is_boolean(t) or pa.types.is_temporal(t))


def to_hash_words(column: "pa.ChunkedArray | pa.Array") -> np.ndarray:
    """(n, 2) uint32 hash words; equal values always map to equal words;
    nulls all map to one sentinel word pair (one deterministic bucket)."""
    column = _combine(column)
    t = column.type
    nulls = _null_mask(column)
    if pa.types.is_floating(t):
        if nulls is not None:
            column = pc.fill_null(column, 0.0)
        arr = column.to_numpy(zero_copy_only=False).astype(np.float64)
        arr = np.where(arr == 0.0, 0.0, arr)  # -0.0 == 0.0 must hash equal
        # All NaN bit patterns hash alike (Spark normalizes NaN for
        # joins/grouping; a negative NaN written by another engine must
        # land with the canonical one).
        arr = np.where(np.isnan(arr), np.float64("nan"), arr)
        bits = arr.view(np.uint64)
    elif is_numeric_type(t):
        bits = _numeric_int64(column, fill_null_zero=True).view(np.uint64)
    else:
        # Variable-length (string/binary/decimal): vectorized stable hash.
        import pandas.util

        arr = column.to_numpy(zero_copy_only=False)
        bits = pandas.util.hash_array(np.asarray(arr, dtype=object))
    out = split_words64(bits.view(np.uint64) if bits.dtype != np.uint64 else bits)
    if nulls is not None:
        out[nulls, 0] = _NULL_WORDS[0]
        out[nulls, 1] = _NULL_WORDS[1]
    return out


def to_order_key(column: "pa.ChunkedArray | pa.Array") -> np.ndarray:
    """(n,) numeric key whose ordering equals the column's value ordering.
    Nulls sort with the placeholder value (ordering among them is not
    semantically observable — within-bucket sort is a layout property)."""
    column = _combine(column)
    t = column.type
    if pa.types.is_floating(t):
        if column.null_count > 0:
            column = pc.fill_null(column, 0.0)
        return column.to_numpy(zero_copy_only=False).astype(np.float64)
    if is_numeric_type(t):
        return _numeric_int64(column, fill_null_zero=True)
    # Strings: dense rank (np.unique inverse is rank-ordered).
    arr = column.to_numpy(zero_copy_only=False)
    _, inverse = np.unique(np.asarray(arr, dtype=object), return_inverse=True)
    return inverse.astype(np.int64)


def _monotone_uint64(keys: np.ndarray) -> np.ndarray:
    """Order-preserving map of an int64/float64 key array into uint64.

    int64: flip the sign bit.  float64: IEEE total-order trick (non-negative
    floats get the sign bit set; negative floats are bit-inverted), which
    ranks -0.0 immediately below +0.0 — an unobservable layout property
    (within-bucket sort order, see ``to_order_key``).
    """
    if keys.dtype == np.float64:
        bits = keys.view(np.int64)
        return np.where(bits >= 0,
                        bits.view(np.uint64) + np.uint64(1 << 63),
                        ~bits.view(np.uint64))
    assert keys.dtype == np.int64, keys.dtype
    return keys.view(np.uint64) ^ np.uint64(1 << 63)


def split_words64(values: np.ndarray) -> np.ndarray:
    """(n,) uint64 → (n, 2) uint32 (hi, lo) — the ONE word layout shared by
    hash words, order words, and the shuffle's row-id words."""
    out = np.empty((len(values), 2), dtype=np.uint32)
    out[:, 0] = (values >> np.uint64(32)).astype(np.uint32)
    out[:, 1] = (values & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return out


def join_words64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Inverse of ``split_words64``."""
    return (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)


def to_order_words(column: "pa.ChunkedArray | pa.Array") -> np.ndarray:
    """(n, 2) uint32 monotone words: lexicographic (hi, lo) order equals the
    column's value order.  This keeps the sort kernel pure 32-bit — TPU's
    native lane width — instead of relying on x64 int64 emulation."""
    return split_words64(_monotone_uint64(to_order_key(column)))


def to_order_codes64(column: "pa.ChunkedArray | pa.Array") -> np.ndarray:
    """(n,) uint64 monotone codes — ``to_order_words`` without the
    split into 32-bit words.  The HOST-side sort-key form (numpy is
    64-bit native; the word split serves the TPU lanes): the external
    build's route pass sorts on these and rides them along the spill
    runs as the writer's sort codes."""
    return _monotone_uint64(to_order_key(column))


def to_device_numeric(column: "pa.ChunkedArray | pa.Array") -> Optional[np.ndarray]:
    """Numeric host array suitable for jnp.asarray, or None if non-numeric
    OR nullable — SQL null semantics (null != null, three-valued predicates)
    are handled by the arrow host path, not the device kernels."""
    column = _combine(column)
    t = column.type
    if not is_numeric_type(t) or column.null_count > 0:
        return None
    if pa.types.is_floating(t):
        return column.to_numpy(zero_copy_only=False).astype(np.float64)
    return _numeric_int64(column, fill_null_zero=False)


def literal_to_numeric(value, t: pa.DataType) -> Optional[float]:
    """Normalize a literal to ``to_device_numeric``'s domain for a column of
    type ``t``; None if the literal doesn't fit that domain."""
    if pa.types.is_temporal(t):
        try:
            arr = pa.array([value], type=t)
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            return None
        return int(_temporal_to_int64(arr)[0].as_py())
    if isinstance(value, bool):
        return int(value)
    if isinstance(value, (int, float)):
        return value
    return None
