"""File listing over source root paths.

Reference contract: the relation ``allFiles`` listing
(sources/default/DefaultFileBasedRelation.scala:57-71) plus PathUtils'
data-file filter.  Listing is recursive; results are sorted for
deterministic signatures.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import List, Optional, Sequence

from hyperspace_tpu.index.log_entry import FileIdTracker, FileInfo
from hyperspace_tpu.utils.paths import is_data_file, normalize_path

_GLOB_CHARS = ("*", "?", "[")


def list_dir(path: str, retry=None) -> List[str]:
    """``os.listdir`` behind the ``io.list`` fault site with bounded
    transient-IO retry — the single listing primitive for METADATA
    discovery (operation-log ids, system-path index names), so
    ``hyperspace.system.io.retry.*`` and injected listing faults cover
    log discovery exactly like data listing.  Missing directories read
    as empty (every caller treated ENOENT that way already)."""
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.utils.retry import RetryPolicy

    policy = retry if retry is not None else RetryPolicy()

    def attempt() -> List[str]:
        faults.check("io.list")
        try:
            return os.listdir(path)
        except (FileNotFoundError, NotADirectoryError):
            return []

    return policy.call(attempt)


def remove_tree(path: str, ignore_errors: bool = False) -> None:
    """``shutil.rmtree`` behind the ``io.delete`` fault site — the single
    recursive-delete primitive for index data (vacuumed versions, spill
    run directories).  Routing deletes through here keeps the IO seam
    airtight: the fault matrix can model a delete that dies half way,
    and the static io-seam lint rule can prove no action deletes index
    state behind the injector's back."""
    import shutil

    from hyperspace_tpu.io import faults

    faults.check("io.delete")
    shutil.rmtree(path, ignore_errors=ignore_errors)


def remove_file(path: str, missing_ok: bool = False) -> None:
    """``os.unlink`` behind the ``io.delete`` fault site (see
    :func:`remove_tree`)."""
    from hyperspace_tpu.io import faults

    faults.check("io.delete")
    try:
        os.unlink(path)
    except FileNotFoundError:
        if not missing_ok:
            raise


def expand_globs(root_paths: Sequence[str]) -> List[str]:
    """Expand glob patterns among ``root_paths`` (sorted matches); plain
    paths pass through.  Globbing patterns let an index cover directories
    that appear later (GLOBBING_PATTERN_KEY, IndexConstants.scala:108-114).

    A path that EXISTS literally is never treated as a pattern, so a
    directory whose name happens to contain ``*``/``?``/``[`` still reads
    as itself."""
    out: List[str] = []
    for root in root_paths:
        if any(c in root for c in _GLOB_CHARS) and not os.path.exists(root):
            out.extend(sorted(_glob.glob(root)))
        else:
            out.append(root)
    return out


def list_data_files(root_paths: Sequence[str],
                    tracker: Optional[FileIdTracker] = None,
                    extension: Optional[str] = None) -> List[FileInfo]:
    """All data files under ``root_paths`` (each a file or directory),
    registered with ``tracker`` when given.

    Walk + stat go through the native runtime when available
    (native/hs_native.cc — the per-query signature check makes this the
    metadata hot loop); the Python fallback below is byte-identical.
    Transient IO errors (a flaky mount mid-walk) retry with the default
    bounded backoff, and the ``io.list`` fault site lets tests inject
    them (io/faults.py); with injection disarmed the overhead is one
    None check per LISTING, not per file.
    """
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.utils.retry import RetryPolicy

    def attempt() -> List[FileInfo]:
        faults.check("io.list")
        return _list_data_files(root_paths, tracker, extension)

    return RetryPolicy().call(attempt)


def _list_data_files(root_paths: Sequence[str],
                     tracker: Optional[FileIdTracker],
                     extension: Optional[str]) -> List[FileInfo]:
    from hyperspace_tpu import native

    normalized = [normalize_path(r) for r in expand_globs(root_paths)]
    scanned = native.scan_files(normalized)
    if scanned is not None:
        out = []
        for path, size, mtime in scanned:
            if extension and not path.endswith(extension):
                continue
            fid = tracker.add_file(path, size, mtime) \
                if tracker is not None else -1
            out.append(FileInfo(path, size, mtime, fid))
        out.sort(key=lambda f: f.name)
        return out

    out: List[FileInfo] = []
    for root in normalized:
        if os.path.isfile(root):
            out.append(_file_info(root, tracker))
        elif os.path.isdir(root):
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames.sort()
                for name in sorted(filenames):
                    if not is_data_file(name):
                        continue
                    if extension and not name.endswith(extension):
                        continue
                    out.append(_file_info(os.path.join(dirpath, name), tracker))
    out.sort(key=lambda f: f.name)
    return out


def _file_info(path: str, tracker: Optional[FileIdTracker]) -> FileInfo:
    st = os.stat(path)
    mtime = int(st.st_mtime_ns)
    fid = tracker.add_file(path, st.st_size, mtime) if tracker is not None else -1
    return FileInfo(path, st.st_size, mtime, fid)
