"""Pluggable operation-log storage backends with conditional-put semantics.

The reference rides HDFS ``create-if-absent`` + atomic rename for its log
protocol (IndexLogManager.scala:149-165).  A production lake lives on
GCS/S3, where **rename does not exist** and the primitives are different:

  - a flat key namespace (no directories; "listing" is a prefix scan)
  - per-key **generation numbers** that bump on every successful put
  - conditional puts: ``put_if_absent`` (generation 0) and
    ``put_if_generation_match`` (the GCS ``ifGenerationMatch`` / S3
    conditional-write model)
  - **listing may lag writes** (eventual visibility), while point reads
    (GET by key) are strongly consistent

This module is the seam: :class:`LogStore` defines exactly those
primitives, and ``index/object_log_manager.py`` builds the Delta-style
numbered-commit + CAS-pointer protocol on top of them.  Two real
implementations ship:

  - :class:`PosixLogStore` — the current POSIX semantics extracted behind
    the interface (``O_EXCL`` create-if-absent; generations via a sidecar
    file under an ``flock``-serialized critical section, so conditional
    puts are atomic across real OS processes).
  - :class:`EmulatedObjectStore` — honest object-store semantics over a
    local directory: flat percent-encoded keys, per-key generations, a
    configurable **stale-list visibility window** (keys committed within
    the window are hidden from ``list_keys`` but visible to point reads),
    and no rename anywhere in its API.  ``os.replace`` appears only
    *inside* the emulation, playing the role of the store server's
    internal atomic commit.

Both stores are fault-injectable (io/faults.py) at the ``store.put`` /
``store.read`` / ``store.list`` / ``store.delete`` sites.  A ``torn`` put
COMMITS half the payload with a real generation before dying — modeling
an upload the store accepted but the writer never finished — so readers
must treat the key as burned-but-unparseable, the same envelope the POSIX
log already survives.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from typing import List, Optional, Tuple

from hyperspace_tpu.io import faults

try:  # flock is the cross-process arbiter; absent (non-POSIX) we degrade
    import fcntl as _fcntl  # to in-process locking only.
except ImportError:  # pragma: no cover - linux container always has it
    _fcntl = None

_LOCK_NAME = ".lock"
_GEN_SUFFIX = ".g"


class LogStore:
    """Flat key→bytes store with per-key generations and conditional puts.

    Contract (mirrors GCS object semantics):
      - ``generation(key)`` is 0 for an absent key and strictly increases
        with every successful put to that key;
      - ``put_if_absent`` / ``put_if_generation_match`` are ATOMIC with
        respect to every other mutation of the same key, across processes;
      - point reads (``read`` / ``read_with_generation`` / ``exists``)
        are strongly consistent;
      - ``list_keys`` MAY lag recent writes (stale-visibility window).
    """

    def list_keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def read(self, key: str) -> bytes:
        """Bytes at ``key``; FileNotFoundError when absent."""
        raise NotImplementedError

    def read_with_generation(self, key: str) -> Tuple[Optional[bytes], int]:
        """(bytes or None, generation) — generation 0 means absent."""
        raise NotImplementedError

    def generation(self, key: str) -> int:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        return self.generation(key) > 0

    def put_if_absent(self, key: str, data: bytes) -> bool:
        """Commit ``data`` iff ``key`` does not exist.  False on conflict."""
        return self.put_if_generation_match(key, data, 0)

    def put_if_generation_match(self, key: str, data: bytes,
                                expected_generation: int) -> bool:
        """Commit ``data`` iff the key's current generation equals
        ``expected_generation`` (0 = must be absent).  False on mismatch —
        the compare-and-swap every pointer update rides."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        """Remove ``key``; absent keys are a no-op."""
        raise NotImplementedError


class PosixLogStore(LogStore):
    """The POSIX backend: keys are files in ``root``; conditional puts are
    serialized by ``flock`` on a root-level lock file (plus an in-process
    mutex), generations live in a ``<key>.g`` sidecar."""

    def __init__(self, root: str, stale_list_s: float = 0.0) -> None:
        self.root = root
        # POSIX directory listings are strongly consistent; the parameter
        # exists so either store class satisfies the same constructor.
        self.stale_list_s = 0.0
        self._mutex = threading.Lock()

    # -- key <-> filename ---------------------------------------------------
    def _encode(self, key: str) -> str:
        return key

    def _decode(self, name: str) -> str:
        return name

    def _data_path(self, key: str) -> str:
        return os.path.join(self.root, self._encode(key))

    def _gen_path(self, key: str) -> str:
        return self._data_path(key) + _GEN_SUFFIX

    # -- locking ------------------------------------------------------------
    def _locked(self):
        """Cross-process critical section: flock on ``root/.lock`` (the
        emulated store server's single-threaded commit point)."""
        store = self

        class _Section:
            def __enter__(self):
                store._mutex.acquire()
                os.makedirs(store.root, exist_ok=True)
                self._fd = os.open(os.path.join(store.root, _LOCK_NAME),
                                   os.O_CREAT | os.O_RDWR)
                if _fcntl is not None:
                    _fcntl.flock(self._fd, _fcntl.LOCK_EX)
                return self

            def __exit__(self, *exc):
                try:
                    if _fcntl is not None:
                        _fcntl.flock(self._fd, _fcntl.LOCK_UN)
                    os.close(self._fd)
                finally:
                    store._mutex.release()
                return False

        return _Section()

    # -- reads (strongly consistent) ----------------------------------------
    def _meta(self, key: str) -> Tuple[int, float]:
        """(generation, commit wall-time) from the sidecar; (0, 0) absent."""
        try:
            with open(self._gen_path(key), "r", encoding="utf-8") as f:
                meta = json.load(f)
            return int(meta["g"]), float(meta.get("t", 0.0))
        except (FileNotFoundError, ValueError, KeyError):
            # No sidecar but a data file = a pre-LogStore layout (or a
            # crash inside the emulation): report generation 1 so the data
            # stays visible and CAS still has something to compare.
            return (1, 0.0) if os.path.isfile(self._data_path(key)) else (0, 0.0)

    def generation(self, key: str) -> int:
        faults.check("store.read")
        return self._meta(key)[0]

    def read(self, key: str) -> bytes:
        faults.check("store.read")
        with open(self._data_path(key), "rb") as f:
            return f.read()

    def read_with_generation(self, key: str) -> Tuple[Optional[bytes], int]:
        faults.check("store.read")
        gen = self._meta(key)[0]
        if gen == 0:
            return None, 0
        try:
            with open(self._data_path(key), "rb") as f:
                return f.read(), gen
        except FileNotFoundError:
            return None, gen

    def list_keys(self, prefix: str = "") -> List[str]:
        faults.check("store.list")
        if not os.path.isdir(self.root):
            return []
        now = time.time()
        out: List[str] = []
        for name in os.listdir(self.root):
            if name == _LOCK_NAME or name.endswith(_GEN_SUFFIX) \
                    or ".tmp-" in name:
                continue
            key = self._decode(name)
            if prefix and not key.startswith(prefix):
                continue
            if self.stale_list_s > 0.0:
                # The visibility window: recently committed keys are
                # hidden from LISTING (point reads still see them) —
                # the eventual-consistency shape the CAS protocol must
                # survive.
                _g, t = self._meta(key)
                if t and now - t < self.stale_list_s:
                    continue
            out.append(key)
        return sorted(out)

    # -- mutations (atomic under the lock) ----------------------------------
    def _commit(self, key: str, data: bytes, gen: int) -> None:
        """Install data+generation.  The replace pair is the emulated
        server's internal atomic commit — nothing above this layer ever
        sees or needs a rename."""
        data_path = self._data_path(key)
        tmp = f"{data_path}.tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, data_path)
        gen_tmp = f"{self._gen_path(key)}.tmp-{os.getpid()}"
        with open(gen_tmp, "w", encoding="utf-8") as f:
            json.dump({"g": gen, "t": time.time()}, f)
        os.replace(gen_tmp, self._gen_path(key))

    def put_if_generation_match(self, key: str, data: bytes,
                                expected_generation: int) -> bool:
        from hyperspace_tpu.telemetry import metrics
        from hyperspace_tpu.telemetry.trace import span

        kind = faults.fire("store.put")  # enospc/eio/crash raise here
        with span("store.put", key=key) as sp, self._locked():
            metrics.inc("log.store.puts")
            cur = self._meta(key)[0]
            if cur != int(expected_generation):
                # The optimistic-concurrency signal: some other writer
                # moved the key's generation between read and CAS.
                metrics.inc("log.cas.conflicts")
                sp.set(outcome="conflict")
                return False
            sp.set(outcome="committed", bytes=len(data))
            if kind == "torn":
                # The store ACCEPTED a partial upload: commit half the
                # payload with a real generation, then the writer dies.
                # The key is burned; readers must skip the garbage.
                self._commit(key, data[:max(1, len(data) // 2)], cur + 1)
                raise faults.InjectedCrash(
                    f"injected torn put of {key!r}")
            self._commit(key, data, cur + 1)
            return True

    def delete(self, key: str) -> None:
        faults.check("store.delete")
        with self._locked():
            for path in (self._data_path(key), self._gen_path(key)):
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass


class EmulatedObjectStore(PosixLogStore):
    """Object-store semantics over a local directory: flat percent-encoded
    keys (``/`` is data, not structure), per-key generations, conditional
    puts, and a configurable stale-list visibility window.

    The window defaults to 0 (strong listing); tests and the conf key
    ``hyperspace.system.objectStore.staleListMs`` widen it to prove the
    log protocol never *depends* on listing freshness: conditional puts
    arbitrate id claims, and readers probe forward with point reads
    (``ObjectStoreLogManager.get_latest_id``)."""

    def __init__(self, root: str, stale_list_s: float = 0.0) -> None:
        super().__init__(root)
        self.stale_list_s = float(stale_list_s)

    def _encode(self, key: str) -> str:
        return urllib.parse.quote(key, safe="")

    def _decode(self, name: str) -> str:
        return urllib.parse.unquote(name)
