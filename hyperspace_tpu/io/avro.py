"""Minimal Avro object-container-file codec (read + write, null codec).

Iceberg stores its manifest lists and manifests as Avro object container
files; the image ships no avro library, so the engine carries its own codec.
Supports the schema subset those files use: null, boolean, int, long, float,
double, bytes, string, fixed, enum, record, array, map, and unions.

Reference parity note: the reference reads manifests through the
``iceberg-spark-runtime`` jar (``table.newScan().planFiles()``,
sources/iceberg/IcebergRelation.scala:60-63); this module is the native
substrate that lets our Iceberg source do the same without a JVM.

Format (Avro 1.11 spec, "Object Container Files"):
  magic "Obj\\x01" | file-metadata map (avro.schema, avro.codec) |
  16-byte sync marker | blocks of (record count, byte size, records, sync).
Binary encoding: zigzag-varint ints/longs, length-prefixed bytes/strings,
IEEE little-endian floats, block-encoded arrays/maps, index-prefixed unions.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterable, List, Optional, Union

Schema = Union[str, Dict[str, Any], List[Any]]

MAGIC = b"Obj\x01"

_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double", "bytes",
               "string"}


# ---------------------------------------------------------------------------
# Binary encoding
# ---------------------------------------------------------------------------
def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: io.BytesIO, n: int) -> None:
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def read_long(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("Truncated Avro varint")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _zigzag_decode(acc)
        shift += 7


class _Resolver:
    """Named-type registry so records/fixeds can be referenced by name."""

    def __init__(self) -> None:
        self.named: Dict[str, Schema] = {}

    def register(self, schema: Dict[str, Any]) -> None:
        name = schema.get("name")
        if name:
            ns = schema.get("namespace")
            self.named[name] = schema
            if ns:
                self.named[f"{ns}.{name}"] = schema

    def resolve(self, schema: Schema) -> Schema:
        if isinstance(schema, str) and schema not in _PRIMITIVES:
            if schema not in self.named:
                raise ValueError(f"Unknown Avro type name: {schema}")
            return self.named[schema]
        return schema


def _walk_register(schema: Schema, resolver: _Resolver) -> None:
    if isinstance(schema, dict):
        if schema.get("type") in ("record", "fixed", "enum"):
            resolver.register(schema)
        if schema.get("type") == "record":
            for f in schema.get("fields", []):
                _walk_register(f["type"], resolver)
        elif schema.get("type") == "array":
            _walk_register(schema["items"], resolver)
        elif schema.get("type") == "map":
            _walk_register(schema["values"], resolver)
    elif isinstance(schema, list):
        for s in schema:
            _walk_register(s, resolver)


def _encode(buf: io.BytesIO, schema: Schema, value: Any,
            resolver: _Resolver) -> None:
    schema = resolver.resolve(schema)
    if isinstance(schema, list):  # union: pick the first matching branch
        idx = _union_index(schema, value, resolver)
        write_long(buf, idx)
        _encode(buf, schema[idx], value, resolver)
        return
    t = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(t, (dict, list)):  # {"type": {...nested...}}
        _encode(buf, t, value, resolver)
        return
    if t == "null":
        return
    if t == "boolean":
        buf.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        write_long(buf, int(value))
    elif t == "float":
        buf.write(struct.pack("<f", float(value)))
    elif t == "double":
        buf.write(struct.pack("<d", float(value)))
    elif t == "bytes":
        data = bytes(value)
        write_long(buf, len(data))
        buf.write(data)
    elif t == "string":
        data = str(value).encode("utf-8")
        write_long(buf, len(data))
        buf.write(data)
    elif t == "fixed":
        data = bytes(value)
        if len(data) != schema["size"]:
            raise ValueError(f"fixed size mismatch: {len(data)} != {schema['size']}")
        buf.write(data)
    elif t == "enum":
        write_long(buf, schema["symbols"].index(value))
    elif t == "record":
        for f in schema["fields"]:
            if f["name"] in value:
                field_value = value[f["name"]]
            elif "default" in f:
                field_value = f["default"]
            else:
                raise ValueError(f"Missing field {f['name']} for record "
                                 f"{schema.get('name')}")
            _encode(buf, f["type"], field_value, resolver)
    elif t == "array":
        items = list(value)
        if items:
            write_long(buf, len(items))
            for item in items:
                _encode(buf, schema["items"], item, resolver)
        write_long(buf, 0)
    elif t == "map":
        entries = dict(value)
        if entries:
            write_long(buf, len(entries))
            for k, v in entries.items():
                _encode(buf, "string", k, resolver)
                _encode(buf, schema["values"], v, resolver)
        write_long(buf, 0)
    else:
        raise ValueError(f"Unsupported Avro type: {t}")


def _union_index(union: List[Any], value: Any, resolver: _Resolver) -> int:
    def kind(s: Schema) -> str:
        s = resolver.resolve(s)
        return s["type"] if isinstance(s, dict) else s

    for i, branch in enumerate(union):
        k = kind(branch)
        if value is None and k == "null":
            return i
        if value is None:
            continue
        if k == "null":
            continue
        if k == "boolean" and isinstance(value, bool):
            return i
        if k in ("int", "long") and isinstance(value, int) and not isinstance(value, bool):
            return i
        if k in ("float", "double") and isinstance(value, float):
            return i
        if k == "string" and isinstance(value, str):
            return i
        if k in ("bytes", "fixed") and isinstance(value, (bytes, bytearray)):
            return i
        if k == "record" and isinstance(value, dict):
            return i
        if k == "array" and isinstance(value, (list, tuple)):
            return i
        if k == "map" and isinstance(value, dict):
            return i
    raise ValueError(f"Value {value!r} matches no branch of union {union}")


def _decode(buf: io.BytesIO, schema: Schema, resolver: _Resolver) -> Any:
    schema = resolver.resolve(schema)
    if isinstance(schema, list):
        idx = read_long(buf)
        return _decode(buf, schema[idx], resolver)
    t = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(t, (dict, list)):
        return _decode(buf, t, resolver)
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return buf.read(read_long(buf))
    if t == "string":
        return buf.read(read_long(buf)).decode("utf-8")
    if t == "fixed":
        return buf.read(schema["size"])
    if t == "enum":
        return schema["symbols"][read_long(buf)]
    if t == "record":
        return {f["name"]: _decode(buf, f["type"], resolver)
                for f in schema["fields"]}
    if t == "array":
        out: List[Any] = []
        while True:
            count = read_long(buf)
            if count == 0:
                return out
            if count < 0:  # block size follows; we don't need it
                read_long(buf)
                count = -count
            for _ in range(count):
                out.append(_decode(buf, schema["items"], resolver))
    if t == "map":
        entries: Dict[str, Any] = {}
        while True:
            count = read_long(buf)
            if count == 0:
                return entries
            if count < 0:
                read_long(buf)
                count = -count
            for _ in range(count):
                k = _decode(buf, "string", resolver)
                entries[k] = _decode(buf, schema["values"], resolver)
    raise ValueError(f"Unsupported Avro type: {t}")


# ---------------------------------------------------------------------------
# Object container files
# ---------------------------------------------------------------------------
def write_container(path: str, schema: Schema, records: Iterable[Dict[str, Any]],
                    metadata: Optional[Dict[str, str]] = None,
                    sync: Optional[bytes] = None) -> None:
    resolver = _Resolver()
    _walk_register(schema, resolver)
    sync = sync or os.urandom(16)
    meta: Dict[str, Any] = {"avro.schema": json.dumps(schema),
                            "avro.codec": "null"}
    for k, v in (metadata or {}).items():
        meta[k] = v

    body = io.BytesIO()
    count = 0
    for rec in records:
        _encode(body, schema, rec, resolver)
        count += 1

    buf = io.BytesIO()
    buf.write(MAGIC)
    meta_schema = {"type": "map", "values": "bytes"}
    _encode(buf, meta_schema, {k: (v.encode() if isinstance(v, str) else v)
                               for k, v in meta.items()}, resolver)
    buf.write(sync)
    if count:
        data = body.getvalue()
        write_long(buf, count)
        write_long(buf, len(data))
        buf.write(data)
        buf.write(sync)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)


def read_container(path: str) -> List[Dict[str, Any]]:
    records, _ = read_container_with_metadata(path)
    return records


def _read_header(buf, path: str) -> Dict[str, Any]:
    """Decode the container header (magic + file-metadata map), leaving the
    stream positioned at the 16-byte sync marker.  Keys normalized to str,
    values left as bytes.  Works on any .read()-able stream."""
    if buf.read(4) != MAGIC:
        raise ValueError(f"Not an Avro object container file: {path}")
    meta = _decode(buf, {"type": "map", "values": "bytes"}, _Resolver())
    return {(k.decode() if isinstance(k, bytes) else k): v
            for k, v in meta.items()}


def read_container_with_metadata(path: str):
    with open(path, "rb") as f:
        buf = io.BytesIO(f.read())
    meta = _read_header(buf, path)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate"):
        raise ValueError(f"Unsupported Avro codec: {codec}")
    resolver = _Resolver()
    _walk_register(schema, resolver)
    sync = buf.read(16)
    out: List[Dict[str, Any]] = []
    while True:
        try:
            count = read_long(buf)
        except EOFError:
            break
        size = read_long(buf)
        data = buf.read(size)
        if codec == "deflate":
            data = zlib.decompress(data, -15)
        block = io.BytesIO(data)
        for _ in range(count):
            out.append(_decode(block, schema, resolver))
        marker = buf.read(16)
        if marker != sync:
            raise ValueError(f"Avro sync marker mismatch in {path}")
    return out, meta


# ---------------------------------------------------------------------------
# Arrow bridge (Avro as a default-source DATA format)
# ---------------------------------------------------------------------------
# The reference's default source allow-lists avro alongside csv/json/orc/
# parquet/text (HyperspaceConf.scala:97, DefaultFileBasedSource.scala:37-148,
# reading through spark-avro).  These helpers let the engine scan Avro data
# files with the same codec that already serves Iceberg manifests.

def avro_schema_to_arrow(schema: Schema):
    """Arrow schema for a top-level Avro record schema."""
    import pyarrow as pa

    if not (isinstance(schema, dict) and schema.get("type") == "record"):
        raise ValueError(f"Avro data files must carry a record schema, "
                         f"got: {schema!r}")
    return pa.schema([(f["name"], _avro_type_to_arrow(f["type"]))
                      for f in schema["fields"]])


def _avro_type_to_arrow(t: Schema):
    import pyarrow as pa

    prims = {"null": pa.null(), "boolean": pa.bool_(), "int": pa.int32(),
             "long": pa.int64(), "float": pa.float32(),
             "double": pa.float64(), "bytes": pa.binary(),
             "string": pa.string()}
    if isinstance(t, str):
        if t in prims:
            return prims[t]
        raise ValueError(f"Unsupported Avro type for Arrow: {t!r}")
    if isinstance(t, list):  # union: ["null", X] → nullable X
        non_null = [x for x in t if x != "null"]
        if len(non_null) == 1:
            return _avro_type_to_arrow(non_null[0])
        raise ValueError(f"Unsupported Avro union for Arrow: {t!r}")
    if isinstance(t, dict):
        kind = t.get("type")
        if kind == "array":
            return pa.list_(_avro_type_to_arrow(t["items"]))
        if kind == "map":
            return pa.map_(pa.string(), _avro_type_to_arrow(t["values"]))
        if kind == "fixed":
            return pa.binary(int(t["size"]))
        if kind == "enum":
            return pa.string()
        if kind == "record":
            return pa.struct([(f["name"], _avro_type_to_arrow(f["type"]))
                              for f in t["fields"]])
        if kind in prims:  # {"type": "long", ...} annotated primitive
            return prims[kind]
    raise ValueError(f"Unsupported Avro type for Arrow: {t!r}")


def read_schema_only(path: str) -> Schema:
    """The writer schema from a container file's header (no record decode —
    read_schema must stay cheap for large data files)."""
    with open(path, "rb") as f:
        meta = _read_header(f, path)
    return json.loads(meta["avro.schema"].decode("utf-8"))


def to_arrow_table(path: str, columns=None):
    """Decode a container file into an arrow Table (column subset honored
    after decode; the row-oriented format has no column projection)."""
    import pyarrow as pa

    records, meta = read_container_with_metadata(path)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    table = pa.Table.from_pylist(records, schema=avro_schema_to_arrow(schema))
    if columns is not None:
        table = table.select([c for c in columns if c in table.column_names])
    return table
