"""Schema vocabulary bridges: arrow <-> Spark StructType <-> Iceberg types.

Single source of truth for the primitive-type tables and the
timestamp/decimal fallbacks; the Delta writer (metaData.schemaString), the
Iceberg writer (schema JSON with field ids), and both lake readers map
through here so a new engine type lands in exactly one place.

The engine's own schema vocabulary is arrow type strings (io/columnar.py);
Spark's is StructType JSON (what every Delta reader expects in
``metaData.schemaString``); Iceberg's is its schema JSON with field ids.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict

import pyarrow as pa

_ARROW_TO_SPARK = {
    "int8": "byte",
    "int16": "short",
    "int32": "integer",
    "int64": "long",
    "float": "float",
    "double": "double",
    "bool": "boolean",
    "string": "string",
    "large_string": "string",
    "date32[day]": "date",
    "binary": "binary",
}

_SPARK_TO_ARROW = {v: k for k, v in _ARROW_TO_SPARK.items() if v != "string"}
_SPARK_TO_ARROW["string"] = "string"

_ARROW_TO_ICEBERG = {
    "bool": "boolean",
    "int8": "int",
    "int16": "int",
    "int32": "int",
    "int64": "long",
    "float": "float",
    "double": "double",
    "string": "string",
    "large_string": "string",
    "date32[day]": "date",
    "binary": "binary",
}

_ICEBERG_TO_ARROW = {
    "boolean": "bool",
    "int": "int32",
    "long": "int64",
    "float": "float",
    "double": "double",
    "date": "date32[day]",
    "string": "string",
    "binary": "binary",
    "timestamp": "timestamp[us]",
    "timestamptz": "timestamp[us, tz=UTC]",
}

_DECIMAL_ARROW_RE = re.compile(r"^decimal128\((\d+),\s*(\d+)\)$")
_DECIMAL_RE = re.compile(r"^decimal\((\d+),\s*(\d+)\)$")


def _arrow_fallback(arrow_type: str, decimal_fmt: str) -> str:
    """Shared timestamp/decimal handling for arrow -> X mappings."""
    if arrow_type.startswith("timestamp"):
        return "timestamp"
    m = _DECIMAL_ARROW_RE.match(arrow_type)
    if m:
        return decimal_fmt.format(p=m.group(1), s=m.group(2))
    return "string"


def arrow_type_to_spark(arrow_type: str) -> str:
    t = _ARROW_TO_SPARK.get(arrow_type)
    return t if t is not None else _arrow_fallback(arrow_type, "decimal({p},{s})")


def spark_type_to_arrow(spark_type: Any) -> str:
    if not isinstance(spark_type, str):
        return "string"  # nested types surface as strings for now
    if spark_type == "timestamp":
        return "timestamp[us]"
    m = _DECIMAL_RE.match(spark_type)
    if m:
        return f"decimal128({m.group(1)}, {m.group(2)})"
    return _SPARK_TO_ARROW.get(spark_type, "string")


def arrow_type_to_iceberg(arrow_type: str) -> str:
    t = _ARROW_TO_ICEBERG.get(arrow_type)
    return t if t is not None else _arrow_fallback(arrow_type, "decimal({p},{s})")


def iceberg_type_to_arrow(iceberg_type: Any) -> str:
    if isinstance(iceberg_type, str):
        if iceberg_type in _ICEBERG_TO_ARROW:
            return _ICEBERG_TO_ARROW[iceberg_type]
        m = _DECIMAL_RE.match(iceberg_type)
        if m:
            return f"decimal128({m.group(1)}, {m.group(2)})"
    return "string"


def spark_schema_string(schema: pa.Schema) -> str:
    """Arrow schema -> Spark StructType JSON (the ``metaData.schemaString``
    format every Delta reader expects)."""
    fields = [{"name": f.name, "type": arrow_type_to_spark(str(f.type)),
               "nullable": True, "metadata": {}} for f in schema]
    return json.dumps({"type": "struct", "fields": fields})


def arrow_schema_from_spark(schema_string: str) -> Dict[str, str]:
    """Spark StructType JSON -> our name -> arrow-type-string schema dict."""
    parsed = json.loads(schema_string)
    return {f["name"]: spark_type_to_arrow(f["type"])
            for f in parsed.get("fields", [])}


def iceberg_schema(schema: pa.Schema) -> Dict[str, Any]:
    """Arrow schema -> Iceberg schema JSON with sequential field ids."""
    fields = [{"id": i, "name": f.name, "required": False,
               "type": arrow_type_to_iceberg(str(f.type))}
              for i, f in enumerate(schema, start=1)]
    return {"type": "struct", "schema-id": 0, "fields": fields}


def arrow_schema_from_iceberg(schema: Dict[str, Any]) -> Dict[str, str]:
    """Iceberg schema JSON -> our name -> arrow-type-string schema dict."""
    return {f["name"]: iceberg_type_to_arrow(f.get("type"))
            for f in schema.get("fields", [])}
