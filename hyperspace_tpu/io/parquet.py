"""Columnar file IO: read source data, write bucketed index data.

Reference contract: the bucketed+sorted Parquet writer
(index/DataFrameWriterExtensions.scala:49-67 ``saveWithBuckets``) writes one
file per hash bucket, rows sorted within each bucket by the bucket columns.
Spark encodes the bucket id in the task file name (BucketingUtils.getBucketId,
used by OptimizeAction.scala:115-133); we do the same with an explicit
``part-bNNNNN`` prefix so compaction and bucket pruning can map file → bucket
without reading footers.

CSV/JSON sources are read through pyarrow for schema-uniform ingestion; index
data is always Parquet regardless of source format (IndexLogEntry.scala:347).
"""

from __future__ import annotations

import os
import re
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu.utils.parallel_map import parallel_map_ordered

_BUCKET_FILE_RE = re.compile(r"part-b(\d{5})-")


def bucket_file_name(bucket: int) -> str:
    return f"part-b{bucket:05d}-{uuid.uuid4().hex[:12]}.parquet"


def bucket_id_of_file(path: str) -> Optional[int]:
    """Recover the bucket id from an index data file name
    (BucketingUtils.getBucketId analog)."""
    m = _BUCKET_FILE_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def read_table(paths: Sequence[str], file_format: str = "parquet",
               columns: Optional[Sequence[str]] = None,
               options: Optional[Dict[str, str]] = None,
               partition_roots: Optional[Sequence[str]] = None,
               partition_spec: Optional[Dict[str, str]] = None) -> pa.Table:
    """Read and concatenate files into one arrow Table.

    ``partition_roots``: when given, hive-style ``key=value`` directory
    segments below these roots materialize as constant columns per file
    (io/partitions.py) — source scans pass their root paths; index-data
    reads never do.  ``partition_spec`` lets a caller that already walked
    the directory tree pass the inferred spec instead of re-walking."""
    spec: Dict[str, str] = {}
    file_columns = columns
    if partition_roots:
        from hyperspace_tpu.io.partitions import (
            attach_partition_columns,
            partition_spec_for_roots,
        )

        # Spec comes from the directory TREE, not this call's file subset:
        # types must resolve identically for every caller (schema, build,
        # hybrid subsets) or concatenation breaks.
        spec = partition_spec if partition_spec is not None \
            else partition_spec_for_roots(partition_roots)
        if spec and columns and file_format != "parquet":
            # Partition columns come from paths, not file data.
            file_columns = [c for c in columns if c not in spec]

    from hyperspace_tpu.telemetry.trace import span as _span

    def load(path: str) -> pa.Table:
        file_spec, cols = spec, file_columns
        if spec and file_format == "parquet":
            # A column present in THIS data file wins over the path value;
            # in a mixed-schema file set the decision must be per file, or
            # files lacking the column get nulls instead of the path value.
            # One ParquetFile serves both the schema decision and the read —
            # pq.read_table after pq.read_schema would parse the footer twice.
            # Context-managed so the fd closes deterministically — a wide
            # scan through the shared pool must not hold descriptors until
            # GC runs.
            def _read_with_spec():
                with pq.ParquetFile(path) as pf:
                    present = set(pf.schema_arrow.names)
                    fspec = {k: t for k, t in spec.items()
                             if k not in present}
                    fcols = cols if cols is None \
                        else [c for c in columns if c not in fspec]
                    return fspec, pf.read(
                        columns=None if fcols is None
                        else [c for c in fcols if c in present])

            file_spec, t = _read_retry(_read_with_spec)
        else:
            t = _read_one(path, file_format, cols, options or {})
        if file_spec:
            t = attach_partition_columns(t, path, partition_roots, file_spec,
                                         columns)
        return t

    with _span("io.read", files=len(paths), format=file_format) as sp:
        tables = parallel_map_ordered(load, paths)
        if not tables:
            return pa.table({})
        out = pa.concat_tables(tables, promote_options="default")
        sp.set(rows=out.num_rows, bytes=out.nbytes)
        return out


def _read_retry(fn):
    """Single-file READ primitive wrapper: the ``data.read`` fault site
    plus bounded transient-IO retry (the write side has had this since
    PR 1 — a flaky mount mid-query deserves the same envelope as one
    mid-build).  Disarmed cost: one None check per FILE, never per row.
    Every single-file read in the engine passes here, so this is also
    where ``io.files.read`` counts."""
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.utils.retry import RetryPolicy

    def attempt():
        faults.check("data.read")
        return fn()

    out = RetryPolicy().call(attempt)
    metrics.inc("io.files.read")
    return out


def read_parquet_file(path: str, columns=None) -> pa.Table:
    """One parquet FILE, exactly its own columns.  ``partitioning=None``
    matters: newer pyarrow (observed at 22.0) hive-infers partition
    columns from the file's OWN path segments, so reading an index file
    under ``v__=N/`` would grow a phantom ``v__`` column — corrupting
    optimize compaction, sketches, and schema checks.  Every
    single-file read in the engine goes through here (and through the
    ``data.read`` fault site + transient retry)."""
    from hyperspace_tpu.io import faults

    # Corruption checkpoint: a bitrot/truncate plan armed at data.read
    # damages the file ON DISK just before this read — the read then
    # fails (or decodes garbage) exactly like bit-rot discovered at
    # query time, and stays failed on retry (corruption is persistent).
    faults.corrupt_file("data.read", path)
    return _read_retry(
        lambda: pq.read_table(path, columns=columns, partitioning=None))


def _read_one(path: str, file_format: str, columns, options: Dict[str, str]) -> pa.Table:
    if file_format != "parquet":
        # Parquet delegates to read_parquet_file (already wrapped); every
        # other format wraps here so each single-file read counts exactly
        # one data.read site call.
        return _read_retry(
            lambda: _read_one_raw(path, file_format, columns, options))
    return _read_one_raw(path, file_format, columns, options)


def _read_one_raw(path: str, file_format: str, columns,
                  options: Dict[str, str]) -> pa.Table:
    if file_format == "parquet":
        # columns=[] is meaningful: read NO data columns but keep the row
        # count (a projection of partition-only columns).
        if columns is not None:
            try:
                return read_parquet_file(path, columns=list(columns))
            except (pa.ArrowInvalid, KeyError):
                # Mixed-schema file set (a column added by a later append):
                # read the columns this file has; concat promotes the rest
                # to nulls.  An empty intersection still reads zero columns
                # (row count preserved).  The footer is only read twice on
                # this rare path, not per file in the uniform-schema case.
                present = set(pq.read_schema(path).names)
                return read_parquet_file(
                    path, columns=[c for c in columns if c in present])
        return read_parquet_file(path)
    if file_format == "csv":
        import pyarrow.csv as pacsv

        read_opts = pacsv.ReadOptions()
        if options.get("header", "true").lower() == "false":
            read_opts.autogenerate_column_names = True
        table = pacsv.read_csv(path, read_options=read_opts)
    elif file_format == "json":
        import pyarrow.json as pajson

        table = pajson.read_json(path)
    elif file_format == "orc":
        import pyarrow.orc as paorc

        if columns is not None:
            present = set(paorc.ORCFile(path).schema.names)
            return paorc.read_table(
                path, columns=[c for c in columns if c in present])
        return paorc.read_table(path)
    elif file_format == "avro":
        from hyperspace_tpu.io import avro as hsavro

        return hsavro.to_arrow_table(path, columns)
    elif file_format == "text":
        # Spark's text source shape: one string column "value", one row per
        # line (DefaultFileBasedSource.scala:37-43's allow-listed format).
        # Split on \n / \r / \r\n ONLY — str.splitlines would also split on
        # \x0b, \x85, U+2028 etc., diverging from Hadoop's LineRecordReader.
        with open(path, "rb") as f:
            text = f.read().decode("utf-8")
        lines = text.replace("\r\n", "\n").replace("\r", "\n").split("\n")
        if lines and lines[-1] == "":
            lines.pop()  # trailing newline does not make an empty last row
        table = pa.table({"value": pa.array(lines, type=pa.string())})
        if columns is not None:
            return table.select([c for c in columns if c in table.column_names])
        return table
    else:
        raise ValueError(f"Unsupported file format: {file_format!r}")
    if columns:
        table = table.select(list(columns))
    return table


def read_schema(path: str, file_format: str = "parquet",
                options: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Column name → arrow dtype string for one file."""
    if file_format == "parquet":
        schema = _read_retry(lambda: pq.read_schema(path))
        return {f.name: str(f.type) for f in schema}
    if file_format == "orc":
        import pyarrow.orc as paorc

        # ORC footers carry the schema — no data read needed.
        return {f.name: str(f.type) for f in paorc.ORCFile(path).schema}
    if file_format == "avro":
        from hyperspace_tpu.io import avro as hsavro

        # Container headers carry the writer schema — no record decode.
        return {f.name: str(f.type) for f in hsavro.avro_schema_to_arrow(
            hsavro.read_schema_only(path))}
    if file_format == "text":
        return {"value": "string"}
    table = _read_one(path, file_format, None, options or {})
    return {f.name: str(f.type) for f in table.schema}


def schema_to_arrow(schema: Dict[str, str]) -> pa.Schema:
    return pa.schema([(name, _dtype_from_string(t)) for name, t in schema.items()])


def _dtype_from_string(t: str) -> pa.DataType:
    if t.startswith("timestamp"):
        m = re.match(r"timestamp\[(\w+)(?:, tz=(.*))?\]", t)
        if m:
            return pa.timestamp(m.group(1), tz=m.group(2))
    if t.startswith("decimal128"):
        m = re.match(r"decimal128\((\d+),\s*(\d+)\)", t)
        if m:
            return pa.decimal128(int(m.group(1)), int(m.group(2)))
    try:
        return pa.type_for_alias(t)
    except ValueError:
        return pa.string()


def bucket_chunks(n_rows: int, max_rows_per_file: int) -> List:
    """[(offset, rows)] splitting a bucket run at ``max_rows_per_file``
    (0 = single chunk) — the one home for the chunking rule."""
    chunk = max_rows_per_file if max_rows_per_file > 0 else max(n_rows, 1)
    return [(off, min(chunk, n_rows - off))
            for off in range(0, n_rows, chunk)]


def zorder_codes_from_order_words(word_cols: List[np.ndarray]
                                  ) -> Tuple[np.ndarray, int]:
    """(uint64 Morton code per row, total code bits) from per-column
    (n, 2) uint32 monotone order words — the streaming build accumulates
    words per chunk (8 B/row/column) instead of raw key columns, so this
    entry point keeps its peak memory independent of key width."""
    from hyperspace_tpu.ops.zorder import zorder_order_words_np

    z = zorder_order_words_np([np.asarray(w) for w in word_cols])
    codes = (z[:, 0].astype(np.uint64) << np.uint64(32)) \
        | z[:, 1].astype(np.uint64)
    return codes, 16 * len(word_cols)


def zorder_codes_host(table: pa.Table, indexed_columns) -> Tuple[np.ndarray, int]:
    """(uint64 Morton code per row, total code bits) for a Z-order layout —
    the writer's file-split key.  Host mirror of the build kernel's codes
    (ops/zorder.py): dense ranks per column scaled to 16 bits, interleaved."""
    from hyperspace_tpu.io import columnar

    return zorder_codes_from_order_words([
        np.asarray(columnar.to_order_words(table.column(c)))
        for c in indexed_columns])


def zorder_split_chunks(z_sorted: np.ndarray, key_bits: int,
                        max_rows_per_file: int) -> List:
    """[(offset, rows)] for one bucket run ALIGNED to Morton cell
    boundaries.  Equal-row splits smear a file across two Z-curve cells and
    widen its per-dimension min/max (the sketch-pruning lever); cutting
    where the top ``level`` code bits change keeps every file inside one
    cell, so range predicates on ANY indexed dimension prune sharply.
    ``max_rows_per_file`` still caps a skewed cell's file size."""
    n = int(len(z_sorted))
    if n == 0:
        return []
    if max_rows_per_file <= 0 or n <= max_rows_per_file:
        return [(0, n)]
    target_files = -(-n // max_rows_per_file)
    level = max(1, min(key_bits, int(np.ceil(np.log2(target_files)))))
    cells = z_sorted >> np.uint64(key_bits - level)
    cuts = (np.flatnonzero(np.diff(cells)) + 1).tolist()
    bounds = [0, *cuts, n]
    out: List = []
    for i in range(len(bounds) - 1):
        off = bounds[i]
        for o, r in bucket_chunks(bounds[i + 1] - off, max_rows_per_file):
            out.append((off + o, r))
    return out


# Parquet codec for index data; "none" means uncompressed.  Conf
# hyperspace.tpu.indexFileCompression overrides per session (actions pass
# it through); the default favors decode speed (see config.py).
INDEX_COMPRESSION_DEFAULT = "lz4"


def _codec(compression: Optional[str]):
    c = (compression or INDEX_COMPRESSION_DEFAULT).lower()
    return None if c == "none" else c


def write_bucket_run(sorted_bucket_table: pa.Table, bucket: int,
                     out_dir: str, max_rows_per_file: int = 0,
                     split_keys: Optional[np.ndarray] = None,
                     split_key_bits: int = 0,
                     compression: Optional[str] = None) -> List[str]:
    """Write ONE bucket's already-sorted rows, split at
    ``max_rows_per_file`` — shared by the external build's phase 2 and
    optimize's compaction (both already parallelize per bucket; the
    monolithic writer parallelizes per chunk via ``bucket_chunks``).
    ``split_keys``: sorted Morton codes for a Z-order layout — files then
    cut at cell boundaries (``zorder_split_chunks``) instead of row
    counts."""
    if split_keys is not None:
        chunks = zorder_split_chunks(split_keys, split_key_bits,
                                     max_rows_per_file)
    else:
        chunks = bucket_chunks(sorted_bucket_table.num_rows,
                               max_rows_per_file)
    from hyperspace_tpu.io import faults

    from hyperspace_tpu.io import integrity
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.trace import span

    out: List[str] = []
    with span("io.write", bucket=bucket,
              rows=sorted_bucket_table.num_rows) as sp:
        for off, rows in chunks:
            path = os.path.join(out_dir, bucket_file_name(bucket))
            # Crash checkpoint: an action killed mid-data-write leaves
            # partial index data under an uncommitted version dir + a
            # transient log state — the shape cancel()/auto-recovery must
            # clean up after.
            faults.check("data.write")
            pq.write_table(sorted_bucket_table.slice(off, rows), path,
                           compression=_codec(compression))
            # Digest of the INTENDED bytes first, then the corruption
            # checkpoint (bitrot keeps size+mtime, truncate halves the
            # file): the damage lands after a write the writer believed
            # good.
            integrity.record_file(path)
            faults.corrupt_file("data.write", path)
            metrics.inc("io.files.written")
            out.append(path)
        sp.set(files=len(out))
    return out


def sort_permutation_host(table: pa.Table, indexed_columns, layout: str):
    """Host-side within-bucket sort permutation honoring the index LAYOUT —
    lexicographic over the indexed columns, or Morton order for
    ``layout == "zorder"`` (per-batch ranks, via the one zorder_codes_host
    code path).  Z-order callers that also need cell-aligned file cuts use
    ``write_zorder_run`` instead."""
    from hyperspace_tpu.io import columnar

    if layout == "zorder":
        codes, _ = zorder_codes_host(table, indexed_columns)
        return np.argsort(codes, kind="stable")
    keys: List[np.ndarray] = []
    for c in reversed(list(indexed_columns)):
        w = np.asarray(columnar.to_order_words(table.column(c)))
        # One uint64 key per column: the same total order as the (hi,
        # lo) uint32 pair in half the stable-sort passes (the 32-bit
        # split serves the TPU lanes, not numpy).
        keys.append(columnar.join_words64(w[:, 0], w[:, 1]))
    return np.lexsort(tuple(keys))


def sort_permutation_from_codes(btable: pa.Table, code_columns) -> np.ndarray:
    """Within-bucket sort permutation from PRECOMPUTED ride-along sort
    codes — one monotone uint64 column per indexed column, attached by
    the external build's route pass (actions/create._BucketSpill), in
    indexed-column order.  The stable lexsort over them reproduces
    ``sort_permutation_host`` bit-exactly for value-mapped key types
    (numeric/temporal/bool: their order words are chunk-independent)
    without re-deriving order words from the raw values — the codes were
    already computed once for the fused route+partition kernel.  Code
    columns are zero-copy uint64, so this is the cheap half of the old
    sort."""
    keys: List[np.ndarray] = []
    # np.lexsort: LAST key is primary — append in reversed column order
    # so the first indexed column sorts first (sort_permutation_host's
    # key order exactly).
    for name in reversed(list(code_columns)):
        keys.append(btable.column(name).to_numpy(zero_copy_only=False))
    return np.lexsort(tuple(keys))


def write_zorder_run(btable: pa.Table, bucket: int, out_dir: str,
                     max_rows_per_file: int, indexed_columns,
                     compression: Optional[str] = None) -> List[str]:
    """Morton-sort one run by BATCH-LOCAL ranks and write it with
    Z-cell-aligned file cuts.  Used by optimize's compaction, which merges
    a SUBSET of an index's files: local ranks keep the merged subset
    clustered (per-file min/max stays narrow, which is all the sketches
    consume) without a global pass.  The BUILD no longer goes through
    here — it computes GLOBAL ranks in the two-pass streaming path
    (actions/create._zorder_streaming_build) or the monolithic writer, so
    fresh indexes carry the exact global curve."""
    codes, bits = zorder_codes_host(btable, indexed_columns)
    perm = np.argsort(codes, kind="stable")
    return write_bucket_run(btable.take(pa.array(perm)), bucket, out_dir,
                            max_rows_per_file,
                            split_keys=codes[perm], split_key_bits=bits,
                            compression=compression)


def write_bucketed(table: pa.Table, bucket_ids: np.ndarray, sort_perm: np.ndarray,
                   num_buckets: int, out_dir: str,
                   max_rows_per_file: int = 0,
                   split_keys: Optional[np.ndarray] = None,
                   split_key_bits: int = 0,
                   compression: Optional[str] = None) -> List[str]:
    """Write ``table`` as sorted Parquet files, one or more per non-empty
    bucket.

    ``sort_perm`` is a permutation ordering rows by (bucket, sort columns) —
    computed on device by the build kernel; ``bucket_ids`` are per-row bucket
    assignments (pre-permutation).  Empty buckets get no file, matching
    Spark's bucketed write behavior.  ``max_rows_per_file`` > 0 splits each
    bucket's sorted run into chunks — consecutive key (or Z-code) ranges per
    file, which is what gives the per-file min/max sketch its pruning
    granularity within a bucket.  ``split_keys`` (per-row PRE-permutation
    Morton codes, Z-order layout) aligns those cuts to Z-curve cell
    boundaries via ``zorder_split_chunks``.
    """
    os.makedirs(out_dir, exist_ok=True)
    sorted_buckets = np.asarray(bucket_ids)[sort_perm]
    sorted_table = table.take(pa.array(sort_perm))
    sorted_keys = None if split_keys is None \
        else np.asarray(split_keys)[sort_perm]
    # Bucket boundaries within the sorted order.
    starts = np.searchsorted(sorted_buckets, np.arange(num_buckets), side="left")
    ends = np.searchsorted(sorted_buckets, np.arange(num_buckets), side="right")
    jobs: List = []  # one PER CHUNK: skewed/low-bucket builds still
    # parallelize their writes
    for b in range(num_buckets):
        n = int(ends[b] - starts[b])
        if n == 0:
            continue
        if sorted_keys is not None:
            chunks = zorder_split_chunks(
                sorted_keys[int(starts[b]):int(ends[b])], split_key_bits,
                max_rows_per_file)
        else:
            chunks = bucket_chunks(n, max_rows_per_file)
        for off, rows in chunks:
            jobs.append((b, int(starts[b]) + off, rows))

    def write(job) -> str:
        from hyperspace_tpu.io import faults, integrity
        from hyperspace_tpu.telemetry import metrics

        b, start, rows = job
        path = os.path.join(out_dir, bucket_file_name(b))
        # Crash checkpoint, same site as write_bucket_run: both writers
        # are "an index data file lands on disk".
        faults.check("data.write")
        pq.write_table(sorted_table.slice(start, rows), path,
                       compression=_codec(compression))
        # Digest of the INTENDED bytes first, then the corruption
        # checkpoint: bitrot/truncate model damage after a write the
        # writer believed good — exactly what the digest must catch.
        integrity.record_file(path)
        faults.corrupt_file("data.write", path)
        metrics.inc("io.files.written")
        return path

    from hyperspace_tpu.telemetry.trace import span

    with span("io.write", rows=table.num_rows, files=len(jobs)):
        return parallel_map_ordered(write, jobs)
