"""Content digests for index data files — the detection layer of the
integrity subsystem (detect → quarantine → serve degraded → repair).

The operation log got its crash-safety in PR 1/PR 2; the index *data*
files under ``v__=N/`` carried none.  Silent corruption (bit-rot, a
truncated put, a partial object-store write) previously surfaced only as
an unexplained scan failure whose sole remedy was the whole-index
degraded fallback.  This module closes the detection gap:

  - every index data file written through ``io/parquet.write_bucket_run``
    (create / refresh / optimize / repair all funnel there) is hashed as
    it lands and the digest recorded here;
  - ``index/log_entry.Directory._scan`` picks the recorded digest up when
    the action builds its content tree, so the committed ``FileInfo``
    carries ``digest`` alongside (size, mtime);
  - ``VerifyIndexAction`` (actions/verify.py) re-hashes on demand and
    quarantines mismatches (index/quarantine.py).

Digest format is ``"<algo>:<hex>"`` — ``xxh64`` when the C extension is
available (the normal container), ``blake2b16`` (8-byte blake2b, stdlib)
otherwise — so a scrub always re-hashes with the ALGORITHM THE WRITER
USED, and moving an index between environments can never manufacture a
false mismatch.  Entries serialized before digests existed load with
``digest=None`` and scrub as ``status="unknown"``.

Recording is a process-global map (abspath → digest), like the fault
injector: the writer (``write_bucket_run``) and the consumer
(``Directory._scan``) are separated by the action layer and a
thread-pool, so threading a handle through every call chain would touch
a dozen signatures for what is one put and one get per file.  The map is
bounded (LRU) — an abandoned build can never grow it without limit.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional

try:  # the normal container ships the C extension; stdlib fallback below
    import xxhash as _xxhash
except ImportError:  # pragma: no cover - exercised via the algo registry
    _xxhash = None

_CHUNK = 1 << 20  # streamed hashing granularity (1 MiB)
_MAX_RECORDED = 8192  # LRU bound on the write-site recorder


def _xxh64_hasher():
    return _xxhash.xxh64()


def _blake2b16_hasher():
    import hashlib

    return hashlib.blake2b(digest_size=8)


# algo name -> zero-arg hasher factory (objects expose update/hexdigest).
_ALGOS = {}
if _xxhash is not None:
    _ALGOS["xxh64"] = _xxh64_hasher
_ALGOS["blake2b16"] = _blake2b16_hasher

DEFAULT_ALGO = "xxh64" if _xxhash is not None else "blake2b16"


def digest_bytes(data: bytes, algo: str = None) -> str:
    algo = algo or DEFAULT_ALGO
    h = _ALGOS[algo]()
    h.update(data)
    return f"{algo}:{h.hexdigest()}"


def digest_file(path: str, algo: str = None) -> str:
    """Streamed content digest of ``path`` (never loads the file whole)."""
    algo = algo or DEFAULT_ALGO
    h = _ALGOS[algo]()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return f"{algo}:{h.hexdigest()}"


def verify_file(path: str, expected: str) -> Optional[bool]:
    """True/False for a recomputable digest; None when ``expected`` names
    an algorithm this environment cannot run (scrub reports "unknown"
    instead of inventing a mismatch)."""
    algo = expected.split(":", 1)[0] if ":" in expected else ""
    if algo not in _ALGOS:
        return None
    return digest_file(path, algo) == expected


# ---------------------------------------------------------------------------
# The write-site recorder
# ---------------------------------------------------------------------------
_enabled = True
_recorded: "OrderedDict[str, str]" = OrderedDict()
_lock = threading.Lock()


def configure_from_conf(conf) -> None:
    """Apply ``hyperspace.system.integrity.digestOnWrite`` (sessions call
    this at construction; actions re-apply before writing so the latest
    conf value wins even for a long-lived session object)."""
    set_enabled(bool(getattr(conf, "integrity_digest_on_write", True)))


def set_enabled(enabled: bool) -> None:
    global _enabled
    _enabled = bool(enabled)


def record_file(path: str) -> Optional[str]:
    """Hash the just-written file at ``path`` and remember its digest for
    the content-tree builder; no-op (None) when digest-on-write is off."""
    if not _enabled:
        return None
    digest = digest_file(path)
    key = os.path.abspath(path)
    with _lock:
        _recorded[key] = digest
        _recorded.move_to_end(key)
        while len(_recorded) > _MAX_RECORDED:
            _recorded.popitem(last=False)
    return digest


def recorded_digest(path: str) -> Optional[str]:
    """The digest recorded for ``path`` at write time, if any (source
    files are never recorded, so their FileInfos keep digest=None)."""
    with _lock:
        return _recorded.get(os.path.abspath(path))


def clear_recorded() -> None:
    with _lock:
        _recorded.clear()
