"""Opt-in workload capture: a bounded, deduplicated log of query shapes.

``Dataset.collect`` feeds one record per query here when
``hyperspace.advisor.capture.enabled`` is on, built from the user's
logical plan plus the query's run report (telemetry/report.py carries the
measured per-scan bytes).  A *fingerprint* is purely structural — filter
columns and their predicate kinds, join keys, grouping and projected
columns, source relation roots — never literal data values, so capturing
is safe to leave on against sensitive data.

Records persist through the :class:`~hyperspace_tpu.io.log_store.LogStore`
seam (backend follows ``hyperspace.index.logStoreClass``) under
``<systemPath>/_hyperspace_workload/`` — one percent-encoded flat key per
fingerprint — so the same code works over :class:`PosixLogStore` and
:class:`EmulatedObjectStore`, survives restarts, and merges across
processes via generation-CAS.

Cost contract (bench.py ``advisor`` section gates < 3% on the filter
workload): repeats of a known fingerprint fold into an in-process hit
counter and only flush to the store at power-of-two total hit counts (or
every 32 pending), so the steady-state per-query cost is a plan walk and
a dict update.  ``flush_pending`` forces the counters out — the
recommender and ``workload_table`` call it first, so reads never lag.

Bound: at most ``hyperspace.advisor.capture.maxEntries`` distinct
fingerprints; new shapes beyond the cap are dropped and counted in the
``advisor.capture.dropped`` metric.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

from hyperspace_tpu.plan.expr import BinOp, Col, IsIn, Lit, split_conjuncts
from hyperspace_tpu.plan.nodes import Aggregate, Filter, Join, LogicalPlan

WORKLOAD_DIR = "_hyperspace_workload"
RECORD_VERSION = 1
# Pending hits are forced out whenever they exceed this, even off a
# power-of-two boundary (bounds worst-case loss on an abrupt exit).
MAX_PENDING = 32


def workload_root(conf) -> str:
    from hyperspace_tpu.index.path_resolver import PathResolver

    return os.path.join(PathResolver(conf).system_path, WORKLOAD_DIR)


def store_for(conf):
    """The capture store: backend class from
    ``hyperspace.index.logStoreClass`` (the quarantine manager's exact
    construction), rooted at the workload dir."""
    from hyperspace_tpu.exceptions import HyperspaceError
    from hyperspace_tpu.io.log_store import LogStore
    from hyperspace_tpu.utils.reflection import load_class

    cls = load_class(conf.log_store_class, LogStore, HyperspaceError)
    return cls(workload_root(conf),
               stale_list_s=float(getattr(
                   conf, "object_store_stale_list_ms", 0.0)) / 1000.0)


# ---------------------------------------------------------------------------
# Fingerprinting
# ---------------------------------------------------------------------------
def _relation_key(rel) -> str:
    return json.dumps({"roots": sorted(rel.root_paths),
                       "format": rel.file_format.lower(),
                       "options": sorted(rel.options)}, sort_keys=True)


def _classify_conjunct(e) -> Optional[Tuple[str, List[str]]]:
    """("eq"|"range", columns) for one conjunct, None when unclassifiable.

    eq = the predicate pins the column to a finite value set (equality or
    IN — the shapes bucket pruning exploits); range = an inequality
    against a literal (the shapes sketch/Z-order pruning exploits)."""
    if isinstance(e, BinOp):
        cols = sorted(e.referenced_columns())
        if not cols:
            return None
        lit_side = isinstance(e.left, Lit) or isinstance(e.right, Lit)
        if e.op == "==" and lit_side:
            return "eq", cols
        if e.op in ("<", "<=", ">", ">=") and lit_side:
            return "range", cols
        return None
    if isinstance(e, IsIn) and isinstance(e.child, Col):
        return "eq", [e.child.name]
    return None


def _resolve_one(col: str, schema: List[str]) -> Optional[str]:
    lowered = col.lower()
    for s in schema:
        if s.lower() == lowered:
            return s
    return None


def fingerprint(session, plan: LogicalPlan) -> Optional[Dict[str, Any]]:
    """The structural fingerprint of ``plan``: per source relation, which
    columns its filters pin (eq) or bound (range), which join keys touch
    it, which columns the query needs from it.  None when the plan has no
    supported source relations (nothing for the advisor to index)."""
    scans = [s for s in plan.leaf_relations()
             if s.relation.index_scan_of is None]
    if not scans:
        return None
    tables: Dict[str, Dict[str, Any]] = {}
    schema_of: Dict[str, List[str]] = {}
    for s in scans:
        key = _relation_key(s.relation)
        if key not in tables:
            tables[key] = {"roots": list(s.relation.root_paths),
                           "format": s.relation.file_format.lower(),
                           "options": [list(kv) for kv in s.relation.options],
                           "eq": [], "range": [], "join": [], "group": [],
                           "projected": []}
            try:
                schema_of[key] = list(session.schema_of(s))
            except Exception:  # noqa: BLE001 — an unreadable relation
                # still fingerprints; column attribution just degrades.
                schema_of[key] = []

    def attribute(cols: List[str], field: str,
                  candidate_keys: List[str]) -> None:
        for c in cols:
            for key in candidate_keys:
                resolved = _resolve_one(c, schema_of.get(key, []))
                if resolved is not None:
                    bucket = tables[key][field]
                    if resolved not in bucket:
                        bucket.append(resolved)
                    break

    all_keys = list(tables)

    def walk(node: LogicalPlan) -> None:
        if isinstance(node, Filter):
            below = [_relation_key(s.relation)
                     for s in node.leaf_relations()
                     if s.relation.index_scan_of is None]
            keys = sorted(set(below)) or all_keys
            for conj in split_conjuncts(node.condition):
                hit = _classify_conjunct(conj)
                if hit is not None:
                    attribute(hit[1], hit[0], keys)
        elif isinstance(node, Join):
            from hyperspace_tpu.plan.expr import as_equi_join_pairs

            for a, b in as_equi_join_pairs(node.condition) or ():
                attribute([a, b], "join", all_keys)
        elif isinstance(node, Aggregate):
            attribute(list(node.group_by), "group", all_keys)
        for c in node.children:
            walk(c)

    walk(plan)
    try:
        output = plan.output_columns(session.schema_of)
    except Exception:  # noqa: BLE001
        output = []
    for key in all_keys:
        needed = list(output) + tables[key]["eq"] + tables[key]["range"] \
            + tables[key]["join"] + tables[key]["group"]
        attribute(needed, "projected", [key])
        for field in ("eq", "range", "join", "group", "projected"):
            tables[key][field] = sorted(tables[key][field])
    return {"tables": [tables[k] for k in sorted(tables)]}


def fingerprint_key(fp: Dict[str, Any]) -> str:
    digest = hashlib.sha1(
        json.dumps(fp, sort_keys=True).encode("utf-8")).hexdigest()[:16]
    return urllib.parse.quote(f"q-{digest}", safe="")


# ---------------------------------------------------------------------------
# The in-process pending cache (the <3%-overhead mechanism)
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class _Pending:
    fp: Dict[str, Any]
    hits: int = 0
    bytes_total: int = 0
    duration_ms_total: float = 0.0
    last: Dict[str, Any] = dataclasses.field(default_factory=dict)
    stored_hits: Optional[int] = None  # None = store state unknown
    dropped: bool = False  # cap hit: stop trying to persist this key


_lock = threading.Lock()
_pending: Dict[Tuple[str, str], _Pending] = {}


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def capture(session, plan: LogicalPlan, report,
            result_rows: Optional[int] = None) -> None:
    """Record one executed query.  Never raises (a capture failure must
    never cost a query its answer); InjectedCrash still propagates —
    a simulated process death is not a capture failure."""
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.trace import span

    try:
        with span("advisor.capture"):
            _capture_inner(session, plan, report, result_rows)
            metrics.inc("advisor.queries_captured")
    except Exception:  # noqa: BLE001 — see docstring
        metrics.inc("advisor.capture.errors")


def _capture_inner(session, plan, report, result_rows) -> None:
    fp = fingerprint(session, plan)
    if fp is None:
        return
    key = fingerprint_key(fp)
    root = workload_root(session.conf)

    bytes_scanned = report.bytes_read() if report is not None else 0
    source_bytes = report.bytes_read(is_index=False) if report else 0
    scans = report.scans() if report is not None else []
    # Per-table measured bytes: match report scan records (relation =
    # ",".join(root_paths) for source scans) back to fingerprint tables.
    by_roots = {",".join(t["roots"]): t for t in fp["tables"]}
    table_bytes = {}
    for d in scans:
        t = by_roots.get(d.get("relation", ""))
        if t is not None:
            tkey = ",".join(t["roots"])
            table_bytes[tkey] = table_bytes.get(tkey, 0) \
                + int(d.get("bytes_read", 0))
    rows_scanned = 0
    stats = session.last_execution_stats or {}
    for s in stats.get("scans", []):
        rows_scanned += int(s.get("rows", 0) or 0)
    selectivity = None
    if result_rows is not None and rows_scanned > 0:
        selectivity = round(min(1.0, result_rows / rows_scanned), 6)

    last = {"bytes_scanned": int(bytes_scanned),
            "source_bytes": int(source_bytes),
            "table_bytes": table_bytes,
            "result_rows": result_rows,
            "selectivity": selectivity,
            "duration_ms": round(getattr(report, "duration_ms", 0.0), 3),
            "ts": time.time()}

    with _lock:
        p = _pending.get((root, key))
        if p is None:
            p = _Pending(fp=fp)
            _pending[(root, key)] = p
        p.hits += 1
        p.bytes_total += int(bytes_scanned)
        p.duration_ms_total += last["duration_ms"]
        p.last = last
        if p.dropped:
            return
        total = (p.stored_hits or 0) + p.hits
        if p.stored_hits is not None and not _is_pow2(total) \
                and p.hits < MAX_PENDING:
            return  # fold into the counter; flush at the next boundary
        _flush_locked(session.conf, key, p)


def _flush_locked(conf, key: str, p: _Pending) -> None:
    """Merge ``p``'s pending counters into the store (generation-CAS,
    bounded retries — losing every race just defers to the next flush)."""
    from hyperspace_tpu.telemetry import metrics

    store = store_for(conf)
    for _ in range(4):
        data, gen = store.read_with_generation(key)
        if data is None:
            if len(store.list_keys()) >= int(conf.advisor_capture_max_entries):
                metrics.inc("advisor.capture.dropped")
                p.dropped = True
                return
            rec = {"v": RECORD_VERSION, "tables": p.fp["tables"],
                   "hits": p.hits, "bytes_scanned_total": p.bytes_total,
                   "duration_ms_total": round(p.duration_ms_total, 3),
                   **{f"last_{k}": v for k, v in p.last.items()}}
            payload = json.dumps(rec).encode("utf-8")
            if store.put_if_absent(key, payload):
                p.stored_hits = p.hits
                p.hits = p.bytes_total = 0
                p.duration_ms_total = 0.0
                return
        else:
            try:
                rec = json.loads(data.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                # Torn record: rewrite it wholesale from what we know.
                rec = {"v": RECORD_VERSION, "tables": p.fp["tables"],
                       "hits": 0, "bytes_scanned_total": 0,
                       "duration_ms_total": 0.0}
            rec["hits"] = int(rec.get("hits", 0)) + p.hits
            rec["bytes_scanned_total"] = \
                int(rec.get("bytes_scanned_total", 0)) + p.bytes_total
            rec["duration_ms_total"] = round(
                float(rec.get("duration_ms_total", 0.0))
                + p.duration_ms_total, 3)
            for k, v in p.last.items():
                rec[f"last_{k}"] = v
            payload = json.dumps(rec).encode("utf-8")
            if store.put_if_generation_match(key, payload, gen):
                p.stored_hits = rec["hits"]
                p.hits = p.bytes_total = 0
                p.duration_ms_total = 0.0
                return
    metrics.inc("advisor.capture.cas_giveup")


def flush_pending(conf) -> None:
    """Force every pending hit counter for this conf's workload root out
    to the store — called before any read path (recommend, table dump) so
    the write-behind counter never skews what the advisor sees."""
    root = workload_root(conf)
    with _lock:
        for (r, key), p in list(_pending.items()):
            if r == root and p.hits > 0 and not p.dropped:
                _flush_locked(conf, key, p)


def reset_cache() -> None:
    """Drop the in-process pending cache (tests; a cleared store)."""
    with _lock:
        _pending.clear()


# ---------------------------------------------------------------------------
# Reads
# ---------------------------------------------------------------------------
def records(conf) -> List[Dict[str, Any]]:
    """Every persisted workload record, with this process's pending
    write-behind counters overlaid IN MEMORY — a pure read.  The overlay
    applies the same merge the flush would, so callers see current
    numbers without this path ever touching the store write side: the
    interop ``workload`` verb answers inline during overload
    (blocking-discipline, docs/18), where a store put could stall it.
    Durability still comes from the pow2-boundary flushes (and
    :func:`flush_pending`, which the recommend/daemon paths call before
    scoring).  Unparseable records are skipped — capture is advisory
    data."""
    store = store_for(conf)
    out: List[Dict[str, Any]] = []
    by_key: Dict[str, Dict[str, Any]] = {}
    for key in store.list_keys():
        try:
            rec = json.loads(store.read(key).decode("utf-8"))
        except (FileNotFoundError, ValueError, UnicodeDecodeError):
            continue
        if not isinstance(rec, dict) or "tables" not in rec:
            continue
        rec["key"] = key
        out.append(rec)
        by_key[key] = rec
    root = workload_root(conf)
    with _lock:
        for (r, key), p in _pending.items():
            if r != root or p.hits <= 0 or p.dropped:
                continue
            rec = by_key.get(key)
            if rec is None:
                rec = {"v": RECORD_VERSION, "tables": p.fp["tables"],
                       "hits": 0, "bytes_scanned_total": 0,
                       "duration_ms_total": 0.0, "key": key}
                out.append(rec)
                by_key[key] = rec
            rec["hits"] = int(rec.get("hits", 0)) + p.hits
            rec["bytes_scanned_total"] = \
                int(rec.get("bytes_scanned_total", 0)) + p.bytes_total
            rec["duration_ms_total"] = round(
                float(rec.get("duration_ms_total", 0.0))
                + p.duration_ms_total, 3)
            for k, v in p.last.items():
                rec[f"last_{k}"] = v
    return sorted(out, key=lambda r: (-int(r.get("hits", 0)), r["key"]))


def workload_table(conf):
    """The captured workload as an arrow table (one row per fingerprint),
    the shape ``Hyperspace.captured_workload()`` and the interop
    ``workload`` verb return."""
    import pyarrow as pa

    rows = {"key": [], "hits": [], "relations": [], "eqColumns": [],
            "rangeColumns": [], "joinColumns": [], "groupColumns": [],
            "projectedColumns": [], "lastBytesScanned": [],
            "bytesScannedTotal": [], "lastDurationMs": [],
            "lastSelectivity": []}
    for rec in records(conf):
        tables = rec.get("tables", [])

        def gather(field):
            return sorted({c for t in tables for c in t.get(field, [])})

        rows["key"].append(rec["key"])
        rows["hits"].append(int(rec.get("hits", 0)))
        rows["relations"].append(
            [",".join(t.get("roots", [])) for t in tables])
        rows["eqColumns"].append(gather("eq"))
        rows["rangeColumns"].append(gather("range"))
        rows["joinColumns"].append(gather("join"))
        rows["groupColumns"].append(gather("group"))
        rows["projectedColumns"].append(gather("projected"))
        rows["lastBytesScanned"].append(int(rec.get("last_bytes_scanned", 0)))
        rows["bytesScannedTotal"].append(
            int(rec.get("bytes_scanned_total", 0)))
        rows["lastDurationMs"].append(
            float(rec.get("last_duration_ms", 0.0)))
        sel = rec.get("last_selectivity")
        rows["lastSelectivity"].append(
            float(sel) if sel is not None else None)
    return pa.table(rows)


def clear(conf) -> None:
    """Wipe the captured workload (store + in-process counters)."""
    store = store_for(conf)
    for key in store.list_keys():
        store.delete(key)
    root = workload_root(conf)
    with _lock:
        for rk in [rk for rk in _pending if rk[0] == root]:
            del _pending[rk]
