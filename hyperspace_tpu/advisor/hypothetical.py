"""Hypothetical indexes and what-if planning: plan as if an index existed.

A hypothetical entry is an ACTIVE-looking
:class:`~hyperspace_tpu.index.log_entry.IndexLogEntry` with ZERO data
files and the ``hypothetical`` property set.  The existing rewrite rules
match it exactly like a real index (the source snapshot and signature are
computed from the live relation, so candidate selection's
signature-match check passes), which is the whole point: the what-if
answer is the real optimizer's answer, not a parallel cost model's.

Three hard guarantees keep what-if entries out of real execution:

  - the log managers refuse to persist a tagged entry (both backends),
    so one can never appear in ``get_indexes`` listings;
  - ``session.optimize`` only considers tagged entries when they are
    passed explicitly through its ``hypothetical=...`` channel (and
    rejects untagged entries passed there);
  - every scan rewritten onto a tagged entry carries
    ``ScanRelation.hypothetical`` and the executor refuses to run it.

What-if itself never invokes the executor and never writes a file: it
optimizes the query twice (without/with the hypothetical entries), diffs
the plans, and estimates the bytes-scanned delta from recorded file
sizes (`index/statistics.py`'s sizeIndexFiles view for real indexes;
source sizes times covered-column fraction for hypothetical ones).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import (
    HYPOTHETICAL_PROPERTY,
    Content,
    CoveringIndex,
    Directory,
    FileIdTracker,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Signature,
    Source,
    States,
)
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan


def hypothetical_entry(session, dataset_or_plan,
                       config: IndexConfig) -> IndexLogEntry:
    """Synthesize the what-if entry for ``config`` over the (single)
    relation of ``dataset_or_plan`` — zero data files, ACTIVE state,
    tagged hypothetical, real source snapshot + signature so the rules'
    candidate selection treats it exactly like a built index."""
    from hyperspace_tpu.index.signatures import get_provider
    from hyperspace_tpu.utils.resolver import resolve_or_raise

    plan = getattr(dataset_or_plan, "plan", dataset_or_plan)
    leaves = [s for s in plan.leaf_relations()
              if s.relation.index_scan_of is None]
    if not leaves:
        raise HyperspaceError("The plan has no source relation to index")
    if len(leaves) > 1:
        # A join plan: the config belongs to the leaf whose schema
        # resolves EVERY config column (ambiguity is an error — name the
        # relation by passing a single-relation dataset instead).
        wanted = {c.lower() for c in config.indexed_columns
                  + list(config.included_columns)}
        matches = []
        for leaf in leaves:
            try:
                schema = {c.lower() for c in session.schema_of(leaf)}
            except Exception:  # noqa: BLE001 — unreadable leaf: no match
                continue
            if wanted <= schema:
                matches.append(leaf)
        if len(matches) != 1:
            raise HyperspaceError(
                f"Hypothetical index {config.index_name!r} matches "
                f"{len(matches)} of the plan's {len(leaves)} relations; "
                f"build it from a single-relation dataset instead")
        leaves = matches
    relation = session.source_provider_manager.get_relation(leaves[0])
    schema = relation.schema()
    indexed = resolve_or_raise(config.indexed_columns, schema,
                               "indexed column")
    included = resolve_or_raise(config.included_columns, schema,
                                "included column")
    provider_name = session.conf.signature_provider
    # Sign the BARE leaf scan, exactly what create_index over this
    # relation signs (its dataset is a plain read): candidate selection
    # recomputes the signature per leaf scan, so the full query plan's
    # operator chain must not leak into the fingerprint.
    value = get_provider(provider_name).signature(
        leaves[0],
        lambda scan: session.source_provider_manager
        .get_relation(scan).all_files())
    if value is None:
        raise HyperspaceError("Could not compute plan signature")
    rel_meta = relation.create_relation_metadata(FileIdTracker())
    return IndexLogEntry(
        name=config.index_name,
        derived_dataset=CoveringIndex(
            indexed_columns=indexed,
            included_columns=included,
            num_buckets=session.conf.num_buckets,
            schema={c: schema[c] for c in indexed + included},
            properties={"layout": getattr(config, "layout",
                                          "lexicographic")},
        ),
        content=Content(Directory("/")),  # zero files, by construction
        source=Source(relations=[rel_meta],
                      fingerprint=LogicalPlanFingerprint(
                          [Signature(provider_name, value)])),
        properties={HYPOTHETICAL_PROPERTY: "true", "lineage": "false"},
        state=States.ACTIVE,
    )


# ---------------------------------------------------------------------------
# Bytes estimation
# ---------------------------------------------------------------------------
def _scan_estimate(session, scan: Scan,
                   hypo_by_name: Dict[str, IndexLogEntry]
                   ) -> Tuple[str, str, float]:
    """(label, kind, estimated bytes) for one leaf scan."""
    from hyperspace_tpu.io.parquet import bucket_id_of_file

    rel = scan.relation
    name = rel.index_scan_of
    if name is not None and rel.hypothetical:
        entry = hypo_by_name.get(name)
        if entry is None:
            return name, "hypothetical-index", 0.0
        src_bytes = sum(f.size for f in entry.source_file_infos())
        width = len(entry.relations[0].schema) or 1
        frac = len(entry.derived_dataset.all_columns) / width
        est = src_bytes * frac
        if rel.prune_to_buckets is not None and entry.num_buckets:
            est *= len(rel.prune_to_buckets) / entry.num_buckets
        return name, "hypothetical-index", est
    if name is not None:
        entry = session.index_collection_manager.get_index(name)
        size_of = {} if entry is None else \
            {f.name: f.size for f in entry.content.file_infos()}
        paths = list(rel.file_paths or size_of)
        if rel.prune_to_buckets is not None:
            wanted = set(rel.prune_to_buckets)
            paths = [p for p in paths
                     if (b := bucket_id_of_file(p)) is None or b in wanted]
        est = 0.0
        for p in paths:
            sz = size_of.get(p)
            if sz is None:
                try:
                    sz = os.path.getsize(p)
                except OSError:
                    sz = 0
            est += sz
        return name, "index", est
    # Source scan (possibly data-skipping pruned to a file subset).
    label = ",".join(rel.root_paths)
    if rel.file_paths is not None:
        est = 0.0
        for p in rel.file_paths:
            try:
                est += os.path.getsize(p)
            except OSError:
                pass
        return label, "source", est
    try:
        files = session.source_provider_manager.get_relation(scan).all_files()
        return label, "source", float(sum(f.size for f in files))
    except Exception:  # noqa: BLE001 — estimation is advisory
        return label, "source", 0.0


def estimate_plan_bytes(session, plan: LogicalPlan,
                        hypo_by_name: Optional[Dict[str, IndexLogEntry]]
                        = None) -> Tuple[float, List[Dict[str, Any]]]:
    """(total estimated bytes scanned, per-scan detail rows) for a plan —
    the advisor's cost model, shared by what-if and the recommender."""
    hypo_by_name = hypo_by_name or {}
    total = 0.0
    detail: List[Dict[str, Any]] = []
    for scan in plan.leaf_relations():
        label, kind, est = _scan_estimate(session, scan, hypo_by_name)
        total += est
        detail.append({"relation": label, "kind": kind,
                       "est_bytes": round(est, 1)})
    return total, detail


# ---------------------------------------------------------------------------
# The what-if report
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class WhatIfReport:
    """What one ``ds.explain(whatif=[...])`` / ``Hyperspace.whatif``
    pass found: the plan diff and the estimated bytes-scanned delta."""

    hypothetical: List[str]
    hypothetical_used: List[str]
    plan_before: str
    plan_after: str
    est_bytes_before: float
    est_bytes_after: float
    detail_before: List[Dict[str, Any]]
    detail_after: List[Dict[str, Any]]

    @property
    def est_bytes_delta(self) -> float:
        """Positive = the hypothetical indexes would REDUCE bytes read."""
        return self.est_bytes_before - self.est_bytes_after

    def to_dict(self) -> Dict[str, Any]:
        return {
            "hypothetical": list(self.hypothetical),
            "hypothetical_used": list(self.hypothetical_used),
            "est_bytes_before": round(self.est_bytes_before, 1),
            "est_bytes_after": round(self.est_bytes_after, 1),
            "est_bytes_delta": round(self.est_bytes_delta, 1),
            "detail_before": list(self.detail_before),
            "detail_after": list(self.detail_after),
            "plan_before": self.plan_before,
            "plan_after": self.plan_after,
        }

    def render(self) -> str:
        bar = "=" * 64
        lines = [bar, "What-if: hypothetical indexes "
                 + (", ".join(self.hypothetical) or "(none)"), bar]
        lines.append("Plan with hypothetical indexes:")
        lines.extend("  " + ln for ln in self.plan_after.splitlines())
        lines.append("")
        lines.append("Plan without:")
        lines.extend("  " + ln for ln in self.plan_before.splitlines())
        lines.append("")
        lines.append(f"Hypothetical indexes used: "
                     f"{', '.join(self.hypothetical_used) or '(none)'}")
        lines.append(f"Estimated bytes scanned: "
                     f"{self.est_bytes_before:,.0f} -> "
                     f"{self.est_bytes_after:,.0f} "
                     f"(delta {self.est_bytes_delta:,.0f})")
        for row in self.detail_after:
            lines.append(f"  scan [{row['kind']}] {row['relation']}: "
                         f"~{row['est_bytes']:,.0f} bytes")
        return "\n".join(lines)


def whatif(session, dataset_or_plan,
           candidates: Sequence) -> WhatIfReport:
    """Plan ``dataset_or_plan`` as if ``candidates`` (IndexConfig specs
    or pre-built hypothetical entries) were built.  Pure planning: the
    executor is never invoked and no file is written — the plan diff and
    an estimated bytes-scanned delta come back as a report."""
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.trace import span

    plan = getattr(dataset_or_plan, "plan", dataset_or_plan)
    entries: List[IndexLogEntry] = []
    for c in candidates:
        if isinstance(c, IndexLogEntry):
            if not c.is_hypothetical:
                raise HyperspaceError(
                    f"whatif() takes hypothetical entries only; "
                    f"{c.name!r} is not tagged")
            entries.append(c)
        elif isinstance(c, IndexConfig):
            entries.append(hypothetical_entry(session, plan, c))
        else:
            raise HyperspaceError(
                f"whatif() candidates are IndexConfig or hypothetical "
                f"IndexLogEntry, got {type(c).__name__}")
    hypo_by_name = {e.name: e for e in entries}

    with span("advisor.whatif", candidates=len(entries)):
        metrics.inc("advisor.whatif.runs")
        was_enabled = session.is_hyperspace_enabled()
        try:
            session.enable_hyperspace()
            plan_before = session.optimize(plan)
            plan_after = session.optimize(plan, hypothetical=entries)
        finally:
            if not was_enabled:
                session.disable_hyperspace()
        before_total, before_detail = estimate_plan_bytes(
            session, plan_before)
        after_total, after_detail = estimate_plan_bytes(
            session, plan_after, hypo_by_name)
        used = sorted({s.relation.index_scan_of
                       for s in plan_after.leaf_relations()
                       if s.relation.hypothetical
                       and s.relation.index_scan_of})
        return WhatIfReport(
            hypothetical=sorted(hypo_by_name),
            hypothetical_used=used,
            plan_before=plan_before.tree_string(),
            plan_after=plan_after.tree_string(),
            est_bytes_before=before_total,
            est_bytes_after=after_total,
            detail_before=before_detail,
            detail_after=after_detail,
        )
