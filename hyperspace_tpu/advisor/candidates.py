"""Candidate enumeration + scoring over the captured workload.

Candidates are covering indexes shaped the way the rewrite rules want
them: *indexed* = the columns the workload's filters pin / joins key on;
*included* = the columns those same queries project, so the rewritten
scan never has to touch the source.  Scoring is bytes-based (the unit
both the capture and the what-if estimator already speak):

  benefit(candidate)  = Σ over supporting fingerprints
                          hits × max(0, measured_bytes − est_index_bytes)
  est_index_bytes     = relation_bytes × covered-column fraction
                          × (1/numBuckets when the query pins every
                             indexed column by equality, else 1)
  build_cost          = relation_bytes × covered-column fraction
                          (≈ rows × covered columns × bytes/value —
                           one full read+write pass over those columns)
  score               = benefit − build_cost

The model is deliberately coarse — the acceptance contract is that the
SIGN and ordering agree with measurement (docs/17-advisor.md documents a
16x band), and the what-if pass exists for anyone who wants the real
optimizer's answer on a specific candidate before building.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Tuple

from hyperspace_tpu.plan.nodes import Scan, ScanRelation


@dataclasses.dataclass
class Candidate:
    """One scored candidate covering index."""

    name: str
    roots: Tuple[str, ...]
    file_format: str
    options: Tuple[Tuple[str, str], ...]
    indexed: List[str]
    included: List[str]
    supporting_keys: List[str] = dataclasses.field(default_factory=list)
    supporting_hits: int = 0
    est_benefit_bytes: float = 0.0
    est_build_cost_bytes: float = 0.0

    @property
    def score(self) -> float:
        return self.est_benefit_bytes - self.est_build_cost_bytes

    def source_scan(self) -> Scan:
        return Scan(ScanRelation(root_paths=tuple(self.roots),
                                 file_format=self.file_format,
                                 options=tuple(self.options)))


def _sanitize(name: str) -> str:
    out = re.sub(r"[^a-z0-9_]+", "_", name.lower()).strip("_")
    return (out or "idx")[:64]


def _candidate_name(roots: Tuple[str, ...], indexed: List[str]) -> str:
    import os

    base = os.path.basename(roots[0].rstrip("/")) if roots else "rel"
    return _sanitize(f"adv_{base}_{'_'.join(indexed)}")


def generate_candidates(records: List[Dict[str, Any]],
                        max_candidates: int) -> List[Candidate]:
    """Enumerate candidates from workload records (workload.records):
    one per hot filter column and one per join-key set, per relation,
    deduplicated by (relation, indexed columns) with included-column
    union — capped at ``max_candidates`` by supporting hit weight."""
    by_key: Dict[Tuple, Candidate] = {}
    for rec in records:
        hits = int(rec.get("hits", 0)) or 1
        for t in rec.get("tables", []):
            roots = tuple(t.get("roots", []))
            fmt = t.get("format", "parquet")
            options = tuple(tuple(kv) for kv in t.get("options", []))
            projected = list(t.get("projected", []))
            groups: List[List[str]] = []
            for col in t.get("eq", []) + t.get("range", []):
                groups.append([col])
            if t.get("join"):
                groups.append(sorted(t["join"]))
            for indexed in groups:
                key = (roots, fmt, tuple(c.lower() for c in indexed))
                cand = by_key.get(key)
                if cand is None:
                    cand = Candidate(
                        name=_candidate_name(roots, indexed),
                        roots=roots, file_format=fmt, options=options,
                        indexed=list(indexed), included=[])
                    by_key[key] = cand
                lowered = {c.lower() for c in cand.indexed}
                for c in projected:
                    if c.lower() not in lowered and \
                            c not in cand.included:
                        cand.included.append(c)
                cand.included.sort()
                cand.supporting_hits += hits
                if rec.get("key") and rec["key"] not in cand.supporting_keys:
                    cand.supporting_keys.append(rec["key"])
    ranked = sorted(by_key.values(),
                    key=lambda c: (-c.supporting_hits, c.name))
    return ranked[:max(0, int(max_candidates))]


def _relation_stats(session, cand: Candidate,
                    records: List[Dict[str, Any]]) -> Tuple[float, int]:
    """(total source bytes, schema width) for the candidate's relation —
    from the live listing when readable, else the largest measured
    source-bytes figure the workload recorded for it."""
    try:
        rel = session.source_provider_manager.get_relation(
            cand.source_scan())
        files = rel.all_files()
        width = len(rel.schema()) or 1
        return float(sum(f.size for f in files)), width
    except Exception:  # noqa: BLE001 — scoring is advisory
        best = 0.0
        roots_key = ",".join(cand.roots)
        for rec in records:
            tb = rec.get("last_table_bytes") or {}
            best = max(best, float(tb.get(roots_key, 0)),
                       float(rec.get("last_source_bytes", 0)))
        width = max(1, len(cand.indexed) + len(cand.included))
        return best, width


def score_candidates(session, candidates: List[Candidate],
                     records: List[Dict[str, Any]]) -> List[Candidate]:
    """Fill in benefit/build-cost estimates (docstring model) and return
    the list sorted by score (desc), ties by name."""
    from hyperspace_tpu.telemetry import metrics

    by_rec_key = {rec.get("key"): rec for rec in records}
    num_buckets = max(1, int(session.conf.num_buckets))
    for cand in candidates:
        rel_bytes, width = _relation_stats(session, cand, records)
        frac = min(1.0, (len(cand.indexed) + len(cand.included))
                   / max(1, width))
        cand.est_build_cost_bytes = rel_bytes * frac
        benefit = 0.0
        roots_key = ",".join(cand.roots)
        indexed_lower = {c.lower() for c in cand.indexed}
        for key in cand.supporting_keys:
            rec = by_rec_key.get(key)
            if rec is None:
                continue
            hits = int(rec.get("hits", 0)) or 1
            measured = 0.0
            eq_pinned = False
            for t in rec.get("tables", []):
                if tuple(t.get("roots", [])) != cand.roots:
                    continue
                eq_pinned = indexed_lower <= {c.lower()
                                              for c in t.get("eq", [])}
            tb = rec.get("last_table_bytes") or {}
            measured = float(tb.get(roots_key,
                                    rec.get("last_source_bytes", 0)))
            est_scan = rel_bytes * frac
            if eq_pinned:
                est_scan /= num_buckets
            benefit += hits * max(0.0, measured - est_scan)
        cand.est_benefit_bytes = benefit
        metrics.inc("advisor.candidates_scored")
    return sorted(candidates, key=lambda c: (-c.score, c.name))
