"""Workload-aware index advisor: capture → what-if → recommend → build.

The loop the Hyperspace paper names as the next step after transparent
index *use*: decide which indexes are worth *building* (the AutoAdmin
what-if / index-selection direction, Chaudhuri & Narasayya VLDB '97).

  - :mod:`~hyperspace_tpu.advisor.workload` — opt-in capture of a
    bounded, deduplicated log of query fingerprints (filter/join/group
    columns, measured bytes scanned — never data values), persisted
    through the LogStore seam so it works over Posix and the emulated
    object store and survives restarts.
  - :mod:`~hyperspace_tpu.advisor.hypothetical` — synthesize
    ACTIVE-looking, zero-data-file index entries and plan queries
    against them (``session.optimize(hypothetical=[...])``,
    ``ds.explain(whatif=[...])``); the executor refuses such plans, the
    log refuses such entries, and nothing touches disk.
  - :mod:`~hyperspace_tpu.advisor.candidates` /
    :mod:`~hyperspace_tpu.advisor.recommend` — enumerate candidate
    covering indexes from the captured workload and rank them by
    workload-weighted estimated benefit minus estimated build cost
    (``Hyperspace.recommend_indexes`` / ``apply_recommendations``).

docs/17-advisor.md is the walkthrough.
"""

from hyperspace_tpu.advisor.hypothetical import (
    WhatIfReport,
    hypothetical_entry,
    whatif,
)
from hyperspace_tpu.advisor.recommend import (
    apply_recommendations,
    recommend_indexes,
)
from hyperspace_tpu.advisor.workload import capture, workload_table

__all__ = [
    "WhatIfReport",
    "hypothetical_entry",
    "whatif",
    "recommend_indexes",
    "apply_recommendations",
    "capture",
    "workload_table",
]
