"""Ranked recommendations + one-call apply.

``Hyperspace.recommend_indexes(top_k)`` delegates here: read the captured
workload (pending counters flushed), enumerate candidates, score them
(advisor/candidates.py's bytes model), and return an arrow table — one
row per candidate with its supporting-query weight and benefit/cost
estimates.  ``apply_recommendations(top_k)`` builds the winners through
the NORMAL CreateAction path (same validation, same log protocol, same
bucketed build as a hand-written ``create_index``), skipping candidates
an existing ACTIVE index already covers.
"""

from __future__ import annotations

from typing import List, Optional

from hyperspace_tpu.advisor import candidates as _cand
from hyperspace_tpu.advisor import workload as _workload
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import States


def scored_candidates(session) -> List[_cand.Candidate]:
    # Persist pending write-behind counters before scoring: records()
    # overlays them in memory either way, but a recommendation is a
    # natural durability point (the verb path reads WITHOUT flushing —
    # blocking-discipline keeps store writes off the inline surface).
    _workload.flush_pending(session.conf)
    recs = _workload.records(session.conf)
    cands = _cand.generate_candidates(
        recs, session.conf.advisor_max_candidates)
    return _cand.score_candidates(session, cands, recs)


def recommend_indexes(session, top_k: int = 5):
    """The ranked recommendation table (see Hyperspace.recommend_indexes
    for the user-facing contract)."""
    import pyarrow as pa

    from hyperspace_tpu.telemetry.trace import span

    with span("advisor.recommend", top_k=top_k):
        ranked = scored_candidates(session)[:max(0, int(top_k))]
    return pa.table({
        "candidate": [c.name for c in ranked],
        "relation": [",".join(c.roots) for c in ranked],
        "indexedColumns": [list(c.indexed) for c in ranked],
        "includedColumns": [list(c.included) for c in ranked],
        "supportingQueries": [len(c.supporting_keys) for c in ranked],
        "supportingHits": [c.supporting_hits for c in ranked],
        "estBenefitBytes": [round(c.est_benefit_bytes, 1) for c in ranked],
        "estBuildCostBytes": [round(c.est_build_cost_bytes, 1)
                              for c in ranked],
        "score": [round(c.score, 1) for c in ranked],
    })


def _already_covered(session, cand: _cand.Candidate) -> bool:
    """An ACTIVE covering index with the same indexed columns over the
    same relation that covers the candidate's included set makes building
    the candidate pointless."""
    try:
        entries = session.index_collection_manager.get_indexes(
            [States.ACTIVE])
    except Exception:  # noqa: BLE001 — a degraded listing must not stop
        return False   # the build; CreateAction re-validates anyway.
    want_indexed = [c.lower() for c in cand.indexed]
    want_cols = {c.lower() for c in cand.indexed + cand.included}
    roots = set(cand.roots)
    for e in entries:
        if not e.is_covering:
            continue
        if sorted(c.lower() for c in e.indexed_columns) \
                != sorted(want_indexed):
            continue
        if not want_cols <= {c.lower()
                             for c in e.derived_dataset.all_columns}:
            continue
        entry_roots = {r for rel in e.relations for r in rel.root_paths}
        if roots <= entry_roots:
            return True
    return False


def _unique_name(session, base: str) -> str:
    mgr = session.index_collection_manager
    name, n = base, 1
    while True:
        try:
            taken = mgr.get_index(name) is not None
        except Exception:  # noqa: BLE001 — unreadable log: the name is
            taken = True   # occupied by SOMETHING; move on
        if not taken:
            return name
        n += 1
        name = f"{base}_{n}"


def apply_recommendations(session, top_k: int = 1,
                          min_score: Optional[float] = None) -> List[str]:
    """Build the top ``top_k`` recommended indexes through the normal
    CreateAction path; returns the names built.  ``min_score`` (bytes)
    skips candidates below it; by default every requested winner builds —
    the operator asked for them."""
    from hyperspace_tpu.dataset import Dataset
    from hyperspace_tpu.telemetry.trace import span

    built: List[str] = []
    with span("advisor.apply", top_k=top_k):
        for cand in scored_candidates(session)[:max(0, int(top_k))]:
            if min_score is not None and cand.score < min_score:
                continue
            if _already_covered(session, cand):
                continue
            name = _unique_name(session, cand.name)
            ds = Dataset(cand.source_scan(), session)
            session.index_collection_manager.create(
                ds, IndexConfig(name, cand.indexed, cand.included))
            built.append(name)
    return built
