"""CancelAction: recover a stuck index from a transient state back to its
last stable state.

Reference contract: actions/CancelAction.scala:35-76 — validate requires the
latest entry to be in a *transient* (non-stable) state; the final state is
the last stable log's state, with the special case VACUUMING → DOESNOTEXIST
(:44-53).  Cancel writes no transient entry of its own: begin() is a no-op
and end() commits directly at base_id + 1.
"""

from __future__ import annotations

import copy

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.log_entry import States
from hyperspace_tpu.telemetry.events import CancelActionEvent


class CancelAction(Action):
    event_class = CancelActionEvent

    def validate(self) -> None:
        if self.previous_log_entry is None:
            raise HyperspaceError("Cancel: index does not exist")
        if self.previous_log_entry.state in States.STABLE:
            raise HyperspaceError(
                f"Cancel is not supported in stable state {self.previous_log_entry.state}")

    @property
    def final_state(self) -> str:  # type: ignore[override]
        # CancelAction.scala:44-53
        if self.previous_log_entry.state == States.VACUUMING:
            return States.DOESNOTEXIST
        stable = self.log_manager.get_latest_stable_log()
        return stable.state if stable is not None else States.DOESNOTEXIST

    def op(self) -> None:
        pass

    def begin(self) -> None:
        pass

    def end(self) -> None:
        stable = self.log_manager.get_latest_stable_log()
        entry = copy.deepcopy(stable if stable is not None else self.previous_log_entry)
        entry.state = self.final_state
        self.log_manager.delete_latest_stable_log()
        self.log_manager.write_log_or_raise(self.base_id + 1, entry)
        self.log_manager.create_latest_stable_log(self.base_id + 1)
