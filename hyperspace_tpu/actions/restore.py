"""RestoreAction: undo a soft delete, DELETED → ACTIVE.

Reference contract: actions/RestoreAction.scala:24-48 — validate requires
DELETED; ``op()`` is a no-op; final entry is the previous one re-activated.
"""

from __future__ import annotations

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.telemetry.events import RestoreActionEvent


class RestoreAction(Action):
    transient_state = States.RESTORING
    final_state = States.ACTIVE
    event_class = RestoreActionEvent

    def validate(self) -> None:
        if self.previous_log_entry is None or self.previous_log_entry.state != States.DELETED:
            raise HyperspaceError(
                f"Restore is only supported in {States.DELETED} state; index is "
                f"{'missing' if self.previous_log_entry is None else self.previous_log_entry.state}")

    def op(self) -> None:
        pass

    def log_entry(self) -> IndexLogEntry:
        return self.log_entry_for_begin()
