"""VacuumAction: hard delete, DELETED → DOESNOTEXIST, physically removing
every index data version.

Reference contract: actions/VacuumAction.scala:24-65 — validate requires
DELETED; ``op()`` deletes version directories newest → 0 (:46-52).
"""

from __future__ import annotations

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.telemetry.events import VacuumActionEvent


class VacuumAction(Action):
    transient_state = States.VACUUMING
    final_state = States.DOESNOTEXIST
    event_class = VacuumActionEvent

    def __init__(self, log_manager: IndexLogManager, data_manager: IndexDataManager) -> None:
        super().__init__(log_manager)
        self.data_manager = data_manager

    def validate(self) -> None:
        if self.previous_log_entry is None or self.previous_log_entry.state != States.DELETED:
            raise HyperspaceError(
                f"Vacuum is only supported in {States.DELETED} state; index is "
                f"{'missing' if self.previous_log_entry is None else self.previous_log_entry.state}")

    def op(self) -> None:
        for version in reversed(self.data_manager.versions()):
            self.data_manager.delete(version)
        # Each delete() dropped its version's quarantine records; sweep
        # whatever remains (records that never mapped to a version dir)
        # so a vacuumed index leaves zero orphaned quarantine keys.
        if getattr(self.data_manager, "quarantine", None) is not None:
            self.data_manager.quarantine.clear()

    def log_entry(self) -> IndexLogEntry:
        return self.log_entry_for_begin()
