"""CreateAction: validate config/plan, build the covering index on device,
commit the log entry.

Reference contract: actions/CreateAction.scala:30-90 (validate :45-66 —
supported relation, resolvable columns, free name) and
actions/CreateActionBase.scala:56-222 —
  - ``write``: select columns → repartition(numBuckets, indexedCols) →
    saveWithBuckets (:124-142).  Here that whole pipeline is the fused TPU
    kernel ``bucket_sort_permutation`` (hash + lexsort on device) plus a
    host-side bucketed Parquet writer — no cluster shuffle exists because
    the permutation materializes the shuffle's effect directly.
  - lineage (:177-222): the reference joins ``input_file_name()`` against a
    broadcast file→id map; we attach ``_data_file_id`` per file at read
    time — same result, no join needed, because the engine owns the reader.
  - ``getIndexLogEntry`` (:56-105): signature of the source plan, content
    tree of the written files, provider-enriched properties.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import (
    Content,
    CoveringIndex,
    FileIdTracker,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Signature,
    Source,
    States,
)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.index.signatures import get_provider
from hyperspace_tpu.io import columnar
from hyperspace_tpu.io.parquet import read_table, write_bucketed
from hyperspace_tpu.plan.nodes import LogicalPlan
from hyperspace_tpu.telemetry.events import CreateActionEvent
from hyperspace_tpu.utils.resolver import resolve_or_raise

DATA_FILE_ID_COLUMN = "_data_file_id"  # IndexConstants.scala lineage column

# Spill temp-dir prefixes (hash spill / zorder two-pass).  Dirs are
# pid-stamped so a later build can prove an orphan's owner is dead before
# reaping it — a SIGKILLed build runs no cleanup handler, and these dirs
# hold a routed copy of the whole source.
_SPILL_DIR_KINDS = ("hs_build_spill_", "hs_zbuild_")


def _spill_dir_prefix(kind: str) -> str:
    return f"{kind}{os.getpid()}_"


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        pass  # EPERM etc.: the pid exists, someone else owns it
    return True


def reap_orphan_spill_dirs(tmp_root: Optional[str] = None) -> int:
    """Best-effort removal of spill dirs leaked by DEAD processes
    (SIGKILL or an injected crash mid-build), run at build start.  Only
    pid-stamped dirs whose owning pid provably no longer exists are
    touched; deletion goes through ``io/files.remove_tree`` so the
    ``io.delete`` fault site applies.  Returns the number reaped."""
    import tempfile

    from hyperspace_tpu.io.files import remove_tree

    root = tmp_root or tempfile.gettempdir()
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    reaped = 0
    for name in names:
        kind = next((k for k in _SPILL_DIR_KINDS if name.startswith(k)),
                    None)
        if kind is None:
            continue
        pid_part = name[len(kind):].split("_", 1)[0]
        if not pid_part.isdigit():
            continue  # pre-pid-stamp dir: ownership unprovable, leave it
        pid = int(pid_part)
        if pid == os.getpid() or _pid_alive(pid):
            continue
        try:
            remove_tree(os.path.join(root, name), ignore_errors=True)
            reaped += 1
        except OSError:
            pass  # best-effort: a flaky mount must not fail THIS build
    return reaped


class _PrefetchReader:
    """Bounded decode-ahead over a source file list.

    ONE reader thread decodes file N+1 while the consumer routes file N
    (double-buffered at ``depth=2``, the conf default); ``depth`` bounds
    decoded-but-unconsumed chunks — the backpressure that keeps peak RSS
    at ~depth device batches instead of the dataset.  ``depth=0`` reads
    inline on the consumer thread: the forced-serial reference path
    (``hyperspace.index.build.pipeline.enabled=false``) and the
    no-thread degrade.  Deadline-aware (each handoff re-checks the
    request deadline) and drain-aware: ``close()`` cancels queued decode
    work and joins the reader, so a failed build never races its own
    prefetcher — the action's cleanup ``finally`` covers it."""

    def __init__(self, action: "CreateActionBase", files, columns,
                 relation, lineage, depth: int, spill=None) -> None:
        self.action = action
        self.files = list(files)
        self.columns = columns
        self.relation = relation
        self.lineage = lineage
        self.depth = max(0, int(depth))
        self.spill = spill
        self.peak_chunks = 0  # max decoded-unconsumed chunks observed
        self._stall_buffer_s = 0.0
        self._pool = None
        self._pending: List = []

    def _record_stall(self, seconds: float) -> None:
        """Attribute consumer stall (the ``prefetch`` phase/lane) — but
        only once the build is known to SPILL.  On a monolithic build
        the consumer has nothing to overlap, so its wait and the reader
        thread's ``read`` cover the same wall time; counting both would
        break the phase-sum-within-10%-of-wall audit.  Pre-spill stalls
        buffer and flush with the first post-spill one."""
        if self.spill is None or not self.spill.spilled:
            self._stall_buffer_s += seconds
            return
        self.action._phase("prefetch_s", self._stall_buffer_s + seconds)
        self._stall_buffer_s = 0.0

    def __iter__(self):
        import time as _time

        from hyperspace_tpu.utils import deadline

        if self.depth == 0:
            for f in self.files:
                deadline.check()
                yield self.action._read_chunk(f, self.columns,
                                              self.relation, self.lineage)
            return
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="hs-prefetch")
        queue = list(self.files)
        try:
            while queue and len(self._pending) < self.depth:
                self._pending.append(self._pool.submit(
                    self.action._read_chunk, queue.pop(0), self.columns,
                    self.relation, self.lineage))
            while self._pending:
                deadline.check()
                ready = sum(1 for f in self._pending if f.done())
                if ready > self.peak_chunks:
                    self.peak_chunks = ready
                fut = self._pending.pop(0)
                # Stall attribution: time the CONSUMER spends waiting on
                # decode is the pipeline bubble prefetch exists to close
                # (the ``prefetch`` phase/lane; near zero when it wins).
                t0 = _time.perf_counter()
                t = fut.result()
                self._record_stall(_time.perf_counter() - t0)
                if queue:
                    self._pending.append(self._pool.submit(
                        self.action._read_chunk, queue.pop(0),
                        self.columns, self.relation, self.lineage))
                yield t
            # A build that spilled only late in the stream still owns
            # its earlier (buffered) stalls.
            if self.spill is not None and self.spill.spilled \
                    and self._stall_buffer_s:
                self._record_stall(0.0)
        finally:
            self.close()

    def close(self) -> None:
        futures, self._pending = self._pending, []
        for fut in futures:
            fut.cancel()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None


class CreateActionBase(Action):
    """Shared by Create and the data-rebuilding Refresh actions."""

    def __init__(self, log_manager: IndexLogManager, data_manager: IndexDataManager,
                 session, plan: LogicalPlan, config: IndexConfig) -> None:
        super().__init__(log_manager)
        self.data_manager = data_manager
        self.session = session
        self.plan = plan
        self.config = config
        self._written_version: Optional[int] = None
        self._file_id_tracker = FileIdTracker()
        self._relation_cache = None
        # Per-phase wall-clock of this build (read / kernel / write /
        # sketch, seconds) — appended to session.build_stats_log on
        # completion so bench.py can attribute build time (the round-2
        # regression was unattributable without this).  Concurrent spill
        # route workers update it, hence the lock (note: summed seconds
        # are CPU-attributed time and can exceed wall-clock once routing
        # overlaps reads).
        self.build_phases: Dict[str, float] = {}
        self._phase_lock = threading.Lock()

    def _phase(self, name: str, seconds: float) -> None:
        with self._phase_lock:
            self.build_phases[name] = \
                self.build_phases.get(name, 0.0) + seconds
        # The structured report keeps the same numbers under bare phase
        # names (telemetry/build_report.py; locked internally).
        self.build_report.add_phase(name, seconds)

    def _publish_build_stats(self) -> None:
        log = getattr(self.session, "build_stats_log", None)
        if log is None:
            log = []
            self.session.build_stats_log = log
        log.append({"index": self.index_name,
                    **{k: round(v, 4) for k, v in self.build_phases.items()}})

    @property
    def conf(self) -> HyperspaceConf:
        return self.session.conf

    @property
    def index_name(self) -> str:
        return self.config.index_name

    @property
    def num_buckets(self) -> int:
        # Z-order clusters via Morton-ordered file cuts, and hash
        # bucketing would scatter that clustering across buckets (a file
        # per bucket sees near-uniform value ranges on every dimension —
        # no pruning).  One bucket makes the whole index a single Z-curve
        # run; file granularity comes from index_max_rows_per_file.
        if getattr(self.config, "layout", None) == "zorder":
            return 1
        return self.conf.num_buckets

    @property
    def lineage_enabled(self) -> bool:
        # Refresh actions override both properties to pin the previous
        # entry's values (RefreshActionBase.scala:56-64).
        return self.conf.lineage_enabled

    def _relation(self):
        # Cached for the action's lifetime: the plan is fixed, and the
        # relation's file listing must not be re-walked per accessor call.
        if self._relation_cache is None:
            leaves = self.plan.leaf_relations()
            if len(leaves) != 1:
                # CreateAction.scala:52-58: exactly one supported relation.
                raise HyperspaceError(
                    f"Only plans over exactly one relation are supported for "
                    f"indexing; found {len(leaves)}")
            self._relation_cache = \
                self.session.source_provider_manager.get_relation(leaves[0])
        return self._relation_cache

    def _resolved_config(self) -> IndexConfig:
        """Resolve config columns against the relation schema
        (CreateActionBase.resolveConfig:155-175)."""
        schema = self._relation().schema()
        indexed = resolve_or_raise(self.config.indexed_columns, schema, "indexed column")
        included = resolve_or_raise(self.config.included_columns, schema, "included column")
        return IndexConfig(self.config.index_name, indexed, included,
                           layout=getattr(self.config, "layout", "lexicographic"))

    # -- the build (CreateActionBase.write:124-142, TPU-style) --------------
    def _build_index_data(self, file_names: Optional[List[str]] = None) -> None:
        """Read source columns, run the fused hash+sort kernel, write one
        sorted Parquet file per bucket into the next ``v__=N`` directory.

        Datasets bigger than one device batch take the EXTERNAL build
        (SURVEY §7's "sort at SF100 exceeds HBM" hard part): source files
        stream through the hash kernel one batch at a time, rows spill into
        per-bucket run files, and each bucket is then sorted independently —
        peak memory is bounded by max(batch, largest bucket), not the
        dataset."""
        import time as _time

        from hyperspace_tpu.io import integrity

        # Build planning: conf application, source listing, column
        # resolution, and the backend probe (_use_distributed_build's
        # first jax.devices() call initializes the backend — a one-off
        # cost that must not hide between phases).
        _t0 = _time.perf_counter()
        # Spill dirs a SIGKILLed prior process leaked are reaped here —
        # the one moment a build provably needs the temp space back.
        reap_orphan_spill_dirs()
        # Digest-on-write follows THIS session's conf (the recorder is
        # process-global, like the fault injector).
        integrity.configure_from_conf(self.conf)
        relation = self._relation()
        resolved = self._resolved_config()
        lineage = self.lineage_enabled
        files = relation.all_files(self._file_id_tracker)
        if file_names is not None:
            wanted = set(file_names)
            files = [f for f in files if f.name in wanted]
        if not files:
            raise HyperspaceError("No source data files to index")

        columns = resolved.all_columns
        batch_rows = max(1, int(self.conf.device_batch_rows))
        # Datasets beyond one batch stream through the spill builder —
        # whose per-chunk route shards over the mesh when one is active
        # (bounded memory AND horizontal scale; parallel/sharded_build).
        # Only an EXPLICIT parallel_build="on" keeps the legacy
        # monolithic all_to_all build, which holds the whole dataset in
        # memory (bit-equal either way — layout never depends on the
        # route).
        streaming = not (
            str(self.conf.parallel_build).lower() in ("on", "true")
            and self._use_distributed_build())
        self._phase("plan_s", _time.perf_counter() - _t0)
        from hyperspace_tpu.parallel import multihost_build
        if multihost_build.armed(self.conf):
            # Fault-tolerant multi-host build: N subprocess hosts route
            # and finalize under crash-recoverable work claims; this
            # action coordinates, validates the staged union, and keeps
            # the ordinary base_id+2 commit as the single transaction.
            multihost_build.run_multihost_build(
                self, files, columns, relation, resolved, lineage,
                batch_rows)
            self._publish_build_stats()
            return
        if streaming and resolved.layout == "zorder":
            # Z-order builds beyond one batch take a dedicated two-pass
            # path that preserves the GLOBAL layout (hash-partition
            # spilling would fragment the curve into partition-local
            # samples and gut second-dimension pruning).
            self._zorder_streaming_build(files, columns, relation, lineage,
                                         resolved, batch_rows)
            self._publish_build_stats()
            return
        spill = _BucketSpill(self, resolved)
        try:
            self._stream_build(files, columns, relation, lineage, resolved,
                               batch_rows, streaming, spill)
            self._publish_build_stats()
        finally:
            # A FINALLY, not an except: it must join + shut down the
            # route/finalize worker pools and remove the spill dir on
            # every exit — InjectedCrash (a BaseException) included,
            # since a leaked pool thread would outlive the simulated
            # kill.  After a clean finish() this is a no-op.  Only a
            # real SIGKILL escapes it, which is what the orphan reap
            # above exists for.
            spill.cleanup()

    def _read_chunk(self, f, columns, relation, lineage) -> pa.Table:
        """One source file's rows with schema-evolution normalization (a
        file predating an added column yields nulls of the relation's
        type, like the monolithic concat's promotion) and, when enabled,
        the constant-per-file lineage column
        (CreateActionBase.scala:177-222 without the broadcast join)."""
        import time as _time

        t0 = _time.perf_counter()
        t = read_table([f.name], relation.read_format, columns,
                       relation.options,
                       partition_roots=relation.root_paths)
        self._phase("read_s", _time.perf_counter() - t0)
        self.build_report.add_bytes(read=t.nbytes)
        missing = [col_name for col_name in columns
                   if col_name not in t.column_names]
        if missing:
            from hyperspace_tpu.io.parquet import _dtype_from_string

            rel_schema = relation.schema()
            for col_name in missing:
                t = t.append_column(col_name, pa.nulls(
                    t.num_rows,
                    type=_dtype_from_string(
                        rel_schema.get(col_name, "string"))))
        if lineage:
            fid = np.full(t.num_rows, f.id, dtype=np.int64)
            t = t.append_column(DATA_FILE_ID_COLUMN, pa.array(fid))
        return t

    def _stream_build(self, files, columns, relation, lineage, resolved,
                      batch_rows, streaming, spill) -> None:
        # The overlapped build pipeline: source decode is prefetched
        # ahead on the reader thread (bounded by
        # hyperspace.index.build.prefetchDepth — the backpressure that
        # keeps peak RSS at ~depth device batches), chunk ROUTING runs
        # on the spill's worker pool when cores allow, and closed bucket
        # groups finalize on their own pool while the tail of the input
        # still routes.  pipeline.enabled=false degrades to the
        # bit-equal forced-serial loop: inline reads, inline routing,
        # sequential finalize (layout NEVER depends on the flag — the
        # pipeline changes scheduling only).
        depth = max(1, int(self.conf.build_prefetch_depth)) \
            if spill.pipelined else 0
        reader = _PrefetchReader(self, files, columns, relation, lineage,
                                 depth, spill=spill)
        buffer: List[pa.Table] = []
        buffered = 0
        try:
            for t in reader:
                buffer.append(t)
                buffered += t.num_rows
                while streaming and buffered > batch_rows:
                    combined = pa.concat_tables(buffer,
                                                promote_options="default")
                    spill.add_chunk(combined.slice(0, batch_rows))
                    rest = combined.slice(batch_rows)
                    buffer = [rest] if rest.num_rows else []
                    buffered = rest.num_rows
        finally:
            reader.close()
        if depth:
            self.build_report.properties.update(
                prefetch_depth=depth,
                prefetch_peak_chunks=reader.peak_chunks)
        remainder = pa.concat_tables(buffer, promote_options="default") \
            if buffer else None
        if not spill.spilled:
            # Everything fit in one batch (or the mesh owns the sharding):
            # the fused monolithic/distributed kernel.
            self._write_table_bucketed(remainder, resolved)
            return
        if remainder is not None and remainder.num_rows:
            spill.add_chunk(remainder)
        spill.finish()

    def _zorder_streaming_build(self, files, columns, relation, lineage,
                                resolved, batch_rows) -> None:
        """Two-pass Z-order build for datasets beyond one device batch,
        producing EXACTLY the monolithic layout:

          A. stream only the INDEXED columns (column-pruned reads).
             Value-mapped types (numeric/temporal/bool — their order words
             are chunk-independent) convert to fixed-width words
             immediately (8 B/row/column); rank-mapped types
             (strings/binary/decimal) must keep the raw column until one
             GLOBAL rank pass — a chunk-local dense rank would not be
             comparable across chunks and the curve would silently
             interleave.  Then compute global Morton
             codes, argsort, and the Z-cell-aligned output-file
             assignment per row;
          B. stream the full rows again, routing each chunk's rows to
             per-output-file run files (codes ride along as a temp
             column); then per output file: concat runs in chunk order,
             stable-sort by code (ties keep original row order, same as
             the monolithic argsort), and write.

        The previous hash-partition spill bounded memory the same way but
        fragmented the curve into partition-local rank samples — per-file
        min/max spanned whole dimensions and second-dimension pruning
        collapsed at scale (measured 50/108 files kept at SF1 for a 5%
        range vs ~1/8 expected)."""
        import tempfile
        import time as _time

        import pyarrow.parquet as pq

        from hyperspace_tpu.io import columnar as _columnar
        from hyperspace_tpu.io.files import remove_tree
        from hyperspace_tpu.io.parquet import (
            write_bucket_run,
            zorder_codes_from_order_words,
            zorder_split_chunks,
        )

        key_cols = list(resolved.indexed_columns)

        def build_monolithic() -> None:
            table = pa.concat_tables(
                [self._read_chunk(f, columns, relation, lineage)
                 for f in files], promote_options="default")
            self._write_table_bucketed(table, resolved)

        # Small datasets skip the two-pass machinery entirely when footers
        # can prove the total fits one batch (parquet only; other formats
        # fall through and pay one extra key-column read).
        footer_n = _footer_row_count(files, relation)
        if footer_n is not None and footer_n <= batch_rows:
            build_monolithic()
            return
        # -- pass A: global codes from the indexed columns only ------------
        word_parts: List[List] = [[] for _ in key_cols]
        value_mapped: List[Optional[bool]] = [None] * len(key_cols)
        n = 0
        for f in files:
            kt = self._read_chunk(f, key_cols, relation, lineage=False)
            n += kt.num_rows
            for i, c in enumerate(key_cols):
                arr = kt.column(c)
                if value_mapped[i] is None:
                    value_mapped[i] = columnar.is_numeric_type(
                        kt.schema.field(c).type)
                if value_mapped[i]:
                    word_parts[i].append(
                        np.asarray(_columnar.to_order_words(arr)))
                else:
                    # Rank-mapped type: keep the raw chunks for ONE global
                    # rank pass below.
                    word_parts[i].extend(arr.chunks)
        if n <= batch_rows:
            # Non-parquet source that turned out small: monolithic writer
            # (identical layout, no run files).
            build_monolithic()
            return
        t0 = _time.perf_counter()
        per_col_words = []
        for i in range(len(key_cols)):
            if value_mapped[i]:
                per_col_words.append(np.concatenate(word_parts[i], axis=0))
            else:
                per_col_words.append(np.asarray(_columnar.to_order_words(
                    pa.chunked_array(word_parts[i]))))
        codes, bits = zorder_codes_from_order_words(per_col_words)
        del word_parts, per_col_words
        order = np.argsort(codes, kind="stable")
        chunks = zorder_split_chunks(codes[order], bits,
                                     self.conf.index_max_rows_per_file)
        file_of_sorted = np.empty(n, np.int32)
        for i, (off, rows) in enumerate(chunks):
            file_of_sorted[off:off + rows] = i
        file_of_row = np.empty(n, np.int32)
        file_of_row[order] = file_of_sorted
        del order, file_of_sorted
        self._phase("kernel_s", _time.perf_counter() - t0)

        # -- pass B: route full rows to per-output-file runs --------------
        # The routing code rides along as a temp column whose name cannot
        # collide with any indexed/included/lineage column.
        z_col = "__z"
        taken_names = set(columns) | {DATA_FILE_ID_COLUMN}
        while z_col in taken_names:
            z_col += "_"
        run_dir = tempfile.mkdtemp(prefix=_spill_dir_prefix("hs_zbuild_"))
        schema = None
        try:
            offset = 0
            for chunk_no, f in enumerate(files):
                t = self._read_chunk(f, columns, relation, lineage)
                if schema is None:
                    schema = t.schema
                t0 = _time.perf_counter()
                rows = t.num_rows
                if offset + rows > n:
                    raise HyperspaceError(
                        "Source grew between Z-order build passes; retry")
                fids = file_of_row[offset:offset + rows]
                t = t.append_column(
                    z_col, pa.array(codes[offset:offset + rows]))
                offset += rows
                o = np.argsort(fids, kind="stable")
                sf = fids[o]
                routed = t.take(pa.array(o))
                uniq = np.unique(sf)
                starts = np.searchsorted(sf, uniq, "left")
                ends = np.searchsorted(sf, uniq, "right")
                for fid, st, en in zip(uniq, starts, ends):
                    d = os.path.join(run_dir, f"file={int(fid):06d}")
                    os.makedirs(d, exist_ok=True)
                    self.build_report.add_bytes(spill=_write_run(
                        routed.slice(int(st), int(en - st)),
                        os.path.join(d, f"run-{chunk_no:05d}.arrow")),
                        spill_runs=1)
                self._phase("spill_route_s", _time.perf_counter() - t0)
            if offset != n:
                raise HyperspaceError(
                    "Source shrank between Z-order build passes; retry")

            t0 = _time.perf_counter()
            version = self.data_manager.get_next_version()
            out_dir = self.data_manager.version_path(version)
            os.makedirs(out_dir, exist_ok=True)

            def finish_file(dname: str) -> None:
                d = os.path.join(run_dir, dname)
                runs = sorted(os.listdir(d))  # chunk order = stable ties
                bt = pa.concat_tables(
                    [_read_run(os.path.join(d, r)) for r in runs],
                    promote_options="default")
                z = np.asarray(bt.column(z_col).to_numpy(
                    zero_copy_only=False))
                perm = np.argsort(z, kind="stable")
                bt = bt.take(pa.array(perm)).drop_columns([z_col])
                # One output file per pass-A chunk (already cell-aligned
                # and capped), written as bucket 0 — the logical index has
                # one bucket.
                written = write_bucket_run(
                    bt, 0, out_dir, 0,
                    compression=self.conf.index_file_compression)
                self.build_report.add_bytes(
                    written=sum(os.path.getsize(p) for p in written),
                    files=len(written))
                remove_tree(d, ignore_errors=True)  # runs consumed

            from hyperspace_tpu.utils.parallel_map import parallel_map_ordered

            parallel_map_ordered(finish_file, sorted(os.listdir(run_dir)),
                                 max_workers=4)
            self._phase("spill_finish_s", _time.perf_counter() - t0)
        finally:
            remove_tree(run_dir, ignore_errors=True)
        t0 = _time.perf_counter()
        self._write_index_file_sketch(out_dir, resolved)
        self._phase("sketch_s", _time.perf_counter() - t0)
        self._written_version = version
        self._index_schema = {name: str(t) for name, t in
                              zip(schema.names, schema.types)}

    def _use_distributed_build(self) -> bool:
        import jax

        mode = str(self.conf.parallel_build).lower()
        if mode in ("on", "true"):
            return True
        if mode in ("off", "false"):
            return False
        if mode != "auto":
            raise HyperspaceError(
                f"Invalid {self.conf.parallel_build!r} for parallel_build; "
                f"expected 'auto', 'on', or 'off'")
        return len(jax.devices()) > 1

    def _write_table_bucketed(self, table: pa.Table, resolved: IndexConfig,
                              version: Optional[int] = None) -> None:
        # Z-order: Morton codes are computed ONCE on host (global dense
        # ranks need a global pass, and the codes double as the writer's
        # split keys — files cut at Z-cell boundaries,
        # io/parquet.zorder_split_chunks, so every file's per-dimension
        # min/max stays narrow).  The permutation is simply their argsort:
        # there is no device shuffle to do for a one-bucket index, and a
        # hash shuffle would fragment the curve into per-partition samples,
        # gutting the pruning — so every build mode takes this path and
        # produces the identical, environment-independent layout.
        import time as _time

        t0 = _time.perf_counter()
        split_keys, split_bits = (None, 0)
        if resolved.layout == "zorder":
            from hyperspace_tpu.io.parquet import zorder_codes_host

            split_keys, split_bits = zorder_codes_host(
                table, resolved.indexed_columns)
            perm = np.argsort(split_keys, kind="stable")
            buckets = np.zeros(table.num_rows, dtype=np.int32)
        elif self._use_distributed_build():
            from hyperspace_tpu.parallel import (
                build_mesh,
                distributed_bucket_sort_permutation,
            )

            buckets, perm = distributed_bucket_sort_permutation(
                table, resolved.indexed_columns, self.num_buckets,
                build_mesh(), slack=self.conf.shuffle_capacity_slack,
                pad_to=self.conf.device_batch_rows)
        else:
            from hyperspace_tpu.ops.sort import (
                bucket_sort_permutation,
                bucket_sort_permutation_np,
            )

            word_cols = [columnar.to_hash_words(table.column(c))
                         for c in resolved.indexed_columns]
            order_words = [
                np.asarray(columnar.to_order_words(table.column(c)))
                for c in resolved.indexed_columns]
            if table.num_rows < self.conf.device_min_rows("build"):
                # Host mirror below the threshold — identical layout, no
                # device transfer/compile latency (see config).
                buckets, perm = bucket_sort_permutation_np(
                    [np.asarray(w) for w in word_cols], order_words,
                    self.num_buckets)
            else:
                buckets, perm = bucket_sort_permutation(
                    [np.asarray(w) for w in word_cols],
                    order_words,
                    self.num_buckets,
                    pad_to=self.conf.device_batch_rows)
        self._phase("kernel_s", _time.perf_counter() - t0)
        version = self.data_manager.get_next_version() if version is None else version
        out_dir = self.data_manager.version_path(version)
        t0 = _time.perf_counter()
        written = write_bucketed(
            table, np.asarray(buckets), np.asarray(perm),
            self.num_buckets, out_dir,
            max_rows_per_file=self.conf.index_max_rows_per_file,
            split_keys=split_keys, split_key_bits=split_bits,
            compression=self.conf.index_file_compression)
        self._phase("write_s", _time.perf_counter() - t0)
        self.build_report.add_bytes(
            written=sum(os.path.getsize(p) for p in written),
            files=len(written))
        t0 = _time.perf_counter()
        self._write_index_file_sketch(out_dir, resolved)
        self._phase("sketch_s", _time.perf_counter() - t0)
        self._written_version = version
        self._index_schema = {name: str(t) for name, t in
                              zip(table.column_names, table.schema.types)}

    def _write_index_file_sketch(self, out_dir: str,
                                 resolved: IndexConfig) -> None:
        """Per-index-file min/max over the indexed columns, written as
        ``_sketch.parquet`` next to the bucket files (underscore-prefixed =
        excluded from data-file listings).  Read from footers — O(footer).
        FilterIndexRule uses it to prune index FILES for range predicates;
        with the Z-order layout every indexed dimension's ranges are narrow
        so the pruning bites on all of them."""
        from hyperspace_tpu.actions.data_skipping import write_index_file_sketch

        write_index_file_sketch(out_dir, resolved.indexed_columns)

    # -- log entry (CreateActionBase.getIndexLogEntry:56-105) ---------------
    def _signature(self) -> Signature:
        provider_name = self.conf.signature_provider
        provider = get_provider(provider_name)
        value = provider.signature(
            self.plan,
            lambda scan: self.session.source_provider_manager
            .get_relation(scan).all_files())
        if value is None:
            raise HyperspaceError("Could not compute plan signature")
        return Signature(provider_name, value)

    def _build_log_entry(self) -> IndexLogEntry:
        relation = self._relation()
        resolved = self._resolved_config()
        rel_meta = relation.create_relation_metadata(self._file_id_tracker)
        # Refresh actions carry forward the previous entry's properties so
        # provider-accumulated state (e.g. the deltaVersions history) survives
        # (CreateActionBase.scala:56-105 + DeltaLakeFileBasedSource enrich).
        prev = getattr(self, "_previous_entry", None)
        properties: Dict[str, str] = dict(prev.properties) if prev is not None else {}
        properties["lineage"] = str(self.lineage_enabled).lower()
        # The log version this entry will commit at (Action end() writes at
        # base_id + 2) — providers record it in their version histories.
        properties["indexLogVersion"] = str(self.base_id + 2)
        properties = self.session.source_provider_manager.enrich_index_properties(
            rel_meta, properties)
        content = Content.from_directory(
            self.data_manager.version_path(self._written_version), FileIdTracker())
        return IndexLogEntry(
            name=self.config.index_name,
            derived_dataset=CoveringIndex(
                indexed_columns=resolved.indexed_columns,
                included_columns=resolved.included_columns,
                num_buckets=self.num_buckets,
                schema=getattr(self, "_index_schema", {}),
                properties={"layout": resolved.layout},
            ),
            content=content,
            source=Source(relations=[rel_meta],
                          fingerprint=LogicalPlanFingerprint([self._signature()])),
            properties=properties,
        )


def _write_chunk_file(routed: pa.Table, path: str, slices) -> int:
    """One (chunk, bucket group) spill file as raw Arrow IPC: one record
    batch per ``(offset, rows)`` slice — bucket-aligned, so finalize
    reads any bucket's run by batch index from a memory map without
    touching the rest.  ``combine_chunks`` pins each slice to ONE chunk
    = ONE batch, keeping batch index == slice position.  Returns the
    bytes landed (the build report's spill accounting)."""
    with pa.OSFile(path, "wb") as sink:
        with pa.ipc.new_file(sink, routed.schema) as writer:
            for off, rows in slices:
                writer.write_table(
                    routed.slice(off, rows).combine_chunks())
    return os.path.getsize(path)


def _write_run(table: pa.Table, path: str) -> int:
    """Temporary spill run file as RAW Arrow IPC: no parquet
    encode/decode for data that is read back exactly once and deleted —
    on a single-core host the encode was most of the spill cost.
    Returns the bytes landed (the build report's spill accounting)."""
    with pa.OSFile(path, "wb") as sink:
        with pa.ipc.new_file(sink, table.schema) as writer:
            writer.write_table(table)
    return os.path.getsize(path)


def _read_run(path: str) -> pa.Table:
    with pa.memory_map(path, "rb") as source:
        return pa.ipc.open_file(source).read_all()


def _footer_row_count(files, relation) -> Optional[int]:
    """Total rows from parquet footers (no decode), or None when any file
    is non-parquet/unreadable — a cheap 'does it fit one batch' probe."""
    import pyarrow.parquet as pq

    if relation.read_format != "parquet":
        return None
    total = 0
    for f in files:
        try:
            total += pq.read_metadata(f.name).num_rows
        except Exception:
            return None
    return total


class _BucketSpill:
    """External-build spill state: per-chunk fused route+partition into
    bucket-aligned Arrow runs, then streaming per-bucket-group finalize.

    Phase 1 (route) runs the SAME fused hash+lexsort program as the
    monolithic build (ops/hash._route_sort_impl — one compiled program,
    every chunk), so bucket assignment and tie order can never diverge
    between build sizes.  The chunk's rows land GROUPED BY BUCKET — and,
    for value-mapped key types, already SORTED within bucket, with the
    monotone uint64 sort codes riding along as temp columns — in ONE
    Arrow IPC file per (chunk, bucket group), one record batch per
    non-empty bucket.  That file layout is the sf10 lever: the old
    per-(chunk, bucket) run files meant chunks × buckets tiny-file
    creates/opens/unlinks (11,400 at sf10), all syscall overhead.

    Phase 2 (finalize) closes bucket GROUPS the moment routing drains
    and merges + parquet-encodes them on a dedicated worker pool,
    CONCURRENT with the tail of routing and with each other.  Pre-sorted
    runs make the merge a lexsort over the ride-along codes instead of
    re-deriving order words for every row; batches read back zero-copy
    from a memory map, and each group's chunk files are deleted the
    moment the group is consumed, so peak disk stays source + runs + a
    few in-flight groups (matters at SF100).  Runs concatenate in chunk
    order, so the stable merge reproduces the monolithic tie order
    exactly.

    ``hyperspace.index.build.pipeline.enabled=false`` forces the serial
    reference: inline reads, inline routing, sequential group finalize —
    the same functions in the same order, so the flag changes SCHEDULING
    only and the output stays bit-equal (tests/test_build_pipeline.py
    holds it to that)."""

    # Route workers: chunk routing (fused kernel + run write) is
    # independent per chunk once its number is assigned, so on
    # multi-core hosts chunks route concurrently while the stream loop
    # keeps decoding.  Single-core hosts degrade to inline routing (a
    # pool of GIL-sharing workers would only add overhead there).
    _MAX_ROUTE_WORKERS = 4
    _MAX_IN_FLIGHT = 3  # each in-flight chunk pins one device batch in RAM
    _MAX_GROUPS = 8     # bucket groups = spill-file + finalize granularity

    def __init__(self, action: "CreateActionBase", resolved: IndexConfig) -> None:
        self.action = action
        self.resolved = resolved
        self.spilled = False
        self.pipelined = bool(getattr(action.conf,
                                      "build_pipeline_enabled", True))
        self._num_buckets = action.num_buckets
        self._groups = min(self._MAX_GROUPS, self._num_buckets)
        # Contiguous bucket ranges per group: group of bucket b is the
        # gid with _bounds[gid] <= b < _bounds[gid + 1] — contiguous in
        # the chunk's sorted order, so a group's rows are one slice.
        # The cuts are the shared ownership contract
        # (parallel/sharded_build.bucket_group_bounds): the multi-host
        # build claims the SAME ranges cross-host.
        from hyperspace_tpu.parallel.sharded_build import bucket_group_bounds

        self._bounds = bucket_group_bounds(self._num_buckets, self._groups)
        self._chunk_no = 0
        self._schema = None
        self._code_cols: tuple = ()
        self._mesh = None       # resolved lazily at first route
        self._mesh_probed = False
        self._dir = None  # created on first spill; non-spilling builds
        # never touch disk
        self._pool = None
        self._futures: List = []
        # Run manifest: bucket -> [(chunk_no, path, batch_index)], plus
        # per-group chunk-file lists for consumed-group deletion.  Route
        # workers append concurrently.
        self._manifest_lock = threading.Lock()
        self._runs: Dict[int, List] = {}
        self._group_files: Dict[int, List[str]] = {}
        # Streaming-finalize state: groups close when the LAST route job
        # lands after end-of-input — possibly on a route worker thread,
        # while finish() is still joining earlier futures.
        self._close_lock = threading.Lock()
        self._routes_pending = 0
        self._input_done = False
        self._closed = False
        self._route_failed = False
        self._finalize_pool = None
        self._finalize_futures: List = []
        self._out_dir: Optional[str] = None

    def _route_pool(self):
        import os as _os

        if not self.pipelined:
            return None  # forced-serial reference: inline routing
        if self._pool is None and (_os.cpu_count() or 1) > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=min(self._MAX_ROUTE_WORKERS,
                                _os.cpu_count() or 1),
                thread_name_prefix="hs-route")
        return self._pool

    def _drain(self) -> None:
        """Wait for in-flight route jobs; re-raise the first failure."""
        futures, self._futures = self._futures, []
        for fut in futures:
            fut.result()

    def _drain_finalize(self) -> None:
        """Wait for in-flight group-finalize jobs; re-raise the first
        failure."""
        futures, self._finalize_futures = self._finalize_futures, []
        for fut in futures:
            fut.result()

    def cleanup(self) -> None:
        try:
            self._drain()
        # cleanup() on the failure path re-raises the ORIGINAL error
        # right after, so a secondary drain failure is discarded.
        # hslint: allow[exception-discipline] secondary failure in cleanup
        except BaseException:
            pass
        try:
            self._drain_finalize()
        # hslint: allow[exception-discipline] secondary failure in cleanup
        except BaseException:
            pass
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self._finalize_pool is not None:
            self._finalize_pool.shutdown(wait=True)
            self._finalize_pool = None
        if self._dir is not None:
            from hyperspace_tpu.io.files import remove_tree

            remove_tree(self._dir, ignore_errors=True)
            self._dir = None

    def _plan_code_columns(self, table: pa.Table) -> tuple:
        """Ride-along sort-code column names (one uint64 per indexed
        column), or () when any key type is rank-mapped (strings/binary/
        decimal): chunk-local dense ranks are not comparable across
        chunks, so those builds keep the route grouped-only and
        re-derive order words per bucket at finalize, exactly like the
        pre-pipeline builder."""
        key_cols = list(self.resolved.indexed_columns)
        for c in key_cols:
            if not columnar.is_numeric_type(table.schema.field(c).type):
                return ()
        taken = set(table.column_names)
        names = []
        for i in range(len(key_cols)):
            name = f"__hs_sort{i}"
            while name in taken:
                name += "_"
            taken.add(name)
            names.append(name)
        return tuple(names)

    def add_chunk(self, table: pa.Table) -> None:
        if self._dir is None:
            import tempfile

            self._dir = tempfile.mkdtemp(
                prefix=_spill_dir_prefix("hs_build_spill_"))
        self.spilled = True
        if self._schema is None:
            self._schema = table.schema
            self._code_cols = self._plan_code_columns(table)
        chunk_no = self._chunk_no
        self._chunk_no += 1
        pool = self._route_pool()
        if pool is None:
            self._route_chunk(table, chunk_no)
            return
        while len(self._futures) >= self._MAX_IN_FLIGHT:
            self._futures.pop(0).result()
        with self._close_lock:
            self._routes_pending += 1
        self._futures.append(
            pool.submit(self._route_traced, table, chunk_no))

    def _route_traced(self, table: pa.Table, chunk_no: int) -> None:
        """Route one chunk on a worker thread and fire the streaming
        close when this was the LAST route job after end-of-input —
        finalize then starts while finish() is still joining futures."""
        ok = False
        try:
            self._route_chunk(table, chunk_no)
            ok = True
        finally:
            fire = False
            with self._close_lock:
                self._routes_pending -= 1
                if not ok:
                    self._route_failed = True
                elif self._input_done and self._routes_pending == 0 \
                        and not self._closed and not self._route_failed:
                    self._closed = True
                    fire = True
            if fire:
                self._close_groups()

    def _active_mesh(self):
        """The engine mesh for this build's chunk routes, resolved once
        (``hyperspace.parallel.mesh.enabled``; None = single-device)."""
        if not self._mesh_probed:
            from hyperspace_tpu.parallel.mesh import active_mesh

            self._mesh = active_mesh(self.action.conf)
            self._mesh_probed = True
        return self._mesh

    def _route_chunk(self, table: pa.Table, chunk_no: int) -> None:
        import time as _time

        from hyperspace_tpu.ops.hash import (
            route_partition,
            route_partition_mesh,
            route_partition_np,
        )

        _t0 = _time.perf_counter()
        n = table.num_rows
        # Z-order builds never spill here (they take the dedicated
        # two-pass path that preserves the global curve), so partitions
        # are always real index buckets.
        num_buckets = self._num_buckets
        key_cols = list(self.resolved.indexed_columns)
        word_cols = [np.asarray(columnar.to_hash_words(table.column(c)))
                     for c in key_cols]
        # Value-mapped keys: monotone sort codes come along, so the ONE
        # fused pass both buckets the rows and sorts them within bucket
        # — and the writer's sort codes are THIS pass's byproduct riding
        # the runs as temp uint64 columns, not a finalize-time recompute
        # over every row.  The host mirror keys on the uint64 codes
        # directly; only the device kernel needs the 32-bit word split.
        codes64 = [columnar.to_order_codes64(table.column(c))
                   for c in key_cols] if self._code_cols else []
        if n < self.action.conf.device_min_rows("build"):
            # Host mirror below the threshold, same cost model as the
            # monolithic build: a per-chunk device round trip (transfer
            # + possible compile, per chunk!) over a remote tunnel
            # dwarfs a host pass — and the mirror is bit-identical, so
            # layout cannot depend on the route.
            buckets, perm = route_partition_np(word_cols, codes64,
                                               num_buckets)
        elif (mesh := self._active_mesh()) is not None:
            # Sharded route: rows data-parallel over the mesh, each
            # device owning buckets ``b % n_devices``, per-device runs
            # gathered through the attributed host seam (one pull per
            # device per chunk) — bit-identical layout, proven by
            # tests/test_parallel_mesh.py's per-bucket digests.
            devices = list(mesh.devices.flat)
            buckets, perm = route_partition_mesh(
                word_cols,
                [columnar.split_words64(k) for k in codes64],
                num_buckets, mesh,
                pad_to=max(1, int(self.action.conf.device_batch_rows)))
            ms = (_time.perf_counter() - _t0) * 1000.0
            report = self.action.build_report
            report.properties["mesh_devices"] = len(devices)
            for dev in devices:
                report.add_device_kernel_ms(
                    int(getattr(dev, "id", -1)), ms)
        else:
            buckets, perm = route_partition(
                word_cols,
                [columnar.split_words64(k) for k in codes64],
                num_buckets,
                pad_to=max(1, int(self.action.conf.device_batch_rows)))
        buckets = np.asarray(buckets)
        perm = np.asarray(perm)
        sorted_buckets = buckets[perm]
        routed = table.take(pa.array(perm))
        for i, name in enumerate(self._code_cols):
            routed = routed.append_column(name,
                                          pa.array(codes64[i][perm]))
        starts = np.searchsorted(sorted_buckets, np.arange(num_buckets),
                                 "left")
        ends = np.searchsorted(sorted_buckets, np.arange(num_buckets),
                               "right")
        self._write_chunk_runs(routed, chunk_no, starts, ends)
        self.action._phase("spill_route_s", _time.perf_counter() - _t0)

    def _write_chunk_runs(self, routed: pa.Table, chunk_no: int,
                          starts, ends) -> None:
        """One Arrow IPC file per (chunk, bucket group), one record
        batch per non-empty bucket: per-bucket random access at finalize
        with _groups file ops per chunk instead of num_buckets.  Run
        files are TEMPORARY (read back once, deleted): raw IPC skips the
        parquet encode/decode entirely, and batches read back zero-copy
        from a memory map."""
        for gid in range(self._groups):
            b0, b1 = self._bounds[gid], self._bounds[gid + 1]
            present = [b for b in range(b0, b1) if ends[b] > starts[b]]
            if not present:
                continue
            path = os.path.join(
                self._dir, f"chunk-{chunk_no:05d}-g{gid:03d}.arrow")
            nbytes = _write_chunk_file(
                routed, path,
                [(int(starts[b]), int(ends[b] - starts[b]))
                 for b in present])
            with self._manifest_lock:
                for bi, b in enumerate(present):
                    self._runs.setdefault(b, []).append(
                        (chunk_no, path, bi))
                self._group_files.setdefault(gid, []).append(path)
            self.action.build_report.add_bytes(
                spill=nbytes, spill_runs=len(present))

    def _finalize_pool_get(self):
        import os as _os

        if self._finalize_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            # Capped at the core count: group finalize is CPU-bound
            # (merge + parquet encode), so extra threads on a small host
            # only buy GIL/scheduler contention — measured ~25% slower
            # with 4 workers on 1 core.  One worker still STREAMS
            # (groups start the moment routing drains).
            workers = max(1, min(
                int(getattr(self.action.conf, "build_finalize_workers",
                            4)),
                _os.cpu_count() or 1))
            self._finalize_pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="hs-finalize")
        return self._finalize_pool

    def _close_groups(self) -> None:
        """Every routed bucket group is now closed: enqueue each on the
        finalize pool (streaming mode) or finish them in order (serial
        reference).  May run on a route worker thread — the finalize
        pool starts draining groups while finish() is still joining the
        earlier route futures."""
        with self._manifest_lock:
            gids = sorted(self._group_files)
        if self.pipelined:
            pool = self._finalize_pool_get()
            self._finalize_futures.extend(
                pool.submit(self._finish_group, gid) for gid in gids)
        else:
            for gid in gids:
                self._finish_group(gid)

    def _finish_group(self, gid: int) -> None:
        """Merge + parquet-encode every bucket of one closed group, then
        delete the group's chunk files — consumed spill space is
        returned while OTHER groups still hold theirs, so peak disk is
        source + runs + in-flight groups, not source + runs + the whole
        final index."""
        import time as _time

        from hyperspace_tpu.io.files import remove_file
        from hyperspace_tpu.io.parquet import (
            sort_permutation_from_codes,
            write_bucket_run,
        )

        _t0 = _time.perf_counter()
        action = self.action
        max_rows = action.conf.index_max_rows_per_file
        b0, b1 = self._bounds[gid], self._bounds[gid + 1]
        with self._manifest_lock:
            paths = list(self._group_files.get(gid, ()))
            buckets = sorted(b for b in self._runs if b0 <= b < b1)
        readers = {}
        handles = []
        try:
            for p in paths:
                mm = pa.memory_map(p, "rb")
                handles.append(mm)
                readers[p] = pa.ipc.open_file(mm)
            for b in buckets:
                with self._manifest_lock:
                    runs = sorted(self._runs[b])  # chunk order = ties
                batches = [readers[p].get_batch(bi) for _, p, bi in runs]
                btable = pa.Table.from_batches(batches)
                if self._code_cols:
                    perm = sort_permutation_from_codes(btable,
                                                       self._code_cols)
                    btable = btable.take(pa.array(perm)).drop_columns(
                        list(self._code_cols))
                else:
                    perm = self._sort_permutation(btable)
                    btable = btable.take(pa.array(perm))
                written = write_bucket_run(
                    btable, b, self._out_dir, max_rows,
                    compression=action.conf.index_file_compression)
                action.build_report.add_bytes(
                    written=sum(os.path.getsize(p) for p in written),
                    files=len(written))
        finally:
            for mm in handles:
                try:
                    mm.close()
                except OSError:
                    pass
        for p in paths:
            remove_file(p, missing_ok=True)
        action._phase("spill_finish_s", _time.perf_counter() - _t0)

    def finish(self) -> None:
        import time as _time

        from hyperspace_tpu.io.files import remove_tree

        action = self.action
        resolved = self.resolved
        # The version dir must exist BEFORE end-of-input is announced:
        # the first finalize worker may start while route futures are
        # still draining.
        version = action.data_manager.get_next_version()
        out_dir = action.data_manager.version_path(version)
        os.makedirs(out_dir, exist_ok=True)
        self._out_dir = out_dir
        fire = False
        with self._close_lock:
            self._input_done = True
            if self._routes_pending == 0 and not self._closed \
                    and not self._route_failed:
                self._closed = True
                fire = True
        if fire:
            self._close_groups()
        self._drain()  # re-raise the first route failure
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        # The EXPOSED finalize tail: how long the build still waits on
        # bucket-group encode after routing fully drained — the number
        # the streaming overlap is spent against (``finalize`` phase and
        # timeline lane; the per-group work itself lands in
        # ``spill_finish`` on the pool workers).
        _t0 = _time.perf_counter()
        try:
            self._drain_finalize()
        finally:
            if self.pipelined:
                action._phase("finalize_s", _time.perf_counter() - _t0)
        if self._finalize_pool is not None:
            self._finalize_pool.shutdown(wait=True)
            self._finalize_pool = None
        remove_tree(self._dir, ignore_errors=True)
        self._dir = None
        _t0 = _time.perf_counter()
        action._write_index_file_sketch(out_dir, resolved)
        action._phase("sketch_s", _time.perf_counter() - _t0)
        action._written_version = version
        action._index_schema = {name: str(t) for name, t in
                                zip(self._schema.names, self._schema.types)}

    def _sort_permutation(self, btable: pa.Table) -> np.ndarray:
        # Always the lexicographic layout here: zorder builds take the
        # dedicated two-pass path and never reach the hash spill.
        from hyperspace_tpu.io.parquet import sort_permutation_host

        return sort_permutation_host(btable, self.resolved.indexed_columns,
                                     self.resolved.layout)


class CreateAction(CreateActionBase):
    transient_state = States.CREATING
    final_state = States.ACTIVE
    event_class = CreateActionEvent

    def validate(self) -> None:
        # CreateAction.scala:45-66
        if self.previous_log_entry is not None and \
                self.previous_log_entry.state not in (States.DOESNOTEXIST,):
            raise HyperspaceError(
                f"Another index with name {self.config.index_name!r} already "
                f"exists in state {self.previous_log_entry.state}")
        leaves = self.plan.leaf_relations()
        if len(leaves) != 1 or not \
                self.session.source_provider_manager.is_supported_relation(leaves[0]):
            raise HyperspaceError("Only plans over one supported file-based "
                                  "relation can be indexed")
        self._resolved_config()  # raises on unresolvable columns

    def log_entry_for_begin(self) -> IndexLogEntry:
        # Fresh entry: the index data hasn't been written yet, so content is
        # a placeholder tree of the (empty) v0 dir.
        relation = self._relation()
        resolved = self._resolved_config()
        rel_meta = relation.create_relation_metadata(FileIdTracker())
        return IndexLogEntry(
            name=self.config.index_name,
            derived_dataset=CoveringIndex(
                indexed_columns=resolved.indexed_columns,
                included_columns=resolved.included_columns,
                num_buckets=self.num_buckets,
                schema={},
            ),
            content=Content.from_leaf_files(
                []) or Content.from_directory(self.data_manager.index_path, FileIdTracker()),
            source=Source(relations=[rel_meta],
                          fingerprint=LogicalPlanFingerprint([self._signature()])),
        )

    def op(self) -> None:
        self._build_index_data()

    def log_entry(self) -> IndexLogEntry:
        return self._build_log_entry()
