"""The action state machine: generic begin → op → end protocol with
optimistic concurrency.

Reference contract: actions/Action.scala:35-105 —
  - ``base_id`` is captured from the latest log id when the action starts (:35)
  - ``begin()`` writes a *transient*-state entry at ``base_id + 1`` (:49-55);
    the create-if-absent write is what detects concurrent writers
  - ``op()`` does the actual work (:58)
  - ``end()`` writes the *final*-state entry at ``base_id + 2``, deleting and
    recreating the ``latestStable`` pointer (:60-75)
  - ``run()`` wraps the protocol with validation, telemetry, and
    NoChangesException no-op handling (:84-105)

An action that dies mid-flight leaves the transient entry as the latest log
record; subsequent actions refuse to run and the user recovers with
``cancel()`` (actions/CancelAction.scala:25-58) — or, with
``hyperspace.index.autoRecovery.enabled``, the next lifecycle call through
the collection manager performs that rollback implicitly
(index/manager.py).  Crash points are exercised under injected faults
(io/faults.py, tests/test_concurrency.py's TestCrashRecovery).

Beyond the reference: ``run()`` is an **optimistic transaction loop**
(the Delta-style commit model).  A ``ConcurrentWriteError`` no longer
necessarily aborts the action — when the collection manager armed
``hyperspace.index.concurrency.maxRetries``, the action REBASES
(recaptures ``base_id`` / the previous entry from the state the winning
writer left behind), re-validates, and retries the whole
begin→op→end sequence after a jittered backoff.  A retry whose
re-validation finds nothing left to do (the winner did our work) exits
through the normal NoChangesError no-op path; one that finds a
structurally impossible state (e.g. create over a now-ACTIVE index)
surfaces the validation error.  Work a failed attempt already wrote
(an uncommitted ``v__=N`` data dir, a stale transient entry below the
winner's commits) is exactly the state cancel()/auto-recovery and
vacuum already clean up.
"""

from __future__ import annotations

import random
import time
from typing import Optional, Type

from hyperspace_tpu.exceptions import ConcurrentWriteError, HyperspaceError, NoChangesError
from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.io import faults
from hyperspace_tpu.telemetry.events import _IndexActionEvent, emit_event
from hyperspace_tpu.utils.retry import RetryPolicy


class Action:
    # Subclasses set these.
    transient_state: str = ""
    final_state: str = ""
    event_class: Optional[Type[_IndexActionEvent]] = None
    # Conflict-retry budget + backoff schedule for the optimistic
    # transaction loop.  Class-level default 0 keeps directly-constructed
    # actions on the reference's abort-on-conflict contract; the
    # collection manager overrides the INSTANCE attributes from
    # ``hyperspace.index.concurrency.maxRetries`` / the io.retry backoff
    # keys (index/manager._dispatch).
    concurrency_max_retries: int = 0
    conflict_backoff: RetryPolicy = RetryPolicy()

    def __init__(self, log_manager: IndexLogManager) -> None:
        self.log_manager = log_manager
        # base_id MUST be captured eagerly (Action.scala:35 is a val): the
        # optimistic-concurrency check works only if begin()/end() write at
        # ids derived from the state this action validated against.
        latest = self.log_manager.get_latest_id()
        self._base_id: int = 0 if latest is None else latest
        self.previous_log_entry: Optional[IndexLogEntry] = self.log_manager.get_latest_log()
        # Conflicts absorbed by the transaction loop this run (observable
        # by tests and telemetry consumers).
        self.conflict_retries: int = 0
        # Per-run performance attribution (telemetry/build_report.py):
        # owned by the ACTION so spill worker threads can record into it
        # without contextvar propagation.  run() finalizes and publishes.
        from hyperspace_tpu.telemetry.build_report import BuildReport

        self.build_report = BuildReport(action=type(self).__name__)

    # -- protocol pieces ----------------------------------------------------
    @property
    def base_id(self) -> int:
        return self._base_id

    @property
    def index_name(self) -> str:
        if self.previous_log_entry is not None:
            return self.previous_log_entry.name
        return ""

    def validate(self) -> None:
        """Precondition check; raise HyperspaceError (or NoChangesError for
        benign no-ops) before any state is written."""

    def op(self) -> None:
        raise NotImplementedError

    def log_entry(self) -> IndexLogEntry:
        """The entry to commit at end(); built after op() so it can reference
        freshly written index data."""
        raise NotImplementedError

    # -- protocol -----------------------------------------------------------
    def begin(self) -> None:
        entry = self.log_entry_for_begin()
        entry.state = self.transient_state
        self.log_manager.write_log_or_raise(self.base_id + 1, entry)

    def log_entry_for_begin(self) -> IndexLogEntry:
        """Entry written at begin(); by default the previous entry (actions on
        existing indexes).  CreateAction overrides to build a fresh one."""
        if self.previous_log_entry is None:
            raise HyperspaceError("No existing index log entry for this action")
        import copy

        return copy.deepcopy(self.previous_log_entry)

    def end(self) -> None:
        entry = self.log_entry()
        entry.state = self.final_state
        self.log_manager.delete_latest_stable_log()
        self.log_manager.write_log_or_raise(self.base_id + 2, entry)
        self.log_manager.create_latest_stable_log(self.base_id + 2)

    def _rebase(self) -> None:
        """Recapture the optimistic-concurrency baseline after a conflict:
        the next attempt must validate against — and write at ids derived
        from — the state the WINNING writer committed, or the retry would
        just re-collide (or worse, resurrect state the winner superseded).
        Subclasses with richer captured state (refresh's previous stable
        entry + file-id tracker) extend this."""
        latest = self.log_manager.get_latest_id()
        self._base_id = 0 if latest is None else latest
        self.previous_log_entry = self.log_manager.get_latest_log()

    def run(self) -> str:
        """Action.scala:84-105, wrapped in the conflict-retrying
        transaction loop (concurrency_max_retries=0 ⇒ reference
        behavior: first conflict aborts).  Returns the outcome —
        ``"ok"`` for a committed run, ``"noop"`` for a benign
        NoChangesError no-op — so dispatchers (the refresh summary, the
        maintenance daemon) can tell the two apart without re-reading
        the log.

        Every turn of the loop is telemetry-visible: a ``CONFLICT_RETRY
        n/max`` ActionEvent per absorbed conflict (attempt number +
        conflict reason in ``state``/``message``) and the
        ``action.conflict.retries`` counter, so PR 2's silent rebases can
        be audited per action after the fact."""

        def emit(state: str, message: str = "") -> None:
            if self.event_class is not None:
                emit_event(self.event_class(
                    index_name=self.index_name, state=state, message=message))

        rng = random.Random()
        # The report times run() itself: construction-to-run gaps (refresh
        # diffing in __init__) are not this run's wall clock.
        report = self.build_report
        report._t0 = time.perf_counter()
        report.started_at = time.time()
        report.index = self.index_name
        # Timeline profiler (telemetry/timeline.py): apply this session's
        # conf and, when enabled, sample memory in the background for the
        # run's duration — per-phase high-water marks instead of one
        # end-of-action peak.  The finally covers InjectedCrash too: a
        # leaked sampler thread would outlive the simulated kill.
        from hyperspace_tpu.telemetry import timeline
        from hyperspace_tpu.telemetry import build_report as _br

        sampler = None
        session = getattr(self, "session", None)
        if session is not None:
            timeline.configure_from_conf(session.conf)
            if _br.profiling_enabled(session.conf):
                sampler = timeline.start_sampler(session.conf, report)
        try:
            return self._run_transaction(emit, rng)
        finally:
            if sampler is not None:
                sampler.stop()

    def _run_transaction(self, emit, rng) -> str:
        """The conflict-retrying transaction loop proper (split from
        ``run()`` so the sampler's try/finally wraps the whole thing)."""
        from hyperspace_tpu.telemetry.trace import span

        with span(f"action.{type(self).__name__}",
                  index=self.index_name) as sp:
            try:
                while True:
                    try:
                        outcome = self._attempt(emit)
                        if outcome == "ok":
                            # A committed index change makes every cached
                            # optimize result suspect: bump the serving
                            # layer's plan-cache generation so the next
                            # served query re-plans against the new state
                            # (execution/plan_cache.py).
                            from hyperspace_tpu.execution import plan_cache

                            plan_cache.bump_generation()
                        sp.set(conflict_retries=self.conflict_retries)
                        self._finish_report(outcome, "", sp)
                        return outcome
                    except ConcurrentWriteError as e:
                        if self.conflict_retries >= \
                                self.concurrency_max_retries:
                            emit("FAILURE", "concurrent modification")
                            raise
                        self.conflict_retries += 1
                        emit(f"CONFLICT_RETRY "
                             f"{self.conflict_retries}/"
                             f"{self.concurrency_max_retries}",
                             f"concurrent write at base_id={self.base_id}: "
                             f"{e}")
                        # Jittered backoff so two rebased racers don't
                        # re-collide in lockstep (and a stale object-store
                        # listing gets its visibility window to pass before
                        # the re-validation).
                        time.sleep(self.conflict_backoff.delay_s(
                            self.conflict_retries - 1, rng))
                        self._rebase()
            except Exception as e:
                # Failed runs still report (a crashed SPILL phase is
                # exactly when attribution matters); InjectedCrash is a
                # BaseException and skips this like a real kill -9 would.
                self._finish_report("error", str(e), sp)
                raise

    def _finish_report(self, outcome: str, error: str, sp) -> None:
        """Finalize + publish this run's BuildReport; export metrics,
        synthesize phase spans, and append the perf-ledger record.
        Diagnostics must never fail the action — everything here is
        best-effort."""
        from hyperspace_tpu.telemetry import build_report as br
        from hyperspace_tpu.telemetry import perf_ledger

        report = self.build_report
        report.conflict_retries = self.conflict_retries
        report.index = report.index or self.index_name
        session = getattr(self, "session", None)
        conf = session.conf if session is not None else None
        try:
            profiled = conf is None or br.profiling_enabled(conf)
            if profiled:
                report.sample_memory()
            report.finish(outcome, error)
            br.publish(report, session)
            if profiled:
                report.export_metrics()
                report.attach_to_span(sp)
            if conf is not None and profiled:
                perf_ledger.append(conf, {
                    "kind": "action", "name": f"{report.action}"
                    f"({report.index})" if report.index else report.action,
                    **{k: v for k, v in report.to_dict().items()
                       if k not in ("started_at",)},
                    "fingerprint": perf_ledger.fingerprint(conf)})
        except Exception:  # noqa: BLE001 — diagnostics must never fail
            # the action; count the swallowed failure so a broken report/
            # ledger path is at least visible in the registry.
            from hyperspace_tpu.telemetry import metrics

            metrics.inc("build.report.errors")

    def _attempt(self, emit) -> str:
        """One turn of the transaction loop; returns the outcome
        (``"ok"``/``"noop"``) for the build report.  The ``validate`` and
        ``commit`` phases are timed here so a report's phase sum accounts
        for the whole protocol, not just op()'s build work."""
        t0 = time.perf_counter()
        try:
            self.validate()
        except NoChangesError as e:
            emit(States.ACTIVE, f"No-op: {e}")
            return "noop"
        finally:
            self.build_report.add_phase("validate", time.perf_counter() - t0)
        try:
            t0 = time.perf_counter()
            self.begin()
            self.build_report.add_phase("commit", time.perf_counter() - t0)
            self.op()
            # Crash checkpoint (io/faults.py): the work is done but the
            # final entry is not committed — the state a killed process
            # leaves behind, which cancel()/auto-recovery must roll back.
            # InjectedCrash is a BaseException, so the handlers below
            # (like a real kill -9) never see it.
            faults.check("action.commit")
            t0 = time.perf_counter()
            self.end()
            self.build_report.add_phase("commit", time.perf_counter() - t0)
            emit(self.final_state)
            return "ok"
        except ConcurrentWriteError:
            raise  # run()'s transaction loop arbitrates: retry or FAILURE
        except Exception as e:
            emit("FAILURE", str(e))
            raise
