"""VerifyIndexAction: scrub an index's data files against its log entry.

The detection half of the integrity loop (docs/15-integrity.md).  Two
modes, mirroring what real lake scrubbers (HDFS block scanner, ZFS
scrub) offer:

  - ``quick``  — stat-level: every file the latest stable entry
    references must exist with the recorded size and mtime.  O(files)
    metadata calls, no data read — cheap enough for a cron.
  - ``full``   — quick plus a streamed re-read + re-hash of every file
    against the content digest recorded at write time
    (io/integrity.py).  Catches silent bit-rot that leaves size and
    mtime untouched.  Entries written before digests existed (or with
    ``digestOnWrite`` off) report ``status="unknown"`` — never a
    fabricated mismatch.

Unlike the lifecycle actions this writes NO log entry: a scrub must be
runnable against a live index from any process without burning log ids
or racing writers.  Its only mutation is the quarantine set
(index/quarantine.py): damaged files are quarantined (idempotently), a
previously-quarantined file that now passes a FULL check is released,
and full mode garbage-collects records no current entry references.
The per-file report comes back as an arrow table; telemetry gets an
``IndexScrubEvent``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.index.quarantine import QuarantineManager
from hyperspace_tpu.io import integrity
from hyperspace_tpu.telemetry.events import IndexScrubEvent, emit_event

# Statuses a scrub can assign; FLAGGED ones are quarantined.
STATUS_OK = "ok"
STATUS_UNKNOWN = "unknown"          # no digest to check against (full mode)
STATUS_MISSING = "missing"
STATUS_SIZE_MISMATCH = "size-mismatch"
STATUS_MTIME_DRIFT = "mtime-drift"  # stat drift alone: reported, not
# quarantined (copies/restores legitimately touch mtime; the digest is
# the truth and full mode checks it)
STATUS_DIGEST_MISMATCH = "digest-mismatch"
STATUS_UNREADABLE = "unreadable"

_FLAGGED = frozenset({STATUS_MISSING, STATUS_SIZE_MISMATCH,
                      STATUS_DIGEST_MISMATCH, STATUS_UNREADABLE})


class VerifyIndexAction:
    def __init__(self, log_manager: IndexLogManager,
                 data_manager: IndexDataManager,
                 quarantine: QuarantineManager,
                 mode: str = "quick") -> None:
        if mode not in ("quick", "full"):
            raise HyperspaceError(f"Unknown verify mode {mode!r}")
        self.log_manager = log_manager
        self.data_manager = data_manager
        self.quarantine = quarantine
        self.mode = mode

    # -- per-file check ------------------------------------------------------
    def _check_file(self, f) -> Dict[str, str]:
        try:
            st = os.stat(f.name)
        except FileNotFoundError:
            return {"status": STATUS_MISSING, "detail": "file not found"}
        except OSError as e:
            return {"status": STATUS_UNREADABLE, "detail": str(e)}
        if st.st_size != f.size:
            return {"status": STATUS_SIZE_MISMATCH,
                    "detail": f"size {st.st_size} != recorded {f.size}"}
        drift = int(st.st_mtime_ns) != f.mtime
        if self.mode == "quick":
            if drift:
                return {"status": STATUS_MTIME_DRIFT,
                        "detail": f"mtime {st.st_mtime_ns} != recorded "
                                  f"{f.mtime}"}
            return {"status": STATUS_OK, "detail": ""}
        # full: re-read and re-hash against the recorded digest.
        if f.digest is None:
            return {"status": STATUS_UNKNOWN,
                    "detail": "no digest recorded (pre-integrity entry or "
                              "digestOnWrite off)"}
        try:
            verdict = integrity.verify_file(f.name, f.digest)
        except OSError as e:
            return {"status": STATUS_UNREADABLE, "detail": str(e)}
        if verdict is None:
            return {"status": STATUS_UNKNOWN,
                    "detail": f"digest algorithm unavailable: {f.digest}"}
        if not verdict:
            return {"status": STATUS_DIGEST_MISMATCH,
                    "detail": f"content does not match {f.digest}"
                              + (" (mtime drifted too)" if drift else "")}
        if drift:
            return {"status": STATUS_MTIME_DRIFT,
                    "detail": "content verified; only mtime drifted"}
        return {"status": STATUS_OK, "detail": ""}

    # -- the scrub -----------------------------------------------------------
    def run(self) -> pa.Table:
        entry: Optional[IndexLogEntry] = \
            self.log_manager.get_latest_stable_log()
        if entry is None:
            raise HyperspaceError(
                "verify_index: index does not exist (no stable log entry)")
        infos = entry.content.file_infos()
        already = self.quarantine.paths()
        rows: List[Dict[str, str]] = []
        flagged = 0
        referenced = set()
        for f in infos:
            referenced.add(f.name)
            res = self._check_file(f)
            status = res["status"]
            quarantined = f.name in already
            if status in _FLAGGED:
                flagged += 1
                if not quarantined:
                    self.quarantine.add(f.name, f"scrub[{self.mode}]: "
                                                f"{status}", size=f.size)
                quarantined = True
            elif quarantined and self.mode == "full" \
                    and status in (STATUS_OK, STATUS_MTIME_DRIFT):
                # The file verified clean end to end (a restore from
                # backup, say): release it.  Quick mode never releases —
                # it did not look at the bytes.
                self.quarantine.remove(f.name)
                quarantined = False
            rows.append({"file": f.name, "status": status,
                         "detail": res["detail"],
                         "quarantined": quarantined})
        if self.mode == "full":
            # GC quarantine records no current entry references (files a
            # repair or optimize already superseded): harmless to the
            # rules — they intersect with entry content — but noise in
            # reports and a leak over many repair cycles.
            for stale in already - referenced:
                self.quarantine.remove(stale)
        emit_event(IndexScrubEvent(
            index_name=entry.name, mode=self.mode,
            files_checked=len(infos), files_flagged=flagged,
            message=f"scrub[{self.mode}] {entry.name}: "
                    f"{flagged}/{len(infos)} flagged"))
        return pa.table({
            "file": pa.array([r["file"] for r in rows], type=pa.string()),
            "status": pa.array([r["status"] for r in rows],
                               type=pa.string()),
            "detail": pa.array([r["detail"] for r in rows],
                               type=pa.string()),
            "quarantined": pa.array([r["quarantined"] for r in rows],
                                    type=pa.bool_()),
        })
