"""Refresh actions: bring an index up to date with mutated source data.

Reference contract:
  - RefreshActionBase (actions/RefreshActionBase.scala:33-145): reconstructs
    the source dataset from the *stored* relation metadata via the provider
    (:71-89), diffs current files vs the entry's recorded files into
    appended/deleted sets (:115-144), and pins numBuckets + lineage to the
    previous entry (:56-64) so a refreshed index stays self-consistent.
  - RefreshAction (full rebuild; no-op when source unchanged,
    actions/RefreshAction.scala:33-59).
  - RefreshIncrementalAction (actions/RefreshIncrementalAction.scala:54-145):
    appended files → index just those into a new version; deleted files →
    rewrite the old index minus rows whose lineage id is deleted; the log
    entry merges old+new content trees only when no deletes occurred.
  - RefreshQuickAction (actions/RefreshQuickAction.scala:37-80): metadata-only
    — records appended/deleted lists + the new fingerprint and defers data
    handling to Hybrid Scan at query time.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import pyarrow as pa

from hyperspace_tpu.actions.create import (
    DATA_FILE_ID_COLUMN,
    CreateActionBase,
    _PrefetchReader,
)
from hyperspace_tpu.exceptions import HyperspaceError, NoChangesError
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import (
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    LogicalPlanFingerprint,
    States,
)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.io.parquet import read_table
from hyperspace_tpu.plan.nodes import Scan, ScanRelation
from hyperspace_tpu.telemetry.events import RefreshActionEvent


@dataclasses.dataclass(frozen=True)
class RefreshSummary:
    """What a refresh actually did — the return value of
    ``Hyperspace.refresh_index`` (it used to return None, leaving the
    caller to re-read the log to learn anything).  ``outcome`` is
    ``"ok"`` for a committed refresh and ``"noop"`` when the source was
    unchanged (a benign no-op, NOT an exception: the maintenance daemon
    journals it and moves on); ``version`` is the committed log id, or
    None for a no-op."""

    index: str
    mode: str              # full | incremental | quick | repair
    outcome: str           # "ok" | "noop"
    appended: int = 0      # source files the diff saw appended
    deleted: int = 0       # source files the diff saw deleted
    version: Optional[int] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class RefreshActionBase(CreateActionBase):
    transient_state = States.REFRESHING
    final_state = States.ACTIVE
    event_class = RefreshActionEvent
    # Human name of the refresh mode, for RefreshSummary / the build
    # report's properties (subclasses override).
    mode_name = "full"

    def __init__(self, log_manager: IndexLogManager, data_manager: IndexDataManager,
                 session, previous: Optional[IndexLogEntry] = None) -> None:
        # ``previous`` lets the dispatching manager hand over the stable
        # entry it already read instead of parsing the log twice.
        prev = previous if previous is not None \
            else log_manager.get_latest_stable_log()
        if prev is None:
            raise HyperspaceError("Refresh: index does not exist")
        if len(prev.relations) != 1:
            raise HyperspaceError("Refresh supports single-relation indexes")
        # Reconstruct the source plan from stored metadata
        # (RefreshActionBase.scala:71-89).
        rel_meta = session.source_provider_manager.refresh_relation_metadata(
            prev.relations[0])
        plan = Scan(ScanRelation(
            root_paths=tuple(rel_meta.root_paths),
            file_format=rel_meta.file_format,
            options=tuple(sorted(rel_meta.options.items())),
        ))
        # Layout pinned like numBuckets/lineage below: a refresh must not
        # silently rebuild a Z-ordered index lexicographic.
        config = IndexConfig(
            prev.name, prev.indexed_columns, prev.included_columns,
            layout=prev.derived_dataset.properties.get("layout",
                                                       "lexicographic"))
        super().__init__(log_manager, data_manager, session, plan, config)
        self._previous_entry = prev
        # Seed the tracker with previous ids so unchanged files keep theirs
        # (lineage soundness, FileIdTracker semantics).
        self._file_id_tracker = FileIdTracker.from_log_entry(prev)

    # numBuckets/lineage pinned to the previous entry
    # (RefreshActionBase.scala:56-64).
    @property
    def num_buckets(self) -> int:
        return self._previous_entry.num_buckets

    @property
    def lineage_enabled(self) -> bool:
        return self._previous_entry.has_lineage_column()

    # -- the diff (RefreshActionBase.scala:115-144), factored so change
    # detection can run it without constructing an action
    # (lifecycle/change_detector.diff_file_sets) ----------------------------
    def current_files(self) -> List[FileInfo]:
        return self._relation().all_files(self._file_id_tracker)

    def appended_files(self) -> List[FileInfo]:
        from hyperspace_tpu.lifecycle.change_detector import diff_file_sets

        appended, _, _ = diff_file_sets(
            self.current_files(), self._previous_entry.source_file_infos())
        return appended

    def deleted_files(self) -> List[FileInfo]:
        from hyperspace_tpu.lifecycle.change_detector import diff_file_sets

        _, deleted, _ = diff_file_sets(
            self.current_files(), self._previous_entry.source_file_infos())
        return deleted

    def validate(self) -> None:
        if self.previous_log_entry is None or \
                self.previous_log_entry.state != States.ACTIVE:
            raise HyperspaceError(
                f"Refresh is only supported in {States.ACTIVE} state")
        appended, deleted = self.appended_files(), self.deleted_files()
        self._record_diff(len(appended), len(deleted))
        if not appended and not deleted:
            raise NoChangesError("Source data is unchanged; refresh is a no-op")

    def _record_diff(self, appended: int, deleted: int) -> None:
        """The diff counts, for RefreshSummary and the build report's
        properties (re-recorded per conflict-retry attempt: the summary
        must describe the diff the WINNING attempt validated)."""
        self._diff_counts = (appended, deleted)
        self.build_report.properties.update(
            refresh_mode=self.mode_name, refresh_appended=appended,
            refresh_deleted=deleted)

    def summary(self, outcome: str) -> RefreshSummary:
        """The user-facing summary of a completed run (``outcome`` is
        what ``Action.run()`` returned)."""
        appended, deleted = getattr(self, "_diff_counts", (0, 0))
        return RefreshSummary(
            index=self.index_name, mode=self.mode_name,
            outcome="ok" if outcome == "ok" else "noop",
            appended=appended, deleted=deleted,
            version=self.base_id + 2 if outcome == "ok" else None)

    def log_entry_for_begin(self) -> IndexLogEntry:
        import copy

        return copy.deepcopy(self._previous_entry)

    def _rebase(self) -> None:
        """Conflict retry: diff and merge against the stable entry the
        WINNING writer committed, not the one captured at construction —
        or the retry would re-index files the winner already covered and
        merge against a superseded content tree (the lost-update shape
        the transaction loop exists to prevent)."""
        super()._rebase()
        stable = self.log_manager.get_latest_stable_log()
        if stable is not None:
            self._previous_entry = stable
            self._file_id_tracker = FileIdTracker.from_log_entry(stable)


class RefreshAction(RefreshActionBase):
    """Full rebuild (RefreshAction.scala:33-59)."""

    def op(self) -> None:
        self._build_index_data()

    def log_entry(self) -> IndexLogEntry:
        return self._build_log_entry()


class RefreshIncrementalAction(RefreshActionBase):
    """Index only what changed (RefreshIncrementalAction.scala:54-145)."""

    mode_name = "incremental"

    def validate(self) -> None:
        super().validate()
        if self.deleted_files() and not self.lineage_enabled:
            # Deleted-row exclusion needs the lineage column
            # (RefreshIncrementalAction.scala:44-52).
            raise HyperspaceError(
                "Refreshing an index incrementally with deleted source files "
                "requires lineage (hyperspace.index.lineage.enabled=true at "
                "creation time)")

    def op(self) -> None:
        appended = self.appended_files()
        deleted = self.deleted_files()
        resolved = self._resolved_config()
        parts: List[pa.Table] = []
        if deleted:
            # Rewrite the old index excluding rows from deleted files
            # (RefreshIncrementalAction.scala:70-97).
            old_files = [f.name for f in self._previous_entry.content.file_infos()]
            old = read_table(old_files, "parquet")
            deleted_ids = pa.array(sorted({f.id for f in deleted}),
                                   type=old.schema.field(DATA_FILE_ID_COLUMN).type)
            import pyarrow.compute as pc

            keep = pc.invert(pc.is_in(old.column(DATA_FILE_ID_COLUMN),
                                      value_set=deleted_ids))
            parts.append(old.filter(keep))
        if appended:
            # Appended-file decode rides the same bounded prefetch as
            # the create pipeline (decode of file N+1 overlaps the
            # concat/normalize of file N; depth bounds peak RSS), and
            # _read_chunk also applies the schema-evolution null fill
            # and lineage stamping the full build gets.
            relation = self._relation()
            depth = max(1, int(self.conf.build_prefetch_depth)) \
                if getattr(self.conf, "build_pipeline_enabled", True) else 0
            reader = _PrefetchReader(self, appended, resolved.all_columns,
                                     relation, self.lineage_enabled, depth)
            try:
                parts.extend(reader)
            finally:
                reader.close()
        if not parts:
            raise NoChangesError("Nothing to refresh")
        combined = pa.concat_tables(parts, promote_options="default")
        self._write_table_bucketed(combined, resolved)
        self._had_deletes = bool(deleted)

    def log_entry(self) -> IndexLogEntry:
        entry = self._build_log_entry()
        if not self._had_deletes:
            # Old index files remain valid: merge content trees
            # (RefreshIncrementalAction.scala:130-145 / Directory.merge).
            entry.content = self._previous_entry.content.merge(entry.content)
        return entry


class RefreshQuickAction(RefreshActionBase):
    """Metadata-only refresh (RefreshQuickAction.scala:37-80)."""

    mode_name = "quick"

    def op(self) -> None:
        pass  # log-only

    def log_entry(self) -> IndexLogEntry:
        fingerprint = LogicalPlanFingerprint([self._signature()])
        return self._previous_entry.copy_with_update(
            fingerprint, self.appended_files(), self.deleted_files())
