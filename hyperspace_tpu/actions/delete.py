"""DeleteAction: metadata-only soft delete, ACTIVE → DELETED.

Reference contract: actions/DeleteAction.scala:24-48 — validate requires the
index to be ACTIVE; ``op()`` is a no-op (index data is kept for restore);
the final entry is the previous one with state DELETED.
"""

from __future__ import annotations

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.telemetry.events import DeleteActionEvent


class DeleteAction(Action):
    transient_state = States.DELETING
    final_state = States.DELETED
    event_class = DeleteActionEvent

    def validate(self) -> None:
        if self.previous_log_entry is None or self.previous_log_entry.state != States.ACTIVE:
            raise HyperspaceError(
                f"Delete is only supported in {States.ACTIVE} state; index is "
                f"{'missing' if self.previous_log_entry is None else self.previous_log_entry.state}")

    def op(self) -> None:
        pass

    def log_entry(self) -> IndexLogEntry:
        return self.log_entry_for_begin()
