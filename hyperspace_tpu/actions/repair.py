"""RepairAction: rebuild ONLY the quarantined buckets of an index.

The self-heal half of the integrity loop (docs/15-integrity.md).  After
scrub/containment has quarantined damaged index data files
(index/quarantine.py), ``refresh_index(name, mode="repair")`` re-derives
exactly those buckets' rows from the RECORDED source snapshot and
commits a new entry whose content keeps every healthy file and swaps the
damaged buckets for fresh ones — an optimize-shaped, index-only commit,
not a full rebuild.  Afterwards the quarantine records the repair made
obsolete are cleared, so the next query serves entirely from the index
again.

Soundness hinges on the snapshot check in validate(): a repaired bucket
must hold the rows the ORIGINAL build put there, so every recorded
source file must still exist with its recorded (size, mtime).  Source
that drifted since indexing is a refresh problem, not a repair problem —
validate says so explicitly.  Bucket membership is recomputed with the
build kernel's bit-identical host mirror (ops/hash.bucket_ids_np), so a
repaired bucket can never capture a different row set than the build
assigned.
"""

from __future__ import annotations

import copy
import os
from typing import List, Optional

import numpy as np
import pyarrow as pa

from hyperspace_tpu.actions.refresh import RefreshActionBase
from hyperspace_tpu.exceptions import HyperspaceError, NoChangesError
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import Content, FileInfo, IndexLogEntry, States
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.index.quarantine import QuarantineManager, quarantine_manager_for
from hyperspace_tpu.io import columnar, integrity
from hyperspace_tpu.io.parquet import (
    bucket_id_of_file,
    sort_permutation_host,
    write_bucket_run,
    write_zorder_run,
)
from hyperspace_tpu.ops.hash import bucket_ids_np
from hyperspace_tpu.telemetry.events import RefreshActionEvent


class RepairAction(RefreshActionBase):
    """Partial rebuild of the quarantined buckets; REFRESHING transient
    state (it is a refresh mode), ACTIVE final state."""

    transient_state = States.REFRESHING
    final_state = States.ACTIVE
    event_class = RefreshActionEvent
    mode_name = "repair"

    def __init__(self, log_manager: IndexLogManager,
                 data_manager: IndexDataManager, session,
                 previous: Optional[IndexLogEntry] = None,
                 quarantine: Optional[QuarantineManager] = None) -> None:
        super().__init__(log_manager, data_manager, session, previous)
        self.quarantine = quarantine if quarantine is not None \
            else quarantine_manager_for(session.conf, data_manager.index_path)
        self._new_files: List[str] = []
        self._retained: List[FileInfo] = []
        self._target_buckets: tuple = ()

    # -- validation ----------------------------------------------------------
    def validate(self) -> None:
        if self.previous_log_entry is None or \
                self.previous_log_entry.state != States.ACTIVE:
            raise HyperspaceError(
                f"Repair is only supported in {States.ACTIVE} state")
        entry = self._previous_entry
        if not entry.is_covering:
            raise HyperspaceError(
                "Repair applies to covering indexes; rebuild a "
                "data-skipping index with refresh_index(mode='full')")
        qpaths = self.quarantine.paths()
        flagged = [f for f in entry.content.file_infos()
                   if f.name in qpaths]
        if not flagged:
            raise NoChangesError(
                "no quarantined index files; nothing to repair")
        buckets = {bucket_id_of_file(f.name) for f in flagged}
        if None in buckets:
            raise HyperspaceError(
                "cannot map a quarantined file to its bucket; run "
                "refresh_index(mode='full') instead")
        # The rebuilt buckets must reproduce the INDEXED snapshot, so the
        # snapshot must still be on disk, byte for byte by (size, mtime).
        for f in entry.source_file_infos():
            try:
                st = os.stat(f.name)
            except OSError:
                raise HyperspaceError(
                    f"repair needs the indexed source snapshot, but "
                    f"{f.name!r} is gone; run refresh_index instead")
            if st.st_size != f.size or int(st.st_mtime_ns) != f.mtime:
                raise HyperspaceError(
                    f"source file {f.name!r} changed since indexing; "
                    f"repair would mix snapshots — run refresh_index "
                    f"(mode='full' or 'incremental') instead")
        self._target_buckets = tuple(sorted(buckets))

    # -- the partial rebuild -------------------------------------------------
    def op(self) -> None:
        integrity.configure_from_conf(self.conf)
        entry = self._previous_entry
        resolved = self._resolved_config()
        relation = self._relation()
        lineage = self.lineage_enabled
        columns = resolved.all_columns
        affected = set(self._target_buckets)
        self._retained = [f for f in entry.content.file_infos()
                          if bucket_id_of_file(f.name) not in affected]
        # The recorded snapshot, read through the build's own chunk
        # reader (schema normalization + lineage ids identical to
        # create/refresh).  Monolithic read: repair is bounded by the
        # damaged buckets' share of the source, and runs off the query
        # path — the streaming spill machinery would buy nothing here.
        table = pa.concat_tables(
            [self._read_chunk(f, columns, relation, lineage)
             for f in entry.source_file_infos()],
            promote_options="default")
        word_cols = [np.asarray(columnar.to_hash_words(table.column(c)))
                     for c in resolved.indexed_columns]
        row_buckets = bucket_ids_np(word_cols, self.num_buckets)
        mask = np.isin(row_buckets,
                       np.asarray(self._target_buckets,
                                  dtype=row_buckets.dtype))
        sub = table.filter(pa.array(mask))
        sub_buckets = row_buckets[mask]
        order = np.argsort(sub_buckets, kind="stable")
        routed = sub.take(pa.array(order))
        sorted_buckets = sub_buckets[order]

        version = self.data_manager.get_next_version()
        out_dir = self.data_manager.version_path(version)
        os.makedirs(out_dir, exist_ok=True)
        max_rows = self.conf.index_max_rows_per_file
        compression = self.conf.index_file_compression
        layout = resolved.layout
        new_files: List[str] = []
        starts = np.searchsorted(sorted_buckets, self._target_buckets, "left")
        ends = np.searchsorted(sorted_buckets, self._target_buckets, "right")
        import time as _time

        t0 = _time.perf_counter()
        for b, lo, hi in zip(self._target_buckets, starts, ends):
            rows = int(hi - lo)
            if rows == 0:
                continue
            bt = routed.slice(int(lo), rows)
            if layout == "zorder":
                new_files.extend(write_zorder_run(
                    bt, int(b), out_dir, max_rows,
                    resolved.indexed_columns, compression=compression))
            else:
                perm = sort_permutation_host(bt, resolved.indexed_columns,
                                             layout)
                bt = bt.take(pa.array(perm))
                new_files.extend(write_bucket_run(
                    bt, int(b), out_dir, max_rows, compression=compression))
        self._phase("write_s", _time.perf_counter() - t0)
        self.build_report.add_bytes(
            written=sum(os.stat(p).st_size for p in new_files),
            files=len(new_files))
        # Per-file min/max sketch for the new version dir, like every
        # build/compaction — repaired buckets keep pruning effective.
        from hyperspace_tpu.actions.data_skipping import write_index_file_sketch

        write_index_file_sketch(out_dir, resolved.indexed_columns)
        self._written_version = version
        self._new_files = new_files

    def log_entry(self) -> IndexLogEntry:
        entry = copy.deepcopy(self._previous_entry)
        new_infos = []
        for path in self._new_files:
            st = os.stat(path)
            new_infos.append(FileInfo(path, st.st_size, int(st.st_mtime_ns),
                                      -1, integrity.recorded_digest(path)))
        entry.content = Content.from_leaf_files(self._retained + new_infos)
        return entry

    def run(self) -> None:
        super().run()
        # Commit succeeded (or no-opped): clear every quarantine record
        # the current entry no longer references — the repaired files for
        # a real run, stale leftovers for a no-op.  Records still naming
        # a referenced file (shouldn't exist after a successful repair)
        # are deliberately kept.
        latest = self.log_manager.get_latest_stable_log()
        referenced = {f.name for f in latest.content.file_infos()} \
            if latest is not None else set()
        for path in self.quarantine.paths():
            if path not in referenced:
                self.quarantine.remove(path)
