"""OptimizeAction: compact small index files bucket-wise.

Reference contract: actions/OptimizeAction.scala:46-175 —
  - mode "quick": only files below ``optimizeFileSizeThreshold`` (256 MB
    default, IndexConstants.scala:91-92) are compaction candidates; mode
    "full": every file (:70-83);
  - buckets with a single candidate file are skipped — nothing to merge
    (:115-133, using the bucket id recovered from the file name);
  - ``op()`` reads each bucket's candidate files, merges them sorted, and
    writes one file per bucket into a new version dir (:85-99);
  - the committed entry's content keeps non-optimized files and swaps the
    merged ones (:139-170); the source snapshot/fingerprint are untouched —
    this is an index-only operation.
"""

from __future__ import annotations

import copy
import dataclasses
import os
from collections import defaultdict
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError, NoChangesError
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import (
    Content,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    States,
)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.io.parquet import (
    bucket_id_of_file,
    read_parquet_file,
    sort_permutation_host,
    write_bucket_run,
)
from hyperspace_tpu.telemetry.events import OptimizeActionEvent


@dataclasses.dataclass(frozen=True)
class OptimizeSummary:
    """What an optimize actually did — the return value of
    ``Hyperspace.optimize_index`` (it used to return None, leaving the
    caller to re-read the log to count the compaction).  ``outcome`` is
    ``"ok"`` for a committed compaction and ``"noop"`` when no bucket
    held mergeable files; ``version`` is the committed log id, or None
    for a no-op."""

    index: str
    mode: str                   # quick | full
    outcome: str                # "ok" | "noop"
    compacted_files: int = 0    # small files merged away
    compacted_buckets: int = 0  # buckets rewritten
    written_files: int = 0      # files the merge produced
    version: Optional[int] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class OptimizeAction(Action):
    transient_state = States.OPTIMIZING
    final_state = States.ACTIVE
    event_class = OptimizeActionEvent

    def __init__(self, log_manager: IndexLogManager, data_manager: IndexDataManager,
                 session, mode: str = "quick") -> None:
        super().__init__(log_manager)
        self.data_manager = data_manager
        self.session = session
        self.mode = mode
        self._new_files: List[str] = []
        self._retained: List[FileInfo] = []

    def _candidates(self) -> Dict[int, List[FileInfo]]:
        """Bucket → files worth merging (OptimizeAction.scala:115-133).
        Memoized: validate() and op() both need it, and the convergence
        check reads Parquet footers."""
        if getattr(self, "_candidates_cache", None) is not None:
            return self._candidates_cache
        entry = self.previous_log_entry
        threshold = self.session.conf.optimize_file_size_threshold
        by_bucket: Dict[int, List[FileInfo]] = defaultdict(list)
        retained: List[FileInfo] = []
        for f in entry.content.file_infos():
            bucket = bucket_id_of_file(f.name)
            if bucket is None or (self.mode == "quick" and f.size >= threshold):
                retained.append(f)
            else:
                by_bucket[bucket].append(f)
        max_rows = self.session.conf.index_max_rows_per_file
        mergeable: Dict[int, List[FileInfo]] = {}
        for b, fs in by_bucket.items():
            if max_rows > 0:
                # With the file-size knob: rewrite when the bucket has more
                # files than its minimal ceil(rows/max_rows) count OR any
                # file exceeds the (possibly lowered) knob — and converge
                # once both hold (re-merging an optimal bucket forever
                # would churn a version per run).
                per_file = [pq.ParquetFile(f.name).metadata.num_rows
                            for f in fs]
                minimal = -(-sum(per_file) // max_rows)
                worth_merging = (len(fs) > minimal
                                 or any(r > max_rows for r in per_file))
            else:
                worth_merging = len(fs) > 1
            if worth_merging:
                mergeable[b] = fs
            else:
                retained.extend(fs)
        self._retained = retained
        self._candidates_cache = mergeable
        return mergeable

    def validate(self) -> None:
        if self.previous_log_entry is None or \
                self.previous_log_entry.state != States.ACTIVE:
            raise HyperspaceError(
                f"Optimize is only supported in {States.ACTIVE} state")
        if not self.previous_log_entry.is_covering:
            # A data-skipping sketch is one small file per version; there is
            # nothing to compact.
            raise HyperspaceError(
                "Optimize applies to covering indexes only")
        if not self._candidates():
            raise NoChangesError(
                "No index files eligible for optimization (every bucket has "
                "a single file or files exceed the size threshold)")

    def op(self) -> None:
        import time as _time

        from hyperspace_tpu.io import integrity

        integrity.configure_from_conf(self.session.conf)
        entry = self.previous_log_entry
        mergeable = self._candidates()
        version = self.data_manager.get_next_version()
        out_dir = self.data_manager.version_path(version)
        os.makedirs(out_dir, exist_ok=True)
        sort_cols = entry.indexed_columns
        max_rows = self.session.conf.index_max_rows_per_file
        layout = entry.derived_dataset.properties.get("layout",
                                                      "lexicographic")
        report = self.build_report
        for bucket, files in sorted(mergeable.items()):
            t0 = _time.perf_counter()
            merged = pa.concat_tables(
                [read_parquet_file(f.name) for f in files],
                promote_options="default")
            report.add_phase("read", _time.perf_counter() - t0)
            report.add_bytes(read=merged.nbytes)
            # Layout-aware: a Z-ordered index must stay Z-ordered through
            # compaction — Morton sort AND Z-cell-aligned file cuts — or its
            # per-file sketches go wide on every non-primary dimension.
            t0 = _time.perf_counter()
            if layout == "zorder":
                from hyperspace_tpu.io.parquet import write_zorder_run

                new = write_zorder_run(merged, bucket, out_dir, max_rows,
                                       sort_cols,
                                       compression=self.session.conf
                                       .index_file_compression)
                self._new_files.extend(new)
                report.add_phase("write", _time.perf_counter() - t0)
                report.add_bytes(
                    written=sum(os.stat(p).st_size for p in new),
                    files=len(new))
                continue
            perm = sort_permutation_host(merged, sort_cols, layout)
            merged = merged.take(pa.array(perm))
            report.add_phase("sort", _time.perf_counter() - t0)
            # Honor the file-size knob: collapsing a bucket to ONE file
            # would destroy the per-file sketch pruning granularity the
            # split exists for.
            t0 = _time.perf_counter()
            new = write_bucket_run(merged, bucket, out_dir, max_rows,
                                   compression=self.session.conf
                                   .index_file_compression)
            self._new_files.extend(new)
            report.add_phase("write", _time.perf_counter() - t0)
            report.add_bytes(written=sum(os.stat(p).st_size for p in new),
                             files=len(new))
        # Per-file min/max sketch for the compacted version, like every
        # build writes — keeps FilterIndexRule's file pruning effective on
        # optimized indexes.
        from hyperspace_tpu.actions.data_skipping import write_index_file_sketch

        t0 = _time.perf_counter()
        write_index_file_sketch(out_dir, sort_cols)
        report.add_phase("sketch", _time.perf_counter() - t0)

    def log_entry(self) -> IndexLogEntry:
        from hyperspace_tpu.io import integrity

        entry = copy.deepcopy(self.previous_log_entry)
        tracker = FileIdTracker()
        new_infos = []
        for path in self._new_files:
            st = os.stat(path)
            # Compacted files carry the digest recorded as they were
            # written (write_bucket_run); retained files keep the digests
            # their own build committed.
            new_infos.append(FileInfo(path, st.st_size, int(st.st_mtime_ns),
                                      -1, integrity.recorded_digest(path)))
        entry.content = Content.from_leaf_files(self._retained + new_infos)
        return entry

    def summary(self, outcome: str) -> OptimizeSummary:
        """The user-facing summary of a completed run (``outcome`` is
        what ``Action.run()`` returned)."""
        mergeable = getattr(self, "_candidates_cache", None) or {}
        return OptimizeSummary(
            index=self.index_name, mode=self.mode,
            outcome="ok" if outcome == "ok" else "noop",
            compacted_files=sum(len(fs) for fs in mergeable.values()),
            compacted_buckets=len(mergeable),
            written_files=len(self._new_files),
            version=self.base_id + 2 if outcome == "ok" else None)
