"""OptimizeAction: compact small index files bucket-wise.

Reference contract: actions/OptimizeAction.scala:46-175 —
  - mode "quick": only files below ``optimizeFileSizeThreshold`` (256 MB
    default, IndexConstants.scala:91-92) are compaction candidates; mode
    "full": every file (:70-83);
  - buckets with a single candidate file are skipped — nothing to merge
    (:115-133, using the bucket id recovered from the file name);
  - ``op()`` reads each bucket's candidate files, merges them sorted, and
    writes one file per bucket into a new version dir (:85-99);
  - the committed entry's content keeps non-optimized files and swaps the
    merged ones (:139-170); the source snapshot/fingerprint are untouched —
    this is an index-only operation.
"""

from __future__ import annotations

import copy
import os
from collections import defaultdict
from typing import Dict, List

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError, NoChangesError
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import (
    Content,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    States,
)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.io import columnar
from hyperspace_tpu.io.parquet import bucket_file_name, bucket_id_of_file
from hyperspace_tpu.telemetry.events import OptimizeActionEvent


class OptimizeAction(Action):
    transient_state = States.OPTIMIZING
    final_state = States.ACTIVE
    event_class = OptimizeActionEvent

    def __init__(self, log_manager: IndexLogManager, data_manager: IndexDataManager,
                 session, mode: str = "quick") -> None:
        super().__init__(log_manager)
        self.data_manager = data_manager
        self.session = session
        self.mode = mode
        self._new_files: List[str] = []
        self._retained: List[FileInfo] = []

    def _candidates(self) -> Dict[int, List[FileInfo]]:
        """Bucket → files worth merging (OptimizeAction.scala:115-133)."""
        entry = self.previous_log_entry
        threshold = self.session.conf.optimize_file_size_threshold
        by_bucket: Dict[int, List[FileInfo]] = defaultdict(list)
        retained: List[FileInfo] = []
        for f in entry.content.file_infos():
            bucket = bucket_id_of_file(f.name)
            if bucket is None or (self.mode == "quick" and f.size >= threshold):
                retained.append(f)
            else:
                by_bucket[bucket].append(f)
        mergeable = {b: fs for b, fs in by_bucket.items() if len(fs) > 1}
        for b, fs in by_bucket.items():
            if len(fs) <= 1:
                retained.extend(fs)
        self._retained = retained
        return mergeable

    def validate(self) -> None:
        if self.previous_log_entry is None or \
                self.previous_log_entry.state != States.ACTIVE:
            raise HyperspaceError(
                f"Optimize is only supported in {States.ACTIVE} state")
        if not self.previous_log_entry.is_covering:
            # A data-skipping sketch is one small file per version; there is
            # nothing to compact.
            raise HyperspaceError(
                "Optimize applies to covering indexes only")
        if not self._candidates():
            raise NoChangesError(
                "No index files eligible for optimization (every bucket has "
                "a single file or files exceed the size threshold)")

    def op(self) -> None:
        entry = self.previous_log_entry
        mergeable = self._candidates()
        version = self.data_manager.get_next_version()
        out_dir = self.data_manager.version_path(version)
        os.makedirs(out_dir, exist_ok=True)
        sort_cols = entry.indexed_columns
        for bucket, files in sorted(mergeable.items()):
            merged = pa.concat_tables(
                [pq.read_table(f.name) for f in files], promote_options="default")
            keys = [columnar.to_order_key(merged.column(c)) for c in sort_cols]
            perm = np.lexsort(tuple(reversed(keys)))
            merged = merged.take(pa.array(perm))
            path = os.path.join(out_dir, bucket_file_name(bucket))
            pq.write_table(merged, path)
            self._new_files.append(path)
        # Per-file min/max sketch for the compacted version, like every
        # build writes — keeps FilterIndexRule's file pruning effective on
        # optimized indexes.
        from hyperspace_tpu.actions.data_skipping import write_index_file_sketch

        write_index_file_sketch(out_dir, sort_cols)

    def log_entry(self) -> IndexLogEntry:
        entry = copy.deepcopy(self.previous_log_entry)
        tracker = FileIdTracker()
        new_infos = []
        for path in self._new_files:
            st = os.stat(path)
            new_infos.append(FileInfo(path, st.st_size, int(st.st_mtime_ns), -1))
        entry.content = Content.from_leaf_files(self._retained + new_infos)
        return entry
