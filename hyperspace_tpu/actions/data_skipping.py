"""Data-skipping index actions: build and refresh per-file sketches.

A data-skipping index stores one row per source data file with min/max (and
row/null counts) for each sketched column, persisted as a single Parquet
sketch file under the index's ``v__=N`` directory.  The query rule
(rules/data_skipping.py) intersects predicates with the per-file intervals
and shrinks the scan's file list — no source data is copied or rewritten.

Capability beyond the reference snapshot (its v0.5 has only the covering
index; ROADMAP.md:92-94 plans "more index types"); lifecycle plumbing (log
states, versioned data dirs, signatures) is shared with the covering-index
actions so every other subsystem treats both kinds uniformly.
"""

from __future__ import annotations

import os
import uuid
from typing import Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

from hyperspace_tpu.actions.create import CreateActionBase
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.index_config import DataSkippingIndexConfig
from hyperspace_tpu.index.log_entry import (
    Content,
    DataSkippingIndex,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Source,
    States,
)
from hyperspace_tpu.io.parquet import read_table
from hyperspace_tpu.telemetry.events import CreateActionEvent
from hyperspace_tpu.utils.resolver import resolve_or_raise

# Sketch-table metadata columns (underscored like the lineage column).
SKETCH_FILE_NAME = "_ds_file_name"
SKETCH_FILE_SIZE = "_ds_file_size"
SKETCH_FILE_MTIME = "_ds_file_mtime"
SKETCH_ROW_COUNT = "_ds_row_count"


def _min_col(c: str) -> str:
    return f"min__{c}"


def _max_col(c: str) -> str:
    return f"max__{c}"


def _null_col(c: str) -> str:
    return f"nulls__{c}"


def _values_col(c: str) -> str:
    return f"values__{c}"


def _bloom_col(c: str) -> str:
    return f"bloom__{c}"


VALUE_LIST_MAX = 64  # beyond this, the list is null and min/max governs
BLOOM_BITS = 8192    # 1 KiB per file per column: ~0.3% false
# positives at 500 distincts with 4 hashes
BLOOM_HASHES = 4


def bloom_positions(values_array) -> "np.ndarray":
    """Bit positions for each value of an arrow array — shared by build and
    probe so membership can never false-negative.  Double hashing over the
    engine's canonical hash words (io/columnar.to_hash_words), which already
    makes equal VALUES hash equal across chunking/encodings."""
    import numpy as np

    from hyperspace_tpu.io.columnar import to_hash_words

    words = np.asarray(to_hash_words(values_array), dtype=np.uint64)
    h1, h2 = words[:, 0], words[:, 1] | np.uint64(1)  # odd step
    i = np.arange(BLOOM_HASHES, dtype=np.uint64)[:, None]
    return ((h1[None, :] + i * h2[None, :]) % np.uint64(BLOOM_BITS)).T


def _bloom_bytes(col) -> Optional[bytes]:
    """Bloom filter over the column's distinct non-null values."""
    import numpy as np

    if col is None:
        return None
    vals = pc.unique(col).drop_null()
    bits = np.zeros(BLOOM_BITS, dtype=bool)
    if len(vals):
        bits[bloom_positions(vals).ravel()] = True
    return np.packbits(bits).tobytes()


def bloom_may_contain(bloom: bytes, probe_positions) -> bool:
    """True when every hash position of SOME probe value is set."""
    import numpy as np

    bits = np.unpackbits(np.frombuffer(bloom, dtype=np.uint8)).astype(bool)
    return bool(np.all(bits[probe_positions], axis=1).any())


def _sketch_from_parquet_footer(path: str,
                                columns: Sequence[str]) -> Optional[Dict]:
    """min/max/null counts from the Parquet footer's row-group statistics —
    O(footer) instead of O(data).  None when any sketched column lacks
    statistics in any row group (caller falls back to a full read)."""
    md = pq.ParquetFile(path).metadata
    name_to_ix = {md.schema.column(i).name: i for i in range(md.num_columns)}
    out: Dict = {SKETCH_ROW_COUNT: md.num_rows}
    for c in columns:
        ix = name_to_ix.get(c)
        if ix is None:
            out[_min_col(c)] = None
            out[_max_col(c)] = None
            out[_null_col(c)] = md.num_rows
            continue
        mins, maxs, nulls = [], [], 0
        for rg in range(md.num_row_groups):
            stats = md.row_group(rg).column(ix).statistics
            if stats is None or not stats.has_min_max \
                    or stats.null_count is None:
                return None
            nulls += stats.null_count
            if md.row_group(rg).num_rows > stats.null_count:
                mins.append(stats.min)
                maxs.append(stats.max)
        out[_min_col(c)] = min(mins) if mins else None
        out[_max_col(c)] = max(maxs) if maxs else None
        out[_null_col(c)] = nulls
    return out


def sketch_rows_for_files(files: Sequence[FileInfo], columns: Sequence[str],
                          read_format: str,
                          options: Dict[str, str],
                          partition_roots: Optional[Sequence[str]] = None,
                          sketch_types: Optional[Sequence[str]] = None
                          ) -> List[Dict]:
    """One sketch row per file: min/max/null-count per sketched column.
    Parquet files are sketched from footer statistics when available.
    Hive partition columns (constant per file, absent from the data) sketch
    as min == max == the path value.  Columns whose sketch type is
    "ValueList" additionally record their distinct values when there are at
    most VALUE_LIST_MAX of them (reading just that column)."""
    types = list(sketch_types) if sketch_types is not None \
        else ["MinMax"] * len(columns)
    value_list_cols = [c for c, t in zip(columns, types) if t == "ValueList"]
    bloom_cols = [c for c, t in zip(columns, types) if t == "BloomFilter"]
    from hyperspace_tpu.io.partitions import (
        partition_spec_for_roots,
        partition_values,
        typed_value,
    )

    spec = partition_spec_for_roots(partition_roots) \
        if partition_roots else {}

    def sketch_one(f: FileInfo) -> Dict:
        row: Dict = {
            SKETCH_FILE_NAME: f.name,
            SKETCH_FILE_SIZE: f.size,
            SKETCH_FILE_MTIME: f.mtime,
        }
        stats = _sketch_from_parquet_footer(
            f.name, [c for c in columns if c not in spec]) \
            if read_format == "parquet" else None
        if stats is not None:
            raw = partition_values(f.name, partition_roots or [])
            for c in columns:
                if c in spec:
                    value = typed_value(raw.get(c), spec[c])
                    stats[_min_col(c)] = value
                    stats[_max_col(c)] = value
                    stats[_null_col(c)] = stats[SKETCH_ROW_COUNT] \
                        if value is None else 0
            row.update(stats)
            _add_data_sketches(row, f, value_list_cols, bloom_cols,
                               read_format, options, partition_roots, spec)
            return row
        t = read_table([f.name], read_format, list(columns), options,
                       partition_roots=partition_roots, partition_spec=spec)
        row[SKETCH_ROW_COUNT] = t.num_rows
        for c in columns:
            col = t.column(c) if c in t.column_names else None
            if col is None or col.null_count == len(col) or t.num_rows == 0:
                row[_min_col(c)] = None
                row[_max_col(c)] = None
                row[_null_col(c)] = t.num_rows
            else:
                mm = pc.min_max(col)
                row[_min_col(c)] = mm["min"].as_py()
                row[_max_col(c)] = mm["max"].as_py()
                row[_null_col(c)] = col.null_count
        _fill_data_sketches(row, t, value_list_cols, bloom_cols)
        return row

    from hyperspace_tpu.utils.parallel_map import parallel_map_ordered

    # Low worker cap: the non-parquet fallback materializes a full table per
    # in-flight file, so concurrency multiplies peak memory.
    return parallel_map_ordered(sketch_one, list(files), max_workers=4)


def _distinct_or_none(col) -> Optional[List]:
    """Sorted distinct non-null values, or None when absent/too many."""
    if col is None:
        return None
    vals = pc.unique(col).drop_null()
    if len(vals) > VALUE_LIST_MAX:
        return None
    return sorted(vals.to_pylist())


def _fill_data_sketches(row: Dict, t, value_list_cols: Sequence[str],
                        bloom_cols: Sequence[str]) -> None:
    """One home for the data-reading sketch families (ValueList, Bloom)."""
    for c in value_list_cols:
        col = t.column(c) if c in t.column_names else None
        row[_values_col(c)] = _distinct_or_none(col)
    for c in bloom_cols:
        col = t.column(c) if c in t.column_names else None
        row[_bloom_col(c)] = _bloom_bytes(col)


def _add_data_sketches(row: Dict, f: FileInfo,
                       value_list_cols: Sequence[str],
                       bloom_cols: Sequence[str],
                       read_format: str, options: Dict[str, str],
                       partition_roots, spec) -> None:
    wanted = list(value_list_cols) + list(bloom_cols)
    if not wanted:
        return
    t = read_table([f.name], read_format, wanted, options,
                   partition_roots=partition_roots, partition_spec=spec)
    _fill_data_sketches(row, t, value_list_cols, bloom_cols)


def write_index_file_sketch(out_dir: str, columns: Sequence[str]) -> None:
    """Per-index-file min/max sketch (``_sketch.parquet``) for a version
    directory of bucket files — shared by create/refresh builds and
    optimize compaction so the format can never drift between them."""
    from hyperspace_tpu.io.files import list_data_files

    files = list_data_files([out_dir], extension=".parquet")
    if not files:
        return
    rows = sketch_rows_for_files(files, columns, "parquet", {})
    pq.write_table(pa.Table.from_pylist(rows),
                   os.path.join(out_dir, "_sketch.parquet"))


def write_sketch(rows: List[Dict], out_dir: str) -> str:
    from hyperspace_tpu.io import integrity

    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"sketch-{uuid.uuid4().hex[:12]}.parquet")
    pq.write_table(pa.Table.from_pylist(rows), path)
    # Sketches are the data-skipping index's DATA: digest them like
    # bucket files so verify_index covers both index kinds.
    integrity.record_file(path)
    return path


def read_sketch(entry: IndexLogEntry) -> pa.Table:
    files = [f.name for f in entry.content.file_infos()]
    if not files:
        return pa.table({})
    from hyperspace_tpu.io.parquet import read_parquet_file

    return pa.concat_tables([read_parquet_file(p) for p in files],
                            promote_options="default")


class CreateDataSkippingAction(CreateActionBase):
    transient_state = States.CREATING
    final_state = States.ACTIVE
    event_class = CreateActionEvent

    # -- config resolution (sketched columns, not indexed/included) --------
    def _resolved_config(self) -> DataSkippingIndexConfig:
        schema = self._relation().schema()
        sketched = resolve_or_raise(self.config.sketched_columns, schema,
                                    "sketched column")
        return DataSkippingIndexConfig(self.config.index_name, sketched,
                                       self.config.sketch_types)

    def validate(self) -> None:
        if self.previous_log_entry is not None and \
                self.previous_log_entry.state not in (States.DOESNOTEXIST,):
            raise HyperspaceError(
                f"Another index with name {self.config.index_name!r} already "
                f"exists in state {self.previous_log_entry.state}")
        leaves = self.plan.leaf_relations()
        if len(leaves) != 1 or not \
                self.session.source_provider_manager.is_supported_relation(leaves[0]):
            raise HyperspaceError("Only plans over one supported file-based "
                                  "relation can be indexed")
        self._resolved_config()

    # -- build -------------------------------------------------------------
    def _build_sketch(self, file_names: Optional[List[str]] = None,
                      carry_rows: Optional[List[Dict]] = None) -> None:
        relation = self._relation()
        resolved = self._resolved_config()
        files = relation.all_files(self._file_id_tracker)
        if file_names is not None:
            wanted = set(file_names)
            files = [f for f in files if f.name in wanted]
        rows = list(carry_rows or [])
        rows.extend(sketch_rows_for_files(
            files, resolved.sketched_columns, relation.read_format,
            relation.options, partition_roots=relation.root_paths,
            sketch_types=resolved.sketch_types))
        if not rows:
            raise HyperspaceError("No source data files to sketch")
        version = self.data_manager.get_next_version()
        write_sketch(rows, self.data_manager.version_path(version))
        self._written_version = version
        schema = self._relation().schema()
        self._index_schema = {c: schema[c] for c in resolved.sketched_columns
                              if c in schema}

    def _derived_dataset(self) -> DataSkippingIndex:
        resolved = self._resolved_config()
        return DataSkippingIndex(
            sketched_columns=resolved.sketched_columns,
            sketch_types=list(resolved.sketch_types),
            schema=getattr(self, "_index_schema", {}),
        )

    def log_entry_for_begin(self) -> IndexLogEntry:
        relation = self._relation()
        rel_meta = relation.create_relation_metadata(FileIdTracker())
        return IndexLogEntry(
            name=self.config.index_name,
            derived_dataset=self._derived_dataset(),
            content=Content.from_leaf_files([]) or Content.from_directory(
                self.data_manager.index_path, FileIdTracker()),
            source=Source(relations=[rel_meta],
                          fingerprint=LogicalPlanFingerprint([self._signature()])),
        )

    def op(self) -> None:
        self._build_sketch()

    def log_entry(self) -> IndexLogEntry:
        relation = self._relation()
        rel_meta = relation.create_relation_metadata(self._file_id_tracker)
        # Refresh carries the previous entry's properties forward so
        # provider-accumulated state (e.g. the deltaVersions history)
        # survives — same contract as the covering _build_log_entry.
        prev = getattr(self, "_previous_entry", None)
        properties: Dict[str, str] = dict(prev.properties) \
            if prev is not None else {}
        properties["lineage"] = "false"
        properties["indexLogVersion"] = str(self.base_id + 2)
        properties = self.session.source_provider_manager.enrich_index_properties(
            rel_meta, properties)
        content = Content.from_directory(
            self.data_manager.version_path(self._written_version), FileIdTracker())
        return IndexLogEntry(
            name=self.config.index_name,
            derived_dataset=self._derived_dataset(),
            content=content,
            source=Source(relations=[rel_meta],
                          fingerprint=LogicalPlanFingerprint([self._signature()])),
            properties=properties,
        )


class RefreshDataSkippingAction(CreateDataSkippingAction):
    """Refresh a data-skipping sketch: re-sketch appended files, drop rows
    for deleted files, carry everything else forward unchanged.  One action
    serves full and incremental modes — per-file sketches make incremental
    the natural implementation (re-sketching unchanged files would produce
    identical rows)."""

    transient_state = States.REFRESHING

    def __init__(self, log_manager, data_manager, session,
                 previous: Optional[IndexLogEntry] = None) -> None:
        from hyperspace_tpu.plan.nodes import Scan, ScanRelation
        from hyperspace_tpu.telemetry.events import RefreshActionEvent

        prev = previous if previous is not None \
            else log_manager.get_latest_stable_log()
        if prev is None:
            raise HyperspaceError("Refresh: index does not exist")
        rel_meta = session.source_provider_manager.refresh_relation_metadata(
            prev.relations[0])
        plan = Scan(ScanRelation(
            root_paths=tuple(rel_meta.root_paths),
            file_format=rel_meta.file_format,
            options=tuple(sorted(rel_meta.options.items())),
        ))
        config = DataSkippingIndexConfig(
            prev.name, prev.derived_dataset.sketched_columns,
            prev.derived_dataset.sketch_types)
        super().__init__(log_manager, data_manager, session, plan, config)
        self.event_class = RefreshActionEvent
        self._previous_entry = prev
        self._file_id_tracker = FileIdTracker.from_log_entry(prev)

    def _rebase(self) -> None:
        """Conflict retry (actions/base.py): re-sketch against the stable
        entry the winning writer committed — same contract as
        RefreshActionBase._rebase."""
        super()._rebase()
        stable = self.log_manager.get_latest_stable_log()
        if stable is not None:
            self._previous_entry = stable
            self._file_id_tracker = FileIdTracker.from_log_entry(stable)

    def _changed_files(self):
        from hyperspace_tpu.lifecycle.change_detector import diff_file_sets

        current = self._relation().all_files(self._file_id_tracker)
        appended, deleted, _ = diff_file_sets(
            current, self._previous_entry.source_file_infos())
        return appended, {(f.name, f.size, f.mtime) for f in deleted}

    def validate(self) -> None:
        from hyperspace_tpu.exceptions import NoChangesError

        if self.previous_log_entry is None or \
                self.previous_log_entry.state != States.ACTIVE:
            raise HyperspaceError(
                f"Refresh is only supported in {States.ACTIVE} state")
        appended, deleted = self._changed_files()
        if not appended and not deleted:
            raise NoChangesError("Source data is unchanged; refresh is a no-op")

    def log_entry_for_begin(self) -> IndexLogEntry:
        import copy

        return copy.deepcopy(self._previous_entry)

    def op(self) -> None:
        appended, deleted_keys = self._changed_files()
        old = read_sketch(self._previous_entry)
        carry: List[Dict] = []
        if old.num_rows:
            for row in old.to_pylist():
                key = (row[SKETCH_FILE_NAME], row[SKETCH_FILE_SIZE],
                       row[SKETCH_FILE_MTIME])
                if key not in deleted_keys:
                    carry.append(row)
        self._build_sketch(file_names=[f.name for f in appended],
                           carry_rows=carry)
