"""Bounded retry with exponential backoff + jitter for transient IO errors.

The op-log writes one small file per action; a transient ``EIO`` (flaky
NFS/FUSE mount, object-store 5xx surfaced as an errno) or ``ENOSPC``
(another process's spill just got reclaimed) should not abort an index
build whose data files are already durably written.  Retries are bounded
and per-attempt delays are jittered so two racing writers don't
re-collide in lockstep (the Spark task-retry model, scoped down to
single file operations).

Retryable = the classic transient errnos.  Everything else — including
``FileExistsError`` (the optimistic-concurrency signal, which must
surface immediately) — propagates on first failure.
"""

from __future__ import annotations

import dataclasses
import errno
import random
import time
from typing import Callable, TypeVar

T = TypeVar("T")

# EIO: flaky transport.  ENOSPC: space can be reclaimed between attempts.
# EAGAIN/EINTR: definitionally transient.
TRANSIENT_ERRNOS = frozenset(
    {errno.EIO, errno.ENOSPC, errno.EAGAIN, errno.EINTR})


def is_transient(exc: BaseException) -> bool:
    return (isinstance(exc, OSError)
            and not isinstance(exc, FileExistsError)
            and exc.errno in TRANSIENT_ERRNOS)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """``max_attempts`` total tries; delay before retry *i* is
    ``initial_backoff_ms * 2**(i-1)`` capped at ``max_backoff_ms``, each
    multiplied by a uniform [0.5, 1.0) jitter factor."""

    max_attempts: int = 3
    initial_backoff_ms: float = 10.0
    max_backoff_ms: float = 1000.0

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        base = min(self.initial_backoff_ms * (2.0 ** attempt),
                   self.max_backoff_ms)
        return base * (0.5 + 0.5 * rng.random()) / 1000.0

    def call(self, fn: Callable[[], T]) -> T:
        """Run ``fn``, retrying transient OSErrors up to the budget.
        Every absorbed retry feeds ``io.retry.attempts`` and the active
        query's run report — a query that silently survived a flaky
        mount stays explainable after the fact."""
        rng = random.Random()
        attempt = 0
        while True:
            try:
                return fn()
            except OSError as e:
                attempt += 1
                if not is_transient(e) or attempt >= max(1, self.max_attempts):
                    raise
                from hyperspace_tpu.telemetry import metrics, report

                metrics.inc("io.retry.attempts")
                report.record("io.retry", attempt=attempt,
                              error=f"{type(e).__name__}: {e}")
                time.sleep(self.delay_s(attempt - 1, rng))


def policy_from_conf(conf) -> RetryPolicy:
    """RetryPolicy from ``hyperspace.system.io.retry.*`` conf keys."""
    return RetryPolicy(
        max_attempts=int(conf.io_retry_max_attempts),
        initial_backoff_ms=float(conf.io_retry_initial_backoff_ms),
        max_backoff_ms=float(conf.io_retry_max_backoff_ms))
