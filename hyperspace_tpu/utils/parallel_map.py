"""Order-preserving threaded map for per-file IO.

pyarrow's readers and writers release the GIL, so scans/writes of many
files overlap decode and filesystem latency instead of serializing on one
core.  Fail-fast: the first exception cancels not-yet-started work and
propagates immediately.

One SHARED pool serves every call: a query plan calls this dozens of times
(per scan, per join bucket), and per-call ThreadPoolExecutor creation /
teardown costs milliseconds of thread churn per query.  Reentrancy is
handled by running NESTED calls inline in the calling worker (the outer
level already provides the parallelism; a bounded shared pool with nested
submission could deadlock).  ``max_workers`` caps a call's in-flight tasks
by THROTTLED SUBMISSION — a call never occupies more pool threads than its
cap, so concurrent callers share the pool instead of queueing behind one
call's backlog — and a failing call stops submitting, joins its in-flight
tasks, then raises: the caller's cleanup (e.g. removing a spill dir) can
never race still-running tasks.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")

_POOL = None
_POOL_PID: Optional[int] = None
_POOL_LOCK = threading.Lock()
_IN_WORKER = threading.local()


def _pool():
    global _POOL, _POOL_PID
    with _POOL_LOCK:
        # Fork guard: a child inherits the pool OBJECT but not its threads;
        # submitting to it would hang forever.
        if _POOL is None or _POOL_PID != os.getpid():
            from concurrent.futures import ThreadPoolExecutor

            _POOL = ThreadPoolExecutor(
                max_workers=min(32, (os.cpu_count() or 4) * 2),
                thread_name_prefix="hs-io")
            _POOL_PID = os.getpid()
        return _POOL


def parallel_map_ordered(fn: Callable[[T], R], items: Sequence[T],
                         max_workers: int = 16) -> List[R]:
    n = len(items)
    if n <= 1 or getattr(_IN_WORKER, "active", False):
        return [fn(x) for x in items]
    workers = min(n, os.cpu_count() or 4, max_workers)
    pool = _pool()
    results: List = [None] * n
    cond = threading.Condition()
    state = {"next": 0, "outstanding": 0, "error": None}

    def run(i: int) -> None:
        _IN_WORKER.active = True
        err = None
        try:
            results[i] = fn(items[i])
        except BaseException as e:  # noqa: BLE001 — re-raised in the caller
            err = e
        finally:
            _IN_WORKER.active = False
        with cond:
            state["outstanding"] -= 1
            if err is not None and state["error"] is None:
                state["error"] = err
            cond.notify_all()

    with cond:
        while True:
            while (state["error"] is None and state["next"] < n
                   and state["outstanding"] < workers):
                i = state["next"]
                state["next"] += 1
                state["outstanding"] += 1
                pool.submit(run, i)
            if state["outstanding"] == 0 and (
                    state["error"] is not None or state["next"] >= n):
                break
            cond.wait()
    if state["error"] is not None:
        raise state["error"]
    return results
