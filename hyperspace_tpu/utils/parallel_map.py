"""Order-preserving threaded map for per-file IO.

pyarrow's readers and writers release the GIL, so scans/writes of many
files overlap decode and filesystem latency instead of serializing on one
core.  Fail-fast: the first exception cancels not-yet-started work and
propagates immediately.
"""

from __future__ import annotations

import os
from typing import Callable, List, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def parallel_map_ordered(fn: Callable[[T], R], items: Sequence[T],
                         max_workers: int = 16) -> List[R]:
    if len(items) <= 1:
        return [fn(x) for x in items]
    from concurrent.futures import FIRST_EXCEPTION, ThreadPoolExecutor, wait

    workers = min(len(items), os.cpu_count() or 4, max_workers)
    with ThreadPoolExecutor(max_workers=workers) as pool:
        futures = [pool.submit(fn, x) for x in items]
        done, not_done = wait(futures, return_when=FIRST_EXCEPTION)
        failed = next((f for f in done if f.exception() is not None), None)
        if failed is not None:
            for f in not_done:
                f.cancel()
            raise failed.exception()
        return [f.result() for f in futures]
