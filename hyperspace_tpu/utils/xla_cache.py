"""Persistent XLA compilation cache, applied lazily at first kernel dispatch.

First compile of the build/query kernels costs tens of seconds on a real
chip; the on-disk cache makes that a once-per-machine cost instead of
once-per-process.  Applied from the engine's own kernel entry points — NOT
at package import — so embedding applications that merely import
hyperspace_tpu never have their own JAX programs redirected into our cache
directory.  ``HS_XLA_CACHE=0`` disables; an app-configured
``jax_compilation_cache_dir`` is always honored.
"""

from __future__ import annotations

import os

_applied = False


def ensure_persistent_xla_cache() -> None:
    global _applied
    if _applied:
        return
    _applied = True
    if os.environ.get("HS_XLA_CACHE", "1") == "0":
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir:
            return  # the application already chose a cache; keep it
        cache_dir = os.path.join(
            os.environ.get("XDG_CACHE_HOME",
                           os.path.join(os.path.expanduser("~"), ".cache")),
            "hyperspace_tpu", "xla-cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every kernel: the default min-entry threshold skips exactly
        # the small-but-slow-to-compile programs we care about.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # pragma: no cover - cache is an optimization only
        pass
