"""Per-request deadline propagation: a contextvar the serving layer sets
and the executor checks at phase boundaries.

The serving layer (interop/server.py) admits a request with a deadline
derived from the request spec's ``deadline_ms`` or the conf default
(``hyperspace.serving.defaultDeadlineMs``).  The worker thread executing
the query enters :func:`scope`, and every ``check()`` site past the
deadline raises :class:`DeadlineExceededError` — so a query that has
already blown its budget stops burning CPU/IO at the NEXT phase boundary
instead of running to completion for an answer nobody is waiting for.

Check sites are deliberately coarse (executor node dispatch, collect's
plan/execute seams — never per row): a check is one contextvar read plus
one clock read, and only when a deadline is actually set does the clock
read happen at all.

Contextvar semantics mean worker threads spawned INSIDE the executor
(``utils/parallel_map``) do not inherit the deadline — their per-file
work finishes and the abort lands at the next boundary on the query's
own thread.  That is the contract: abort cleanly BETWEEN phases, never
tear a phase mid-flight.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator, Optional

from hyperspace_tpu.exceptions import DeadlineExceededError

__all__ = ["DeadlineExceededError", "scope", "remaining", "check",
           "active"]

_deadline: "contextvars.ContextVar[Optional[float]]" = \
    contextvars.ContextVar("hyperspace_deadline", default=None)


@contextlib.contextmanager
def scope(seconds: Optional[float]) -> Iterator[None]:
    """Run the with-block under a deadline ``seconds`` from now.
    ``None`` (or a non-positive value) is a no-op scope, so callers can
    pass an optional deadline through unconditionally.  Scopes nest: the
    inner scope wins inside the block and the outer one is restored on
    exit (an inner scope cannot EXTEND an outer deadline — the tighter
    of the two applies)."""
    if seconds is None or seconds <= 0:
        yield
        return
    now = time.monotonic()
    target = now + seconds
    outer = _deadline.get()
    if outer is not None:
        target = min(target, outer)
    token = _deadline.set(target)
    try:
        yield
    finally:
        _deadline.reset(token)


def active() -> bool:
    return _deadline.get() is not None


def remaining() -> Optional[float]:
    """Seconds until the current deadline (negative once past it), or
    None when no deadline is set."""
    dl = _deadline.get()
    if dl is None:
        return None
    return dl - time.monotonic()


def check(phase: str = "") -> None:
    """Raise :class:`DeadlineExceededError` if the current deadline has
    passed.  No deadline set = one contextvar read, nothing else."""
    dl = _deadline.get()
    if dl is None:
        return
    over = time.monotonic() - dl
    if over > 0:
        where = f" at {phase}" if phase else ""
        raise DeadlineExceededError(
            f"deadline exceeded{where} ({over * 1000.0:.0f} ms past)")
