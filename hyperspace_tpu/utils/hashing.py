"""Host-side hashing helpers for signatures/fingerprints.

Reference contract: util/HashingUtils.scala:24-35 (md5 of a string) and the
fold pattern in index/FileBasedSignatureProvider.scala:38-61 (fold md5 over
(size, mtime, path) per file).  Device-side bucket hashing lives in
hyperspace_tpu.ops.hash — the two are deliberately different: signatures are
host metadata, bucket assignment is a TPU kernel.
"""

from __future__ import annotations

import hashlib
from typing import Iterable


def md5_hex(value: str) -> str:
    return hashlib.md5(value.encode("utf-8")).hexdigest()


def fold_md5(parts: Iterable[str], init: str = "") -> str:
    """Order-sensitive md5 fold: h_{i+1} = md5(h_i + part_i).

    Matches the accumulate-then-hash shape of
    FileBasedSignatureProvider.scala:38-61.
    """
    acc = init
    for part in parts:
        acc = md5_hex(acc + part)
    return acc
