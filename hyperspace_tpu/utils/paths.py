"""Path helpers.

Reference contract: util/PathUtils.scala:22-40 — qualify paths and filter out
non-data files (names starting with ``_`` or ``.``).
"""

from __future__ import annotations

import os


def normalize_path(path: str) -> str:
    """Absolute, symlink-free, scheme-less canonical form of a local path."""
    return os.path.abspath(os.path.expanduser(path))


def is_data_file(name: str) -> bool:
    """Spark convention: files starting with '_' or '.' are metadata, not data
    (PathUtils.scala:31-36)."""
    base = os.path.basename(name)
    return not (base.startswith("_") or base.startswith("."))
