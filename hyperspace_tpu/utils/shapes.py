"""Static-shape capacity policy for dynamic-size kernel outputs.

XLA traces one program per static output shape, so kernels with
data-dependent result sizes (join materialization) must pick a padded
capacity.  The policy lives here, in one place: round up to the next power
of two, so distinct result sizes collapse onto O(log n) compiled programs —
a fresh compile costs 20-40 s on a real chip.  Callers slice the padded
output back to the true count host-side.
"""

from __future__ import annotations


def round_up_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (n - 1).bit_length()
