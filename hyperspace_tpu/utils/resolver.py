"""Column-name resolution honoring case-insensitivity.

Reference contract: util/ResolverUtils.scala:25-74 — requested column names
resolve against the schema case-insensitively (Spark's default resolver),
returning the schema's own spelling; unresolvable names are an error
surfaced with the full list.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

from hyperspace_tpu.exceptions import HyperspaceError


def resolve(requested: Sequence[str], available: Iterable[str]) -> Optional[List[str]]:
    """Resolve all of ``requested`` against ``available`` (case-insensitive);
    None if any fail."""
    lookup: Dict[str, str] = {}
    for name in available:
        lookup.setdefault(name.lower(), name)
    out: List[str] = []
    for name in requested:
        hit = lookup.get(name.lower())
        if hit is None:
            return None
        out.append(hit)
    return out


def resolve_or_raise(requested: Sequence[str], available: Iterable[str],
                     what: str = "column") -> List[str]:
    available = list(available)
    resolved = resolve(requested, available)
    if resolved is None:
        missing = [n for n in requested if resolve([n], available) is None]
        raise HyperspaceError(
            f"Could not resolve {what}(s) {missing} against schema {available}")
    return resolved
