"""Deprecation alias: the XLA profiling seam moved into the tracing layer.

``profiler_trace`` now lives in ``hyperspace_tpu.telemetry.trace`` — one
timing subsystem (spans time the engine's decisions, the XLA trace times
the device kernels) instead of two.  This module re-exports it so
existing callers keep working; new code should import from
``hyperspace_tpu.telemetry``.
"""

from __future__ import annotations

from hyperspace_tpu.telemetry.trace import profiler_trace

__all__ = ["profiler_trace"]
