"""Profiling surface: XLA traces for the device data plane.

SURVEY.md §5: the reference inherits its observability from the Spark UI;
the TPU build's equivalent is the JAX/XLA profiler.  ``profiler_trace``
wraps a region (an index build, a query) and writes a TensorBoard-loadable
trace of every XLA program launch, transfer, and kernel.
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def profiler_trace(log_dir: str) -> Iterator[None]:
    """Trace device activity in the with-block into ``log_dir`` (view with
    TensorBoard's profile plugin or Perfetto).

    >>> with profiler_trace("/tmp/hs-trace"):
    ...     hs.create_index(df, config)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
