"""Reflective class loading — the one home for conf-pluggable backends.

The reference resolves pluggable classes (source builders, signature
provider, event logger) via JVM reflection from Spark conf strings
(e.g. telemetry/HyperspaceEventLogging.scala:42-64); this is the Python
equivalent, shared by every conf key that names a class so error behavior
and path syntax cannot drift between them.
"""

from __future__ import annotations

from typing import Dict, Type


_CACHE: Dict[tuple, type] = {}


def load_class(name: str, base_cls: type,
               exc_cls: Type[Exception] = ValueError) -> type:
    """Load ``name`` (``module.Class`` or ``module:Class``) and require it
    to subclass ``base_cls``.  Failures raise ``exc_cls`` with context.
    Memoized per (name, base)."""
    key = (name, base_cls)
    cls = _CACHE.get(key)
    if cls is not None:
        return cls
    import importlib

    module_name, _, cls_name = name.replace(":", ".").rpartition(".")
    if not module_name:
        raise exc_cls(f"Invalid class path: {name!r}")
    try:
        cls = getattr(importlib.import_module(module_name), cls_name)
    except (ImportError, AttributeError) as e:
        raise exc_cls(f"Cannot load class {name!r} ({e})") from e
    if not (isinstance(cls, type) and issubclass(cls, base_cls)):
        raise exc_cls(f"{name!r} is not a {base_cls.__name__} subclass")
    _CACHE[key] = cls
    return cls
