"""Measured device-routing thresholds: transfer physics, not constants.

The host-vs-device cost model (SURVEY.md §2.4 "per-core XLA data parallelism
over HBM-resident columnar batches") needs a row threshold per op kind:
below it, shipping columns to the accelerator costs more than a vectorized
host pass.  Rounds 2-3 hardwired thresholds measured over ONE remote-tunnel
environment (~4 MB/s, ~100 ms RTT); on a locally attached TPU (GB/s PCIe,
sub-ms latency) those constants would misroute genuinely device-sized work
to the host.  This module measures the attachment at first use and derives
the thresholds from the observed physics:

    device_time(R) ~ latency + R * bytes_per_row / bandwidth
    host_time(R)   ~ R / host_rows_per_s          (measured per op kind)
    threshold      = smallest R where device_time < host_time
                     (infinite when per-row transfer alone exceeds the
                     host's per-row cost -> capped sentinel)

Device COMPUTE rate is deliberately not probed at session start: the first
invocation of each kernel would pay a 20-40 s XLA compile over a tunnel,
which is not a calibration a session can afford.  The model instead assumes
device compute is never the bottleneck (true on the MXU/VPU for these
elementwise/sort/segment kernels) — so the threshold is purely the
transfer-amortization point, which is exactly what the hardwired constants
were approximating.

Explicit conf values always win (``HyperspaceConf.device_min_rows``); env
``HS_CALIBRATE=0`` disables probing and falls back to the conservative
remote-tunnel constants (the test suite pins this for determinism).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, Optional

# Conservative fallbacks: the round-2/3 measured remote-tunnel constants.
# Used when calibration is disabled (HS_CALIBRATE=0) or the probe fails.
STATIC_MIN_ROWS: Dict[str, int] = {
    "filter": 1 << 26,
    "join": 1 << 26,
    "agg": 1 << 26,
    "join_agg": 1 << 26,
    "build": 1 << 22,
}

# "Device never organically wins" sentinel — finite so conf arithmetic and
# JSON round-trips stay safe, far above any realizable batch.
NEVER_MIN_ROWS = 1 << 40

# Conservative fallbacks for the RESIDENT-data thresholds (inputs already
# in HBM via execution/device_cache.py; only round-trip latency must be
# repaid).  Used when calibration is disabled.
STATIC_RESIDENT_MIN_ROWS: Dict[str, int] = {
    "filter": 1 << 24,
    "join": 1 << 22,
    "agg": 1 << 22,
    # Fused join+aggregate returns O(groups) — not O(rows) — so its
    # resident break-even sits well below the plain join's.
    "join_agg": 1 << 20,
    "build": 1 << 22,
}

# Bytes shipped to the device per row, per op kind (the dominant transfer):
#   filter: two 8-B columns up, 1-B mask down
#   join:   8-B keys both sides up, two 8-B index vectors down
#   agg:    (n,2)-u32 key words + one f64 value column up, results down
#   build:  (n,2)-u32 hash words + (n,2)-u32 order words up, 2x i32 down
_BYTES_PER_ROW: Dict[str, float] = {
    "filter": 17.0,
    "join": 32.0,
    "agg": 24.0,
    # join_agg ships keys for both sides plus ~3 referenced value/group
    # columns cold; results return per GROUP, so the down direction is
    # negligible per row.
    "join_agg": 40.0,
    "build": 24.0,
}


@dataclasses.dataclass(frozen=True)
class DeviceProfile:
    """Measured attachment physics + host baseline rates."""

    platform: str
    latency_s: float           # fixed host->device->host round-trip
    h2d_bytes_per_s: float     # host->device bandwidth
    d2h_bytes_per_s: float     # device->host bandwidth
    host_rows_per_s: Dict[str, float]  # per op kind

    def _host_rate(self, kind: str) -> float:
        """Per-kind host rate; profiles predating the fused join_agg
        kind (or built by tests) derive it from join + agg — the host
        mirror literally runs both."""
        rate = self.host_rows_per_s.get(kind)
        if rate is None and kind == "join_agg":
            j = self.host_rows_per_s["join"]
            a = self.host_rows_per_s["agg"]
            rate = 1.0 / (1.0 / j + 1.0 / a)
        if rate is None:
            raise KeyError(f"Unknown device op kind: {kind!r}")
        return rate

    def min_rows(self, kind: str) -> int:
        """Break-even row count for ``kind`` under this profile."""
        host_s_per_row = 1.0 / self._host_rate(kind)
        transfer_s_per_row = _BYTES_PER_ROW[kind] / self.h2d_bytes_per_s
        margin = host_s_per_row - transfer_s_per_row
        if margin <= 0:
            # Per-row transfer alone already exceeds the host's per-row
            # cost: the device can never repay the shipping (round-3's
            # measured tunnel regime).
            return NEVER_MIN_ROWS
        rows = self.latency_s / margin
        # Round up to a power of two: thresholds are routing knobs, not
        # precision instruments, and pow2 values keep logs legible.
        threshold = 1 << max(0, (int(rows) - 1).bit_length())
        return min(threshold, NEVER_MIN_ROWS)

    def resident_min_rows(self, kind: str) -> int:
        """Break-even row count when the inputs are ALREADY device-resident
        (execution/device_cache.py): no per-row shipping — the kernel only
        has to repay its round-trip latency (x2 margin: the two-phase
        kernels sync a scalar mid-flight), assuming device compute beats
        the host mirror at any size that clears this."""
        # The fused join+aggregate pipeline syncs twice (match count,
        # group count) and pulls only per-group results: three round
        # trips to repay.  The other two-phase kernels sync once
        # mid-flight (x2).
        trips = 3.0 if kind == "join_agg" else 2.0
        rows = trips * self.latency_s * self._host_rate(kind)
        threshold = 1 << max(12, (max(1, int(rows)) - 1).bit_length())
        return min(threshold, NEVER_MIN_ROWS)


_PROFILE: Optional[DeviceProfile] = None
_PROFILE_FAILED = False
# One probe per process: concurrent first queries (interop server threads)
# must not each run the probe — timings measured under mutual load would be
# cached as the permanent routing physics.
import threading

_PROBE_LOCK = threading.Lock()


def calibration_enabled() -> bool:
    return os.environ.get("HS_CALIBRATE", "1").lower() not in ("0", "false")


def _median_time(fn, reps: int = 3) -> float:
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _probe_host_rates(n: int = 1 << 20) -> Dict[str, float]:
    """Host per-row rates for each op kind's dominant host-mirror cost:
    arrow elementwise compare (filter), numpy argsort (join: the mirror is
    sort+searchsorted), arrow hash aggregation (agg), numpy 3-key lexsort
    (build)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc

    rng = np.random.default_rng(0)
    ints = rng.integers(0, n, n)
    arr = pa.array(ints)
    tbl = pa.table({"k": ints % 1024, "v": rng.random(n)})

    t_filter = _median_time(lambda: pc.greater(arr, n // 2))
    t_join = _median_time(lambda: np.argsort(ints, kind="stable"))
    t_agg = _median_time(
        lambda: tbl.group_by("k").aggregate([("v", "sum")]))
    u32 = (ints % (1 << 31)).astype(np.uint32)
    t_build = _median_time(lambda: np.lexsort((u32, u32, u32 % 16)))
    return {
        "filter": n / max(t_filter, 1e-9),
        "join": n / max(t_join, 1e-9),
        "agg": n / max(t_agg, 1e-9),
        # The fused pipeline's host mirror does BOTH: join then hash-agg.
        "join_agg": n / max(t_join + t_agg, 1e-9),
        "build": n / max(t_build, 1e-9),
    }


def _probe_transfer() -> "tuple[str, float, float, float]":
    """(platform, latency_s, h2d_Bps, d2h_Bps) via jit-free transfers
    (device_put / np.asarray compile nothing, so the probe never pays an
    XLA compile)."""
    import jax
    import numpy as np

    dev = jax.devices()[0]
    small = np.zeros(8, dtype=np.float32)
    # Warm the dispatch path once before timing.
    np.asarray(jax.device_put(small, dev))
    latency = _median_time(lambda: np.asarray(jax.device_put(small, dev)))

    big = np.zeros(1 << 16, dtype=np.float32)  # 256 KiB
    nbytes = big.nbytes

    def h2d():
        jax.device_put(big, dev).block_until_ready()

    h2d()  # warm
    t_h2d = max(_median_time(h2d) - latency / 2, 1e-9)
    # d2h: jax arrays CACHE their fetched host copy, so each timed pull
    # must read a DISTINCT resident array or the probe measures a cache
    # hit (observed as an absurd quarter-TB/s on a 4 MB/s tunnel).
    residents = [jax.device_put(big + np.float32(i), dev).block_until_ready()
                 for i in range(3)]
    times = []
    for r in residents:
        t0 = time.perf_counter()
        np.asarray(r)
        times.append(time.perf_counter() - t0)
    times.sort()
    t_d2h = max(times[len(times) // 2] - latency / 2, 1e-9)
    return dev.platform, latency, nbytes / t_h2d, nbytes / t_d2h


def device_profile(refresh: bool = False) -> Optional[DeviceProfile]:
    """The process-wide measured profile (physics don't change mid-process),
    or None when probing is disabled or the accelerator is unreachable."""
    global _PROFILE, _PROFILE_FAILED
    if not calibration_enabled():
        return None
    with _PROBE_LOCK:
        if _PROFILE is not None and not refresh:
            return _PROFILE
        if _PROFILE_FAILED and not refresh:
            return None
        try:
            platform, latency, h2d, d2h = _probe_transfer()
            _PROFILE = DeviceProfile(
                platform=platform,
                latency_s=latency,
                h2d_bytes_per_s=h2d,
                d2h_bytes_per_s=d2h,
                host_rows_per_s=_probe_host_rates(),
            )
            _PROFILE_FAILED = False
            return _PROFILE
        except Exception:
            _PROFILE_FAILED = True
            return None


def calibrated_min_rows(kind: str) -> int:
    """The derived threshold for ``kind`` — measured when possible, the
    conservative tunnel constants otherwise.  A CPU-fallback backend keeps
    the conservative constants too: the model's "device compute is never
    the bottleneck" premise holds for the MXU/VPU, not for XLA-CPU
    re-running the very kernels the numpy/arrow mirrors beat."""
    if kind not in STATIC_MIN_ROWS:
        raise KeyError(f"Unknown device op kind: {kind!r}")
    profile = device_profile()
    if profile is None or profile.platform == "cpu":
        return STATIC_MIN_ROWS[kind]
    return profile.min_rows(kind)


def calibrated_resident_min_rows(kind: str) -> int:
    """Threshold for device-RESIDENT inputs — latency-only break-even
    (conservative constants on a CPU-fallback backend, as above)."""
    if kind not in STATIC_RESIDENT_MIN_ROWS:
        raise KeyError(f"Unknown device op kind: {kind!r}")
    profile = device_profile()
    if profile is None or profile.platform == "cpu":
        return STATIC_RESIDENT_MIN_ROWS[kind]
    return profile.resident_min_rows(kind)


def profile_summary() -> Dict[str, object]:
    """JSON-ready view for bench/telemetry output."""
    profile = device_profile()
    if profile is None:
        return {"calibrated": False,
                "thresholds": dict(STATIC_MIN_ROWS),
                "resident_thresholds": dict(STATIC_RESIDENT_MIN_ROWS)}
    return {
        "calibrated": True,
        "platform": profile.platform,
        "latency_ms": round(profile.latency_s * 1e3, 3),
        "h2d_mb_per_s": round(profile.h2d_bytes_per_s / 1e6, 2),
        "d2h_mb_per_s": round(profile.d2h_bytes_per_s / 1e6, 2),
        "host_mrows_per_s": {k: round(v / 1e6, 2)
                             for k, v in profile.host_rows_per_s.items()},
        # Via the calibrated_* gates, so a CPU-fallback backend reports
        # the conservative constants actually in effect.
        "thresholds": {k: calibrated_min_rows(k) for k in STATIC_MIN_ROWS},
        "resident_thresholds": {k: calibrated_resident_min_rows(k)
                                for k in STATIC_RESIDENT_MIN_ROWS},
    }
