"""Version-compat shims for the small jax API surface the engine leans on.

``jax.enable_x64`` (the context-manager form) is only a top-level alias in
newer jax; older releases ship it as ``jax.experimental.enable_x64``.  The
engine wraps every int64-precision region in it, so a missing alias took
down the whole device data plane on otherwise-supported jax versions.
Import it from here instead of from jax directly.
"""

from __future__ import annotations

import jax

try:
    enable_x64 = jax.enable_x64
except AttributeError:  # older jax: context manager lives in experimental
    from jax.experimental import enable_x64  # type: ignore[no-redef]

__all__ = ["enable_x64"]
