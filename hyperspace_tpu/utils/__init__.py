from hyperspace_tpu.utils.hashing import md5_hex, fold_md5
from hyperspace_tpu.utils.paths import normalize_path, is_data_file
