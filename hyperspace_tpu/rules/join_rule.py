"""JoinIndexRule: rewrite both sides of an equi-join to bucketed index scans.

Reference contract: index/rules/JoinIndexRule.scala —
  - applicability (:108-140, 165-166, 233-272): inner join, condition is a
    CNF of column==column equalities, each side a linear plan over one
    supported relation, every equality spanning the two sides 1:1;
  - index selection (:282-334, 448-530): per side, usable indexes must have
    indexed columns == that side's join keys (same set; compatible pairs
    require the same order) and cover that side's required columns;
  - ranking: JoinIndexRanker (rankers.py);
  - rewrite (:57-98): both scans become index scans WITH bucket spec —
    giving the shuffle-free sort-merge join (JoinIndexRule.scala:36-50); the
    executor's merge join then runs directly over per-bucket sorted data.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.plan.expr import as_equi_join_pairs
from hyperspace_tpu.plan.nodes import Join, LogicalPlan
from hyperspace_tpu.rules import rule_utils
from hyperspace_tpu.rules.rankers import rank_join_index_pairs
from hyperspace_tpu.telemetry.events import HyperspaceIndexUsageEvent, emit_event
from hyperspace_tpu.utils.resolver import resolve


class JoinIndexRule:
    def __init__(self, session, entries: Optional[List[IndexLogEntry]] = None) -> None:
        self.session = session
        self._entries = entries

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        if isinstance(plan, Join):
            rewritten = self._try_rewrite(plan)
            if rewritten is not None:
                return rewritten
        new_children = tuple(self.apply(c) for c in plan.children)
        if new_children != plan.children:
            return plan.with_children(new_children)
        return plan

    def _try_rewrite(self, join: Join) -> Optional[LogicalPlan]:
        spm = self.session.source_provider_manager
        if join.how != "inner":
            # Reference scope: the rewrite applies to inner equi-joins only
            # (JoinIndexRule.scala:134-140).  Other join types still execute
            # — and FilterIndexRule may still index their sides.
            return None
        pairs = as_equi_join_pairs(join.condition)
        if not pairs:
            return None
        if not (join.left.is_linear() and join.right.is_linear()):
            return None
        left_leaves = join.left.leaf_relations()
        right_leaves = join.right.leaf_relations()
        if len(left_leaves) != 1 or len(right_leaves) != 1:
            return None
        l_scan, r_scan = left_leaves[0], right_leaves[0]
        if rule_utils.is_index_applied(l_scan) or rule_utils.is_index_applied(r_scan):
            return None
        if not (spm.is_supported_relation(l_scan) and spm.is_supported_relation(r_scan)):
            return None

        l_schema = self.session.schema_of(l_scan)
        r_schema = self.session.schema_of(r_scan)
        # Orient every equality pair as (left column, right column); the 1:1
        # requirement (JoinIndexRule.scala:233-272).
        l_keys: List[str] = []
        r_keys: List[str] = []
        for a, b in pairs:
            if resolve([a], l_schema) and resolve([b], r_schema):
                l_keys.append(a)
                r_keys.append(b)
            elif resolve([b], l_schema) and resolve([a], r_schema):
                l_keys.append(b)
                r_keys.append(a)
            else:
                return None
        l_map: Dict[str, str] = {}
        r_map: Dict[str, str] = {}
        for lk, rk in zip(l_keys, r_keys):
            lk_l, rk_l = lk.lower(), rk.lower()
            if l_map.get(lk_l, rk_l) != rk_l or r_map.get(rk_l, lk_l) != lk_l:
                return None  # one left column equated to two right columns
            l_map[lk_l] = rk_l
            r_map[rk_l] = lk_l

        l_required = self._required_columns(join.left, l_schema)
        r_required = self._required_columns(join.right, r_schema)

        entries = self._entries
        if entries is None:
            entries = self.session.index_collection_manager.get_indexes([States.ACTIVE])
        l_candidates = rule_utils.get_candidate_indexes(self.session, entries, l_scan)
        r_candidates = rule_utils.get_candidate_indexes(self.session, entries, r_scan)
        # The join rewrite's whole value is the bucket-ALIGNED merge; a
        # quarantined bucket's source-side replacement has no bucket
        # structure to align, so any quarantine disqualifies the entry
        # here (the filter rule still serves it with containment).
        from hyperspace_tpu.rules.hybrid import quarantined_split

        l_candidates = [e for e in l_candidates
                        if not quarantined_split(self.session, e)[0]]
        r_candidates = [e for e in r_candidates
                        if not quarantined_split(self.session, e)[0]]
        l_usable = _usable_indexes(l_candidates, l_keys, l_required)
        r_usable = _usable_indexes(r_candidates, r_keys, r_required)
        compatible = _compatible_pairs(l_usable, r_usable, l_keys, r_keys)
        best = rank_join_index_pairs(compatible, l_scan, r_scan,
                                     self.session.conf.hybrid_scan_enabled)
        if best is None:
            return None
        l_entry, r_entry = best

        hybrid = self.session.conf.hybrid_scan_enabled

        def rewrite_side(side_plan, scan, entry):
            if hybrid:
                from hyperspace_tpu.rules.hybrid import (
                    hybrid_file_lists,
                    transform_plan_to_use_hybrid_scan,
                )

                appended, deleted = hybrid_file_lists(entry, scan)
                if appended or deleted:
                    return transform_plan_to_use_hybrid_scan(
                        self.session, side_plan, scan, entry, bucket_union=True)
            return rule_utils.transform_plan_to_use_index_only_scan(
                side_plan, scan, entry, use_bucket_spec=True)

        new_left = rewrite_side(join.left, l_scan, l_entry)
        new_right = rewrite_side(join.right, r_scan, r_entry)
        new_plan = Join(new_left, new_right, join.condition, join.how,
                        residual=join.residual)
        emit_event(HyperspaceIndexUsageEvent(
            index_names=[l_entry.name, r_entry.name],
            plan_before=Join(join.left, join.right, join.condition, join.how).tree_string(),
            plan_after=new_plan.tree_string(),
            message="JoinIndexRule applied"))
        return new_plan

    def _required_columns(self, side_plan: LogicalPlan, schema: List[str]) -> List[str]:
        """All SOURCE columns this side must provide: its output plus any
        columns referenced by intermediate filters
        (JoinIndexRule.scala:371-383).  Computed outputs (Compute /
        WithColumns / Aggregate results) resolve to their expressions'
        referenced source columns — the index need only cover the inputs,
        since the computation runs above the scan."""
        from hyperspace_tpu.plan.expr import Expr as _Expr
        from hyperspace_tpu.plan.nodes import (
            Aggregate,
            Compute,
            Filter,
            WithColumns,
        )

        needed: Set[str] = set(side_plan.output_columns(self.session.schema_of))

        def walk(node: LogicalPlan) -> None:
            if isinstance(node, Filter):
                needed.update(node.condition.referenced_columns())
            elif isinstance(node, (Compute, WithColumns)):
                # Top-down: a computed name needed above is replaced by the
                # source columns its expression reads.
                for name, e in node.exprs:
                    if name in needed:
                        needed.discard(name)
                        needed.update(e.referenced_columns())
            elif isinstance(node, Aggregate):
                # An aggregate output needed above is replaced by its input
                # column(s); group keys pass through as themselves.
                for func, agg_in, out in node.aggs:
                    if out in needed:
                        needed.discard(out)
                        if isinstance(agg_in, _Expr):
                            needed.update(agg_in.referenced_columns())
                        elif agg_in:
                            needed.add(agg_in)
                needed.update(node.group_by)
            for c in node.children:
                walk(c)

        walk(side_plan)
        return sorted(needed)


def _usable_indexes(candidates: List[IndexLogEntry], keys: List[str],
                    required: List[str]) -> List[IndexLogEntry]:
    """JoinIndexRule.scala:448-460: indexed columns == join keys (as sets),
    and all required columns covered."""
    keyset = {k.lower() for k in keys}
    req = {c.lower() for c in required}
    out = []
    for e in candidates:
        if {c.lower() for c in e.indexed_columns} != keyset:
            continue
        if not req <= {c.lower() for c in e.derived_dataset.all_columns}:
            continue
        out.append(e)
    return out


def _compatible_pairs(left: List[IndexLogEntry], right: List[IndexLogEntry],
                      l_keys: List[str], r_keys: List[str]
                      ) -> List[Tuple[IndexLogEntry, IndexLogEntry]]:
    """JoinIndexRule.scala:483-530: pair up indexes whose indexed-column
    ORDER is mutually consistent with the join-key mapping."""
    key_map = {lk.lower(): rk.lower() for lk, rk in zip(l_keys, r_keys)}
    out = []
    for le in left:
        expected_right_order = [key_map[c.lower()] for c in le.indexed_columns]
        for re in right:
            if [c.lower() for c in re.indexed_columns] == expected_right_order:
                out.append((le, re))
    return out
