"""FilterIndexRule: rewrite Scan→Filter[→Project] to an index-only scan.

Reference contract: index/rules/FilterIndexRule.scala —
  - pattern extraction (:158-197): Filter directly over a supported Scan,
    optionally under a Project;
  - applicability (:99-155): the index's FIRST indexed column must appear in
    the predicate, and the index must cover filter + output columns;
  - rewrite (:43-88): swap the scan, optionally with bucket spec
    (IndexConstants.scala:52-53).

TPU extension with reference semantics intact: when the predicate pins every
indexed column with equality/IN, we precompute the matching hash buckets with
a bit-identical host mirror of the build kernel (ops/hash.bucket_ids_np;
parity-tested against the device kernel) and prune the index files read
(the bucket-pruning effect Spark gets from its bucketed FileSourceScan).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.plan.expr import BinOp, Col, Expr, IsIn, Lit, Or, split_conjuncts
from hyperspace_tpu.plan.nodes import Filter, LogicalPlan, Project, Scan
from hyperspace_tpu.rules import rule_utils
from hyperspace_tpu.rules.rankers import rank_filter_indexes
from hyperspace_tpu.telemetry.events import HyperspaceIndexUsageEvent, emit_event
from hyperspace_tpu.utils.resolver import resolve


class FilterIndexRule:
    def __init__(self, session, entries: Optional[List[IndexLogEntry]] = None) -> None:
        self.session = session
        self._entries = entries

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        """Rewrite EVERY matching Filter-over-Scan site, not just the first
        — a join of two filtered relations should use both sides' indexes.
        One forward pass suffices: transform_up keeps untouched subtrees'
        identities, so later matches still locate their nodes in the
        rewritten plan."""
        for matched in _extract_filter_nodes(plan):
            new_plan = self._try_rewrite(plan, matched)
            if new_plan is not None:
                plan = new_plan
        return plan

    def _try_rewrite(self, plan: LogicalPlan, matched) -> Optional[LogicalPlan]:
        scan, filter_node, project_cols = matched
        if rule_utils.is_index_applied(scan):
            return None
        if not self.session.source_provider_manager.is_supported_relation(scan):
            return None

        schema = self.session.schema_of(scan)
        filter_cols = sorted(filter_node.condition.referenced_columns())
        output_cols = project_cols if project_cols is not None else schema
        if resolve(filter_cols, schema) is None:
            return None

        entries = self._entries
        if entries is None:
            entries = self.session.index_collection_manager.get_indexes([States.ACTIVE])
        candidates = rule_utils.get_candidate_indexes(self.session, entries, scan)
        covering = _find_covering_indexes(candidates, filter_cols, output_cols)
        best = rank_filter_indexes(covering, scan,
                                   self.session.conf.hybrid_scan_enabled,
                                   filter_cols=filter_cols)
        if best is None:
            return None

        hybrid_needed = False
        if self.session.conf.hybrid_scan_enabled:
            from hyperspace_tpu.rules.hybrid import hybrid_file_lists

            appended, deleted = hybrid_file_lists(best, scan)
            hybrid_needed = bool(appended or deleted)
        # Quarantined buckets route through the hybrid transform even on
        # an exact signature match: the index side drops the damaged
        # buckets and a BucketIn source branch re-reads exactly their
        # rows (rules/hybrid.py) — containment instead of PR 2's
        # whole-index fallback.
        from hyperspace_tpu.rules.hybrid import quarantined_split

        _, qbuckets = quarantined_split(self.session, best)
        if hybrid_needed or qbuckets:
            from hyperspace_tpu.rules.hybrid import transform_plan_to_use_hybrid_scan

            # Bucket pruning applies to the index PORTION of a hybrid scan
            # too — only the appended raw files must always be read.
            new_plan = transform_plan_to_use_hybrid_scan(
                self.session, plan, scan, best, bucket_union=False,
                prune_to_buckets=_bucket_pruning(filter_node.condition, best))
        else:
            prune = _bucket_pruning(filter_node.condition, best)
            use_bucket_spec = (self.session.conf.filter_rule_use_bucket_spec
                               or prune is not None)
            # Per-index-file min/max pruning (_sketch.parquet written at
            # build): bites on range predicates — on every indexed dimension
            # when the layout is Z-order (ops/zorder.py).
            from hyperspace_tpu.rules.data_skipping import prune_index_files_by_sketch

            pruned = prune_index_files_by_sketch(best, filter_node.condition)
            file_paths, file_stats = (None, None) if pruned is None \
                else (pruned[0], (len(pruned[0]), pruned[1]))
            new_plan = rule_utils.transform_plan_to_use_index_only_scan(
                plan, scan, best, use_bucket_spec, prune, file_paths,
                file_stats)
        emit_event(HyperspaceIndexUsageEvent(
            index_names=[best.name],
            plan_before=plan.tree_string(),
            plan_after=new_plan.tree_string(),
            message="FilterIndexRule applied"))
        return new_plan


def _extract_filter_nodes(plan: LogicalPlan
                          ) -> List[Tuple[Scan, Filter, Optional[List[str]]]]:
    """ALL Project(Filter(Scan)) / Filter(Scan) matches in the plan
    (ExtractFilterNode, FilterIndexRule.scala:158-186), seeing through a
    pruning Project directly over the Scan (plan/pruning.py inserts those;
    Catalyst instead embeds pruning in the relation, so the reference never
    needed this)."""
    out: List[Tuple[Scan, Filter, Optional[List[str]]]] = []
    claimed: Optional[LogicalPlan] = None  # Filter consumed by a
    # Project-over-Filter match: skip re-matching it as a bare Filter.
    if isinstance(plan, Project) and isinstance(plan.child, Filter):
        scan = _scan_below(plan.child.child)
        if scan is not None:
            out.append((scan, plan.child, list(plan.columns)))
            claimed = plan.child
    elif isinstance(plan, Filter):
        scan = _scan_below(plan.child)
        if scan is not None:
            # With no outer Project, the pruning Project (if any) defines the
            # output columns.
            cols = list(plan.child.columns) \
                if isinstance(plan.child, Project) else None
            out.append((scan, plan, cols))
    # Recurse so filters under joins/unions also rewrite; a claimed Filter
    # is skipped itself but its interior is still searched.
    for child in plan.children:
        if child is claimed:
            for sub in child.children:
                out.extend(_extract_filter_nodes(sub))
        else:
            out.extend(_extract_filter_nodes(child))
    return out


def _scan_below(node: LogicalPlan) -> Optional[Scan]:
    """The scan at ``node``, unwrapping at most one pruning Project."""
    if isinstance(node, Scan):
        return node
    if isinstance(node, Project) and isinstance(node.child, Scan):
        return node.child
    return None


def _find_covering_indexes(candidates: Sequence[IndexLogEntry],
                           filter_cols: List[str],
                           output_cols: List[str]) -> List[IndexLogEntry]:
    """FilterIndexRule.scala:99-155: first indexed column in the predicate;
    index covers filter+output columns (case-insensitive).

    Z-order-layout indexes relax the first-column rule to ANY indexed
    column: the Morton clustering makes per-file pruning effective on every
    indexed dimension, which is the point of that layout (lexicographic
    data only clusters the first column, hence the reference's rule)."""
    out = []
    for entry in candidates:
        filter_set = {c.lower() for c in filter_cols}
        indexed_lower = [c.lower() for c in entry.indexed_columns]
        if entry.derived_dataset.properties.get("layout") == "zorder":
            if not filter_set & set(indexed_lower):
                continue
        elif indexed_lower[0] not in filter_set:
            continue
        index_cols = {c.lower() for c in entry.derived_dataset.all_columns}
        needed = {c.lower() for c in filter_cols} | {c.lower() for c in output_cols}
        if needed <= index_cols:
            out.append(entry)
    return out


def _pinned_values(e: Expr) -> Optional[Tuple[str, set]]:
    """(column, finite value set) when ``e`` pins one column: an equality,
    an IN list, or a DISJUNCTION of those over the same column
    (``a == 1 OR a IN (2, 3)`` pins a to {1, 2, 3} — same normalization
    the sketch pruning applies)."""
    if isinstance(e, BinOp) and e.op == "==":
        if isinstance(e.left, Col) and isinstance(e.right, Lit):
            return e.left.name.lower(), {e.right.value}
        if isinstance(e.right, Col) and isinstance(e.left, Lit):
            return e.right.name.lower(), {e.left.value}
        return None
    if isinstance(e, IsIn) and isinstance(e.child, Col):
        return e.child.name.lower(), set(e.values)
    if isinstance(e, Or):
        left = _pinned_values(e.left)
        right = _pinned_values(e.right)
        if left is not None and right is not None and left[0] == right[0]:
            return left[0], left[1] | right[1]
    return None


def _bucket_pruning(condition: Expr, entry: IndexLogEntry
                    ) -> Optional[Tuple[int, ...]]:
    """Buckets that can possibly hold matching rows, or None if not prunable.

    Only sound when every indexed column is pinned to a finite value set by
    top-level conjuncts (equality or IN).  The bucket for each value tuple is
    computed with the build kernel itself, so pruning can never disagree with
    bucket assignment.
    """
    pinned: dict = {}
    for conj in split_conjuncts(condition):
        hit = _pinned_values(conj)
        if hit is not None:
            name, values = hit
            pinned.setdefault(name, set()).update(values)
    indexed = [c.lower() for c in entry.indexed_columns]
    if not all(c in pinned for c in indexed):
        return None
    value_sets = [sorted(pinned[c], key=repr) for c in indexed]
    n_combos = 1
    for vs in value_sets:
        n_combos *= len(vs)
    if n_combos == 0 or n_combos > 1024:
        return None

    import itertools

    from hyperspace_tpu.io.columnar import to_hash_words
    from hyperspace_tpu.io.parquet import schema_to_arrow
    from hyperspace_tpu.ops.hash import bucket_ids_np

    # Literals MUST be hashed with the indexed column's stored type, not the
    # literal's inferred type: an int literal probing a float64 column would
    # otherwise hash different bits than the build did and prune the wrong
    # bucket.
    index_schema = schema_to_arrow(entry.derived_dataset.schema)
    schema_by_lower = {f.name.lower(): f.type for f in index_schema}
    combos = list(itertools.product(*value_sets))
    word_cols = []
    for col_i, col_name in enumerate(indexed):
        col_type = schema_by_lower.get(col_name)
        try:
            col_vals = pa.array([c[col_i] for c in combos], type=col_type)
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            return None  # literal not castable to the column type: no pruning
        word_cols.append(to_hash_words(col_vals))
    # Host mirror of the build kernel (bit-identical; parity-tested): a
    # device round trip for <=1024 probe rows would be pure latency.
    buckets = bucket_ids_np([np.asarray(w) for w in word_cols],
                            entry.num_buckets)
    return tuple(sorted(set(int(b) for b in buckets)))
