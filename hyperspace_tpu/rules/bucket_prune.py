"""BucketPruneRule: push bucket pruning into ALREADY-REWRITTEN index scans.

FilterIndexRule computes bucket pruning while rewriting a Filter-over-Scan
itself, but a filter above a scan that JoinIndexRule rewrote (a
point-filtered join side) is skipped by that rule (is_index_applied), so
its selective predicate never pruned buckets.  This pass runs after the
rewrite rules and annotates any ``Filter -> [Project] -> index Scan``
chain whose predicate pins every indexed column (the same
FilterIndexRule._bucket_pruning math — one implementation, one hash
mirror) with ``prune_to_buckets``.

Spark gets this effect for free from bucketed FileSourceScan pruning
inside the scan operator; our executor prunes by file name, so the plan
must carry the bucket set.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Filter, LogicalPlan, Project, Scan


class BucketPruneRule:
    def __init__(self, session, entries: List[IndexLogEntry]) -> None:
        self.session = session
        self._by_name = {e.name.lower(): e for e in entries}

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        from hyperspace_tpu.rules.filter_rule import _bucket_pruning

        def visit(node: LogicalPlan) -> LogicalPlan:
            if not isinstance(node, Filter):
                return node
            scan, wrap = _index_scan_below(node.children[0])
            if scan is None:
                return node
            rel = scan.relation
            if rel.prune_to_buckets is not None:
                # FilterIndexRule already pruned this scan from the SAME
                # condition chain; recomputing the hash probes here would
                # be duplicate work for an identical (or looser) set.
                return node
            entry = self._by_name.get((rel.index_scan_of or "").lower())
            if entry is None:
                return node
            prune = _bucket_pruning(node.condition, entry)
            if prune is None:
                return node
            new_scan = Scan(dataclasses.replace(rel, prune_to_buckets=prune))
            child = new_scan if wrap is None \
                else wrap.with_children((new_scan,))
            return Filter(node.condition, child)

        return plan.transform_up(visit)


def _index_scan_below(node: LogicalPlan):
    """(scan, wrapping Project or None) when ``node`` is an index scan with
    a bucket spec, optionally under one pruning Project."""
    wrap: Optional[Project] = None
    if isinstance(node, Project):
        wrap, node = node, node.children[0]
    if (isinstance(node, Scan) and node.relation.index_scan_of
            and node.relation.bucket_spec):
        return node, wrap
    return None, None
