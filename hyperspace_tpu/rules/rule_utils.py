"""Shared rule machinery: candidate index selection and plan rewriting.

Reference contract: index/rules/RuleUtils.scala —
  - ``get_candidate_indexes`` (:52-164): an ACTIVE index is a candidate when
    its stored fingerprint matches the recomputed signature of the current
    leaf relation (signature memoized per provider per rule invocation,
    :59-74); under hybrid scan, file-overlap math replaces exact matching
    (:79-133, implemented in hybrid.py).
  - ``transform_plan_to_use_index_only_scan`` (:255-286): swap the leaf scan
    for a scan over the index's bucketed Parquet files, optionally carrying
    the bucket spec.
  - already-applied detection via the index-scan marker on the relation
    (:173-183 / IndexConstants.scala:59 — here ``ScanRelation.index_scan_of``).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from hyperspace_tpu.index.log_entry import IndexLogEntry, IndexLogEntryTags
from hyperspace_tpu.index.signatures import get_provider
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan, ScanRelation


def is_index_applied(scan: Scan) -> bool:
    return scan.relation.index_scan_of is not None


def get_candidate_indexes(session, entries: Sequence[IndexLogEntry],
                          scan: Scan) -> List[IndexLogEntry]:
    """Filter ACTIVE entries down to COVERING indexes valid for ``scan``
    (data-skipping entries have their own rule + validity model)."""
    entries = [e for e in entries if e.is_covering]
    if is_index_applied(scan):
        return []
    # Integrity gate: an entry whose quarantine leaves no containment plan
    # (every bucket damaged, or a file→bucket mapping lost) is not a
    # candidate at all — the query answers from source.  Partially
    # quarantined entries STAY candidates; the transforms read only the
    # healthy buckets and re-read the damaged ones from source
    # (rules/hybrid.py quarantined_split / the BucketIn repair branch).
    from hyperspace_tpu.rules.hybrid import quarantine_excludes_entry

    entries = [e for e in entries
               if not quarantine_excludes_entry(session, e)]
    if session.conf.hybrid_scan_enabled:
        from hyperspace_tpu.rules.hybrid import get_hybrid_scan_candidates

        return get_hybrid_scan_candidates(session, entries, scan)
    # Signature-exact path: recompute per provider once (RuleUtils.scala:59-74).
    signature_cache: Dict[str, Optional[str]] = {}

    def current_signature(provider_name: str) -> Optional[str]:
        if provider_name not in signature_cache:
            provider = get_provider(provider_name)
            signature_cache[provider_name] = provider.signature(
                scan,
                lambda s: session.source_provider_manager.get_relation(s).all_files())
        return signature_cache[provider_name]

    out: List[IndexLogEntry] = []
    for entry in entries:
        if entry.has_source_update():
            # Quick-refreshed entries record appended/deleted files; they are
            # only usable through Hybrid Scan — the index data alone is stale.
            continue
        cached = entry.get_tag(IndexLogEntryTags.SIGNATURE_MATCHED, scan)
        if cached is None:
            sig = entry.signature()
            matched = current_signature(sig.provider) == sig.value
            entry.set_tag(IndexLogEntryTags.SIGNATURE_MATCHED, matched, scan)
        else:
            matched = cached
        if matched:
            out.append(entry)
    return out


def index_scan_relation(entry: IndexLogEntry,
                        use_bucket_spec: bool,
                        prune_to_buckets: Optional[Tuple[int, ...]] = None,
                        file_paths: Optional[Sequence[str]] = None,
                        file_stats: Optional[Tuple[int, int]] = None) -> ScanRelation:
    """The ScanRelation for reading an index's bucketed Parquet data
    (RuleUtils.scala:255-286; display marker IndexHadoopFsRelation.scala:29-50).
    ``file_paths``/``file_stats`` carry a sketch-pruned file subset."""
    files = list(file_paths) if file_paths is not None \
        else [f.name for f in entry.content.file_infos()]
    root = os.path.dirname(files[0]) if files else ""
    cols = tuple(entry.indexed_columns)
    return ScanRelation(
        root_paths=(root,),
        file_format="parquet",
        index_scan_of=entry.name,
        bucket_spec=(entry.num_buckets, cols, cols) if use_bucket_spec else None,
        file_paths=tuple(files),
        prune_to_buckets=prune_to_buckets,
        data_skipping_stats=file_stats,
        # What-if entries produce plan-only scans the executor refuses to
        # run (advisor/hypothetical.py): the tag rides the relation so no
        # downstream transform can lose it, and the entry's schema rides
        # along too — with zero files there is no footer to resolve from.
        hypothetical=entry.is_hypothetical,
        hypothetical_schema=tuple(
            (c, entry.derived_dataset.schema.get(c, "string"))
            for c in entry.derived_dataset.all_columns)
        if entry.is_hypothetical else None,
    )


def transform_plan_to_use_index_only_scan(
        plan: LogicalPlan, target: Scan, entry: IndexLogEntry,
        use_bucket_spec: bool,
        prune_to_buckets: Optional[Tuple[int, ...]] = None,
        file_paths: Optional[Sequence[str]] = None,
        file_stats: Optional[Tuple[int, int]] = None) -> LogicalPlan:
    """Swap ``target`` for an index-only scan throughout ``plan``."""
    new_node: LogicalPlan = Scan(
        index_scan_relation(entry, use_bucket_spec, prune_to_buckets,
                            file_paths, file_stats))
    if entry.has_lineage_column():
        # The stored lineage column is an implementation detail: project it
        # away so enabling hyperspace never changes a query's output schema.
        from hyperspace_tpu.plan.nodes import Project

        new_node = Project(entry.derived_dataset.all_columns, new_node)

    def swap(node: LogicalPlan) -> LogicalPlan:
        return new_node if node is target else node

    return plan.transform_up(swap)
