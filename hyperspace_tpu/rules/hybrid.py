"""Hybrid Scan: use a stale index over mutated source data by merging the
index with appended files and filtering out deleted rows via lineage.

Reference contract: index/rules/RuleUtils.scala —
  - candidate math (:79-133): an index whose signature no longer matches is
    still usable when the byte overlap is high enough: appended-bytes ratio
    ≤ conf threshold (0.3), deleted-bytes ratio ≤ threshold (0.2, deletes
    additionally require the lineage column); common bytes are tagged for
    the rankers.
  - plan transform (:302-443): index side gets a Filter(~isin(lineage_col,
    deleted_ids)) when rows were deleted (:399-408); appended files are read
    through a separate scan and merged with BucketUnion (join side, so
    bucketing survives, :422-439) or plain Union (filter side).

Beyond the reference — QUARANTINE CONTAINMENT: index data files recorded
as corrupt (index/quarantine.py; flagged by ``verify_index`` or by an
execution-time read failure) are treated as deleted-from-index.  The
whole hash BUCKET a quarantined file belongs to is dropped from the
index side, and exactly that bucket's source rows are re-read through a
``Filter(BucketIn(indexed, numBuckets, buckets), Scan(common source
files))`` branch unioned back in — the same merge shape the
appended-files path already uses.  One corrupt bucket costs one bucket's
worth of source IO, not the whole index; PR 2's full source fallback
remains the last resort.
"""

from __future__ import annotations

import os
from typing import FrozenSet, List, Optional, Sequence, Tuple

from hyperspace_tpu.actions.create import DATA_FILE_ID_COLUMN
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.log_entry import FileInfo, IndexLogEntry, IndexLogEntryTags
from hyperspace_tpu.plan.expr import BucketIn, Col, IsIn, Not
from hyperspace_tpu.plan.nodes import (
    BucketUnion,
    Filter,
    LogicalPlan,
    Project,
    Scan,
    ScanRelation,
    Union,
)
from hyperspace_tpu.rules import rule_utils

_HYBRID_INFO_TAG = "hybridScanFileLists"  # (appended, deleted) FileInfo lists
_QUARANTINE_TAG = "quarantineSplit"  # (excluded paths, buckets | None)


def _file_key(f: FileInfo) -> Tuple[str, int, int]:
    return (f.name, f.size, f.mtime)


def get_hybrid_scan_candidates(session, entries: Sequence[IndexLogEntry],
                               scan: Scan) -> List[IndexLogEntry]:
    """RuleUtils.scala:79-133."""
    relation = session.source_provider_manager.get_relation(scan)
    current = relation.all_files()
    current_by_key = {_file_key(f): f for f in current}
    conf = session.conf
    out: List[IndexLogEntry] = []
    # Multi-version index selection: a time-traveled lake read swaps each
    # candidate for its closest indexed version before the overlap math
    # (RuleUtils.scala:96-101 / DeltaLakeRelation.closestIndex).  Only for
    # entries over THIS relation — swapping an unrelated table's index would
    # load its old log versions per query and discard cached tags for
    # nothing (the overlap math excludes it anyway).
    scan_roots = {os.path.abspath(p) for p in relation.root_paths}

    def _same_relation(e: IndexLogEntry) -> bool:
        return any(os.path.abspath(p) in scan_roots
                   for r in e.relations for p in r.root_paths)

    entries = [relation.closest_index(e) if _same_relation(e) else e
               for e in entries]
    for entry in entries:
        cached = entry.get_tag(IndexLogEntryTags.IS_HYBRIDSCAN_CANDIDATE, scan)
        if cached is not None:
            if cached:
                out.append(entry)
            continue
        indexed_keys = {_file_key(f): f for f in entry.source_file_infos()}
        common_keys = indexed_keys.keys() & current_by_key.keys()
        common_bytes = sum(k[1] for k in common_keys)
        appended = [f for k, f in current_by_key.items() if k not in common_keys]
        deleted = [f for k, f in indexed_keys.items() if k not in common_keys]
        appended_bytes = sum(f.size for f in appended)
        deleted_bytes = sum(f.size for f in deleted)
        total_current = common_bytes + appended_bytes
        total_indexed = common_bytes + deleted_bytes
        ok = common_bytes > 0
        if ok and appended_bytes:
            ok = appended_bytes / total_current <= conf.hybrid_scan_max_appended_ratio
        if ok and deleted_bytes:
            ok = (entry.has_lineage_column()
                  and deleted_bytes / total_indexed <= conf.hybrid_scan_max_deleted_ratio)
        entry.set_tag(IndexLogEntryTags.IS_HYBRIDSCAN_CANDIDATE, ok, scan)
        entry.set_tag(IndexLogEntryTags.COMMON_BYTES, common_bytes, scan)
        entry.set_tag(_HYBRID_INFO_TAG, (appended, deleted), scan)
        if ok:
            out.append(entry)
    return out


def quarantined_split(session, entry: IndexLogEntry
                      ) -> Tuple[FrozenSet[str], Optional[Tuple[int, ...]]]:
    """(excluded index file paths, affected bucket ids) for ``entry``.

    A quarantined file poisons its whole BUCKET (a bucket split across
    several files must drop entirely, or the source branch would
    duplicate the healthy siblings' rows).  ``buckets is None`` with a
    non-empty exclusion means the entry is UNUSABLE for containment — a
    quarantined file whose bucket id cannot be recovered from its name,
    or a quarantine covering every file — and candidate selection drops
    it (the query falls back to source, PR 2's behavior).  Memoized per
    optimize pass through the entry tag (tags reset each pass), so the
    quarantine store is listed once per entry per query.
    """
    cached = entry.get_tag(_QUARANTINE_TAG)
    if cached is not None:
        return cached
    from hyperspace_tpu.io.parquet import bucket_id_of_file

    qpaths = session.index_collection_manager \
        .quarantine_manager(entry.name).paths()
    result: Tuple[FrozenSet[str], Optional[Tuple[int, ...]]]
    if not qpaths:
        result = (frozenset(), ())
    else:
        infos = entry.content.file_infos()
        flagged = [f.name for f in infos if f.name in qpaths]
        if not flagged:
            result = (frozenset(), ())
        else:
            buckets = {bucket_id_of_file(p) for p in flagged}
            if None in buckets:
                result = (frozenset(f.name for f in infos), None)
            else:
                excluded = frozenset(
                    f.name for f in infos
                    if bucket_id_of_file(f.name) in buckets)
                if len(excluded) == len(infos):
                    # Nothing healthy left to scan: containment would be
                    # a pure source scan wearing an index costume.
                    result = (excluded, None)
                else:
                    result = (excluded, tuple(sorted(buckets)))
    entry.set_tag(_QUARANTINE_TAG, result)
    return result


def quarantine_excludes_entry(session, entry: IndexLogEntry) -> bool:
    """True when quarantine leaves no usable containment plan for
    ``entry`` (drop it from the candidates; source answers the query)."""
    excluded, buckets = quarantined_split(session, entry)
    return bool(excluded) and buckets is None


def hybrid_file_lists(entry: IndexLogEntry, scan: Scan
                      ) -> Tuple[List[FileInfo], List[FileInfo]]:
    """(appended, deleted) for this entry vs this scan: the candidate-math
    tag when present (set by get_hybrid_scan_candidates), else the lists a
    quick refresh recorded in the entry itself."""
    info = entry.get_tag(_HYBRID_INFO_TAG, scan)
    if info is not None:
        return info
    return entry.appended_files(), entry.deleted_files()


def transform_plan_to_use_hybrid_scan(session, plan: LogicalPlan, target: Scan,
                                      entry: IndexLogEntry,
                                      bucket_union: bool,
                                      prune_to_buckets=None) -> LogicalPlan:
    """RuleUtils.scala:302-443: build the merged index∪appended subtree and
    swap it for ``target``.  ``prune_to_buckets`` restricts the INDEX side's
    buckets (the appended side is unbucketed raw data and always scans)."""
    appended, deleted = hybrid_file_lists(entry, target)
    excluded, qbuckets = quarantined_split(session, entry)
    if excluded and qbuckets is None:
        # Callers filter unusable entries out of the candidates; reaching
        # here means a caller skipped that check — refuse loudly (the
        # degradable rule boundary turns this into a source-scan plan).
        raise HyperspaceError(
            f"index {entry.name!r} has unusable quarantined files")
    if excluded and bucket_union:
        # The join side's merge is bucket-aligned; a source-side bucket
        # branch has no bucket structure to align.  JoinIndexRule drops
        # quarantined entries from its candidates, so this is a guard.
        raise HyperspaceError(
            f"index {entry.name!r} has quarantined buckets; bucket-aligned "
            "join merge is not possible")
    visible_cols = entry.derived_dataset.all_columns

    index_files = None if not excluded else tuple(
        f.name for f in entry.content.file_infos() if f.name not in excluded)
    index_side: LogicalPlan = Scan(rule_utils.index_scan_relation(
        entry, use_bucket_spec=bucket_union or prune_to_buckets is not None,
        prune_to_buckets=prune_to_buckets, file_paths=index_files))
    if deleted:
        # Filter(Not(In(lineage, deleted ids))) (RuleUtils.scala:399-408).
        deleted_ids = sorted({f.id for f in deleted})
        index_side = Filter(Not(IsIn(Col(DATA_FILE_ID_COLUMN), deleted_ids)),
                            index_side)
    index_side = Project(visible_cols, index_side)

    src_rel = target.relation
    repair_side: Optional[LogicalPlan] = None
    if qbuckets:
        # Containment branch: the quarantined buckets' rows, re-read from
        # the COMMON source files (recorded minus deleted — appended
        # files' rows come through the appended branch for every bucket,
        # and deleted files' rows must not reappear).  BucketIn uses the
        # build kernel's host mirror, so the branch returns exactly the
        # rows the dropped index files held.
        deleted_keys = {_file_key(f) for f in deleted}
        common = [f for f in entry.source_file_infos()
                  if _file_key(f) not in deleted_keys]
        if common:
            repair_scan = Scan(ScanRelation(
                root_paths=src_rel.root_paths,
                file_format=src_rel.file_format,
                options=src_rel.options,
                file_paths=tuple(f.name for f in common),
            ))
            repair_side = Project(visible_cols, Filter(
                BucketIn(tuple(entry.indexed_columns), entry.num_buckets,
                         qbuckets),
                repair_scan))

    if appended:
        appended_scan = Scan(ScanRelation(
            root_paths=src_rel.root_paths,
            file_format=src_rel.file_format,
            options=src_rel.options,
            file_paths=tuple(f.name for f in appended),
        ))
        appended_side: LogicalPlan = Project(visible_cols, appended_scan)
        cols = tuple(entry.indexed_columns)
        if bucket_union:
            # Join side: appended rows must be routed into the same bucket
            # space so the bucketed merge stays shuffle-free for the index
            # side (RuleUtils.scala:511-570's on-the-fly shuffle).
            merged: LogicalPlan = BucketUnion(
                [index_side, appended_side],
                (entry.num_buckets, cols, cols))
        else:
            # strict: the index ∪ its own source must not silently widen
            # on schema drift (see Union's docstring).
            sides = [index_side, appended_side]
            if repair_side is not None:
                sides.append(repair_side)
            merged = Union(sides, strict=True)
    elif repair_side is not None:
        merged = Union([index_side, repair_side], strict=True)
    else:
        merged = index_side

    def swap(node: LogicalPlan) -> LogicalPlan:
        return merged if node is target else node

    return plan.transform_up(swap)
