"""Candidate rankers: choose the best index(es) among candidates.

Reference contract: index/rankers/FilterIndexRanker.scala:43-58 (hybrid scan:
max common bytes, else head) and index/rankers/JoinIndexRanker.scala:52-90
(prefer equal-bucket pairs, then more buckets, then more common bytes).
Common-bytes tags are keyed by the scan they were computed against
(IndexLogEntry tag semantics, IndexLogEntry.scala:560-603).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from hyperspace_tpu.index.log_entry import IndexLogEntry, IndexLogEntryTags
from hyperspace_tpu.plan.nodes import Scan


def _common_bytes(entry: IndexLogEntry, scan: Scan) -> int:
    v = entry.get_tag(IndexLogEntryTags.COMMON_BYTES, scan)
    return v if v is not None else 0


def rank_filter_indexes(candidates: List[IndexLogEntry], scan: Scan,
                        hybrid_scan: bool) -> Optional[IndexLogEntry]:
    if not candidates:
        return None
    if hybrid_scan:
        return max(candidates, key=lambda e: _common_bytes(e, scan))
    return candidates[0]


def rank_join_index_pairs(
        pairs: List[Tuple[IndexLogEntry, IndexLogEntry]],
        l_scan: Scan, r_scan: Scan,
        hybrid_scan: bool) -> Optional[Tuple[IndexLogEntry, IndexLogEntry]]:
    if not pairs:
        return None

    def key(pair: Tuple[IndexLogEntry, IndexLogEntry]):
        l, r = pair
        equal_buckets = l.num_buckets == r.num_buckets
        if hybrid_scan:
            # Under hybrid scan, maximizing common bytes minimizes the
            # appended/deleted data that must be merged on the fly
            # (JoinIndexRanker.scala:52-72): it outranks bucket count.
            return (equal_buckets, _common_bytes(l, l_scan) + _common_bytes(r, r_scan))
        return (equal_buckets, l.num_buckets + r.num_buckets)

    return max(pairs, key=key)
