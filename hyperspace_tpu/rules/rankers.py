"""Candidate rankers: choose the best index(es) among candidates.

Reference contract: index/rankers/FilterIndexRanker.scala:43-58 (hybrid scan:
max common bytes, else head) and index/rankers/JoinIndexRanker.scala:52-90
(prefer equal-bucket pairs, then more buckets, then more common bytes).
Common-bytes tags are keyed by the scan they were computed against
(IndexLogEntry tag semantics, IndexLogEntry.scala:560-603).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from hyperspace_tpu.index.log_entry import IndexLogEntry, IndexLogEntryTags
from hyperspace_tpu.plan.nodes import Scan


def _common_bytes(entry: IndexLogEntry, scan: Scan) -> int:
    v = entry.get_tag(IndexLogEntryTags.COMMON_BYTES, scan)
    return v if v is not None else 0


def _size_index_files(entry: IndexLogEntry) -> int:
    return sum(f.size for f in entry.content.file_infos())


def _tie_break_key(entry: IndexLogEntry,
                   filter_cols: Optional[Sequence[str]]) -> tuple:
    """Deterministic ranking of equally-applicable filter candidates.

    Primary: a candidate whose FIRST indexed column appears in the
    predicate outranks one admitted only through the Z-order any-column
    relaxation — the leading column is what bucket pruning and the sort
    order accelerate.  Then the stability tie-break: fewest included
    columns (least over-covering => least data read per row), smallest
    ``sizeIndexFiles``, then name.  The reference returns head() here
    (FilterIndexRanker.scala:55-57), which made the winner depend on
    log-listing discovery order: plans — and advisor what-if results —
    flapped across runs whenever two indexes covered the same query."""
    first_not_filtered = 1
    if filter_cols is not None and entry.indexed_columns:
        lowered = {c.lower() for c in filter_cols}
        first_not_filtered = \
            0 if entry.indexed_columns[0].lower() in lowered else 1
    return (first_not_filtered, len(entry.included_columns),
            _size_index_files(entry), entry.name)


def rank_filter_indexes(candidates: List[IndexLogEntry], scan: Scan,
                        hybrid_scan: bool,
                        filter_cols: Optional[Sequence[str]] = None
                        ) -> Optional[IndexLogEntry]:
    if not candidates:
        return None
    if hybrid_scan:
        # Max common bytes (JoinIndexRanker.scala:43-58 analog), with
        # common-bytes ties broken by the same deterministic key.
        return min(candidates,
                   key=lambda e: (-_common_bytes(e, scan),)
                   + _tie_break_key(e, filter_cols))
    return min(candidates, key=lambda e: _tie_break_key(e, filter_cols))


def rank_join_index_pairs(
        pairs: List[Tuple[IndexLogEntry, IndexLogEntry]],
        l_scan: Scan, r_scan: Scan,
        hybrid_scan: bool) -> Optional[Tuple[IndexLogEntry, IndexLogEntry]]:
    if not pairs:
        return None

    def key(pair: Tuple[IndexLogEntry, IndexLogEntry]):
        l, r = pair
        equal_buckets = l.num_buckets == r.num_buckets
        if hybrid_scan:
            # Under hybrid scan, maximizing common bytes minimizes the
            # appended/deleted data that must be merged on the fly
            # (JoinIndexRanker.scala:52-72): it outranks bucket count.
            return (equal_buckets, _common_bytes(l, l_scan) + _common_bytes(r, r_scan))
        return (equal_buckets, l.num_buckets + r.num_buckets)

    return max(pairs, key=key)
