"""DataSkippingFilterRule: shrink a scan's file list using per-file sketches.

Runs after the covering-index rules (a full rewrite beats file pruning).
Pattern: the same Filter-over-Scan shapes FilterIndexRule matches.  For each
top-level conjunct of the predicate that constrains exactly one sketched
column with ==/</<=/>/>=/IN, a file whose [min, max] interval cannot satisfy
the constraint is dropped from the scan's file list.  The scan still reads
the SOURCE data — only fewer files of it.

Staleness safety WITHOUT signatures: pruning only ever drops a file that is
(a) present in the sketch under the exact (name, size, mtime) it was
sketched with, and (b) provably non-matching.  Files the sketch has never
seen (appends) or whose stats changed (rewrites) always survive, so a stale
sketch can only prune less, never wrongly — the index stays useful through
source mutations with no hybrid-scan machinery.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.actions.data_skipping import (
    SKETCH_FILE_MTIME,
    SKETCH_FILE_NAME,
    SKETCH_FILE_SIZE,
    SKETCH_ROW_COUNT,
    _bloom_col,
    _max_col,
    _min_col,
    _null_col,
    _values_col,
    bloom_may_contain,
    bloom_positions,
    read_sketch,
)
from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.plan.expr import (
    And,
    BinOp,
    Col,
    Expr,
    IsIn,
    IsNull,
    Lit,
    Not,
    Or,
)
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.rules import rule_utils
from hyperspace_tpu.rules.filter_rule import _extract_filter_nodes
from hyperspace_tpu.telemetry.events import HyperspaceIndexUsageEvent, emit_event

# In-process memo of loaded sketches keyed by the sketch files' identity
# (name, size, mtime): correct across rebuilds AND across same-name indexes
# in different system paths — (name, log id) would collide there.
_SKETCH_CACHE: Dict[Tuple, List[dict]] = {}
_SKETCH_CACHE_MAX = 64


class _Constraint:
    """Closed-interval + optional value-set constraint on one column."""

    def __init__(self) -> None:
        self.lo = None          # value, inclusive unless lo_open
        self.lo_open = False
        self.hi = None
        self.hi_open = False
        self.values: Optional[set] = None  # IN / == value set
        # Explicit null-ness constraints (IS NULL / IS NOT NULL):
        # sketches store per-file null counts, so a file with no nulls
        # cannot satisfy IS NULL, and an all-null file cannot satisfy
        # IS NOT NULL.
        self.require_null = False
        self.require_non_null = False

    def add_cmp(self, op: str, value) -> None:
        if op == "==":
            self.values = {value} if self.values is None \
                else self.values & {value}
        elif op in (">", ">="):
            if self.lo is None or value > self.lo or \
                    (value == self.lo and op == ">"):
                self.lo, self.lo_open = value, op == ">"
        elif op in ("<", "<="):
            if self.hi is None or value < self.hi or \
                    (value == self.hi and op == "<"):
                self.hi, self.hi_open = value, op == "<"

    def add_values(self, values) -> None:
        vs = set(values)
        self.values = vs if self.values is None else self.values & vs

    def file_may_match(self, fmin, fmax) -> bool:
        """Could a file with non-null range [fmin, fmax] hold a matching
        row?  ``None`` min/max means the file has no non-null values — no
        predicate matches null, so it cannot."""
        if fmin is None or fmax is None:
            return False
        try:
            if self.values is not None:
                if not any(fmin <= v <= fmax for v in self.values):
                    return False
            if self.lo is not None:
                if fmax < self.lo or (self.lo_open and fmax == self.lo):
                    return False
            if self.hi is not None:
                if fmin > self.hi or (self.hi_open and fmin == self.hi):
                    return False
        except TypeError:
            return True  # incomparable literal/stat types: never mis-prune
        return True


_MIRROR = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "=="}


def _copy(c: _Constraint) -> _Constraint:
    out = _Constraint()
    out.lo, out.lo_open = c.lo, c.lo_open
    out.hi, out.hi_open = c.hi, c.hi_open
    out.values = None if c.values is None else set(c.values)
    out.require_null = c.require_null
    out.require_non_null = c.require_non_null
    return out


def _is_false(c: _Constraint) -> bool:
    """An unsatisfiable constraint: empty value set, or IS NULL combined
    with anything only non-null rows can satisfy."""
    if c.values is not None and len(c.values) == 0:
        return True
    return c.require_null and (c.require_non_null
                               or c.values is not None
                               or c.lo is not None or c.hi is not None)


def _union(a: _Constraint, b: _Constraint) -> Optional[_Constraint]:
    """Sound OR of two single-column constraints: pure value sets union
    exactly; anything involving ranges widens to the covering interval
    (values collapse to [min, max]); unbounded sides make the union
    unconstrained (None).  An unsatisfiable branch (empty value set, e.g.
    from ``a==0 AND a==1``) is the union identity."""
    if _is_false(a):
        return _copy(b)
    if _is_false(b):
        return _copy(a)
    out = _Constraint()
    # Null-ness survives an OR only when BOTH branches require it.
    out.require_null = a.require_null and b.require_null
    out.require_non_null = a.require_non_null and b.require_non_null
    if a.values is not None and b.values is not None \
            and a.lo is None and a.hi is None and b.lo is None and b.hi is None:
        out.values = a.values | b.values
        return out

    def bounds(c: _Constraint):
        lo, lo_open, hi, hi_open = c.lo, c.lo_open, c.hi, c.hi_open
        if c.values is not None:
            try:
                vmin, vmax = min(c.values), max(c.values)
            except TypeError:
                return None
            lo = vmin if lo is None else min(lo, vmin)
            hi = vmax if hi is None else max(hi, vmax)
            lo_open = hi_open = False
        return lo, lo_open, hi, hi_open

    def flags_only():
        return out if (out.require_null or out.require_non_null) else None

    ba, bb = bounds(a), bounds(b)
    if ba is None or bb is None:
        return flags_only()
    try:
        if ba[0] is None or bb[0] is None:
            out.lo = None
        else:
            out.lo, out.lo_open = min((ba[0], ba[1]), (bb[0], bb[1]),
                                      key=lambda t: (t[0], t[1]))
        if ba[2] is None or bb[2] is None:
            out.hi = None
        else:
            out.hi, out.hi_open = max((ba[2], not ba[3]), (bb[2], not bb[3]),
                                      key=lambda t: (t[0], t[1]))
            out.hi_open = not out.hi_open
    except TypeError:
        return flags_only()
    if out.lo is None and out.hi is None:
        return flags_only()
    return out


def _intersect_into(target: _Constraint, c: _Constraint) -> None:
    """AND ``c`` into ``target`` (both constrain the same column)."""
    target.require_null |= c.require_null
    target.require_non_null |= c.require_non_null
    if c.values is not None:
        target.values = set(c.values) if target.values is None \
            else target.values & c.values
    if c.lo is not None:
        target.add_cmp(">" if c.lo_open else ">=", c.lo)
    if c.hi is not None:
        target.add_cmp("<" if c.hi_open else "<=", c.hi)


def _analyze(expr: Expr) -> Optional[Dict[str, _Constraint]]:
    """Per-column constraints implied by ``expr`` (names lowercased).
    {} = no usable constraint; never over-constrains (pruning stays
    conservative): an AND merges by intersection, an OR keeps only columns
    constrained on BOTH branches, merged by sound union."""
    if isinstance(expr, BinOp) and expr.op in _MIRROR:
        c = _Constraint()
        if isinstance(expr.left, Col) and isinstance(expr.right, Lit):
            c.add_cmp(expr.op, expr.right.value)
            return {expr.left.name.lower(): c}
        if isinstance(expr.right, Col) and isinstance(expr.left, Lit):
            c.add_cmp(_MIRROR[expr.op], expr.left.value)
            return {expr.right.name.lower(): c}
        return {}
    if isinstance(expr, IsIn) and isinstance(expr.child, Col):
        c = _Constraint()
        c.add_values(expr.values)
        return {expr.child.name.lower(): c}
    if isinstance(expr, IsNull) and isinstance(expr.child, Col):
        c = _Constraint()
        c.require_null = True
        return {expr.child.name.lower(): c}
    if isinstance(expr, Not) and isinstance(expr.child, IsNull) \
            and isinstance(expr.child.child, Col):
        c = _Constraint()
        c.require_non_null = True
        return {expr.child.child.name.lower(): c}
    if isinstance(expr, And):
        left = _analyze(expr.left) or {}
        right = _analyze(expr.right) or {}
        out = dict(left)
        for name, c in right.items():
            if name in out:
                _intersect_into(out[name], c)
            else:
                out[name] = c
        return out
    if isinstance(expr, Or):
        left = _analyze(expr.left)
        right = _analyze(expr.right)
        if not left or not right:
            return {}  # an unconstrained branch admits anything
        out: Dict[str, _Constraint] = {}
        for name in left.keys() & right.keys():
            u = _union(left[name], right[name])
            if u is not None:
                out[name] = u
        return out
    return {}


def extract_constraints(condition: Expr,
                        sketched: List[str]) -> Dict[str, _Constraint]:
    """Per-column constraints over the sketched columns.  Conjunctions
    intersect; disjunctions union soundly (pure value sets exactly, ranges
    as covering intervals) — so ``a == 1 OR a == 5`` prunes by the value
    pair and ``(a BETWEEN 1 AND 5) OR (a BETWEEN 90 AND 95)`` by the
    covering interval [1, 95]; opposite-unbounded sides (``a<3 OR a>90``)
    correctly yield no constraint.  NOT and other shapes contribute
    nothing (always conservative)."""
    analyzed = _analyze(condition) or {}
    lowered = {c.lower(): c for c in sketched}
    return {lowered[name]: c for name, c in analyzed.items()
            if name in lowered}


class _TypedProbe:
    """The constraint's equality/IN probe values COERCED to the sketched
    column's stored type — the same coercion execution applies to literals
    (executor's _arrow_eval cast), so membership tests agree with what a
    scan would actually match.  Uncoercible probes disable value-based
    pruning for the column (always conservative)."""

    def __init__(self, values=None, positions=None) -> None:
        self.values = values        # set of typed python values, or None
        self.positions = positions  # bloom bit positions, or None


def _typed_probe(entry: IndexLogEntry, col_name: str,
                 constraint: _Constraint, sketch_type: str) -> _TypedProbe:
    if not constraint.values:
        return _TypedProbe()
    type_str = entry.derived_dataset.schema.get(col_name)
    if not type_str:
        return _TypedProbe()
    import pyarrow as pa

    from hyperspace_tpu.io.parquet import _dtype_from_string

    try:
        arr = pa.array(sorted(constraint.values, key=repr),
                       type=_dtype_from_string(type_str))
    except (pa.ArrowInvalid, pa.ArrowTypeError, ValueError, TypeError):
        return _TypedProbe()
    positions = bloom_positions(arr) if sketch_type == "BloomFilter" else None
    return _TypedProbe(set(arr.to_pylist()), positions)


def _file_ok(row: dict, col_name: str, constraint: _Constraint,
             probe: _TypedProbe) -> bool:
    if _is_false(constraint):
        return False
    nulls = row.get(_null_col(col_name))
    if constraint.require_null and nulls is not None and nulls == 0:
        return False  # no null anywhere in the file: IS NULL never holds
    if constraint.require_non_null:
        rows = row.get(SKETCH_ROW_COUNT)
        if nulls is not None and rows is not None and nulls >= rows:
            return False  # all-null file: IS NOT NULL never holds
    if constraint.require_null:
        # A null row satisfies no range/value constraint, so when ONLY
        # null rows are wanted the min/max checks below do not apply.
        return True
    fvalues = row.get(_values_col(col_name))
    if constraint.values is not None and fvalues is not None \
            and probe.values is not None:
        if not (set(fvalues) & probe.values):
            return False
    if not constraint.file_may_match(row.get(_min_col(col_name)),
                                     row.get(_max_col(col_name))):
        return False
    bloom = row.get(_bloom_col(col_name))
    if bloom is not None and probe.positions is not None \
            and not bloom_may_contain(bloom, probe.positions):
        return False
    return True


def _sketch_rows(entry: IndexLogEntry) -> List[dict]:
    key = tuple(sorted((f.name, f.size, f.mtime)
                       for f in entry.content.file_infos()))
    rows = _SKETCH_CACHE.get(key)
    if rows is None:
        rows = read_sketch(entry).to_pylist()
        if len(_SKETCH_CACHE) >= _SKETCH_CACHE_MAX:
            _SKETCH_CACHE.clear()
        _SKETCH_CACHE[key] = rows
    return rows


class DataSkippingFilterRule:
    def __init__(self, session,
                 entries: Optional[List[IndexLogEntry]] = None) -> None:
        self.session = session
        self._entries = entries

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        """Prune EVERY matching filter site in one forward pass
        (transform_up keeps untouched subtrees' identities; the session
        uniquifies the plan, so identity swaps touch exactly one site)."""
        files_memo: Dict = {}  # relation value -> listed files, per pass
        for matched in _extract_filter_nodes(plan):
            new_plan = self._try_apply(plan, matched, files_memo)
            if new_plan is not None:
                plan = new_plan
        return plan

    def _try_apply(self, plan: LogicalPlan, matched,
                   files_memo: Dict) -> Optional[LogicalPlan]:
        scan, filter_node, _ = matched
        if rule_utils.is_index_applied(scan) or \
                scan.relation.data_skipping_of is not None:
            return None
        spm = self.session.source_provider_manager
        if not spm.is_supported_relation(scan):
            return None

        entries = self._entries
        if entries is None:
            entries = self.session.index_collection_manager.get_indexes(
                [States.ACTIVE])
        ds_entries = [e for e in entries if not e.is_covering]
        if not ds_entries:
            return None

        # Cheap predicate check FIRST: the file listing (a full directory
        # walk + stat) only happens when some entry can actually constrain.
        # A bare IS NOT NULL (the ubiquitous join null-guard) is NOT
        # actionable on its own — it could only drop fully-all-null
        # files, which almost never exist, so paying the listing for it
        # on every such query would be a poor trade.
        def actionable(c: _Constraint) -> bool:
            return (c.values is not None or c.lo is not None
                    or c.hi is not None or c.require_null)

        with_constraints = []
        for entry in ds_entries:
            constraints = extract_constraints(
                filter_node.condition, entry.derived_dataset.sketched_columns)
            if constraints and any(actionable(c)
                                   for c in constraints.values()):
                with_constraints.append((entry, constraints))
        if not with_constraints:
            return None

        memo_key = scan.relation
        if memo_key not in files_memo:
            files_memo[memo_key] = spm.get_relation(scan).all_files()
        current = files_memo[memo_key]
        best: Optional[Tuple[IndexLogEntry, List[str]]] = None
        for entry, constraints in with_constraints:
            sketch_by_key = {
                (r[SKETCH_FILE_NAME], r[SKETCH_FILE_SIZE],
                 r[SKETCH_FILE_MTIME]): r
                for r in _sketch_rows(entry)
            }
            type_by_col = dict(zip(entry.derived_dataset.sketched_columns,
                                   entry.derived_dataset.sketch_types))
            probes = {col: _typed_probe(entry, col, c,
                                        type_by_col.get(col, "MinMax"))
                      for col, c in constraints.items()}
            surviving: List[str] = []
            for f in current:
                row = sketch_by_key.get((f.name, f.size, f.mtime))
                if row is None:
                    surviving.append(f.name)  # unknown to the sketch: keep
                    continue
                ok = all(_file_ok(row, col, c, probes[col])
                         for col, c in constraints.items())
                if ok:
                    surviving.append(f.name)
            if len(surviving) < len(current):
                if best is None or len(surviving) < len(best[1]):
                    best = (entry, surviving)
        if best is None:
            return None
        entry, surviving = best
        if not surviving:
            # Provably empty result; keep one file so the scan retains its
            # schema — the filter yields zero rows from it.
            surviving = [current[0].name]

        import dataclasses as dc

        new_rel = dc.replace(scan.relation,
                             file_paths=tuple(surviving),
                             data_skipping_of=entry.name,
                             data_skipping_stats=(len(surviving), len(current)))
        new_scan = Scan(new_rel)

        def swap(node: LogicalPlan) -> LogicalPlan:
            return new_scan if node is scan else node

        new_plan = plan.transform_up(swap)
        emit_event(HyperspaceIndexUsageEvent(
            index_names=[entry.name],
            plan_before=plan.tree_string(),
            plan_after=new_plan.tree_string(),
            message="DataSkippingFilterRule applied"))
        return new_plan


# ---------------------------------------------------------------------------
# Index-file pruning for covering indexes (the Z-order payoff)
# ---------------------------------------------------------------------------
_INDEX_SKETCH_CACHE: Dict[Tuple, List[dict]] = {}


def _load_index_sketch(path: str) -> List[dict]:
    import os

    import pyarrow.parquet as pq

    st = os.stat(path)
    key = (path, st.st_size, st.st_mtime_ns)
    rows = _INDEX_SKETCH_CACHE.get(key)
    if rows is None:
        from hyperspace_tpu.io.parquet import read_parquet_file

        rows = read_parquet_file(path).to_pylist()
        if len(_INDEX_SKETCH_CACHE) >= _SKETCH_CACHE_MAX:
            _INDEX_SKETCH_CACHE.clear()
        _INDEX_SKETCH_CACHE[key] = rows
    return rows


def prune_index_files_by_sketch(entry: IndexLogEntry, condition: Expr
                                ) -> Optional[Tuple[List[str], int]]:
    """For a covering index, drop index FILES whose per-file min/max (the
    ``_sketch.parquet`` each build version writes) provably excludes the
    predicate.  Returns (surviving file paths, total) or None when nothing
    prunes (no constraints, no sketches, or everything survives).  Versions
    without a sketch keep all their files — always conservative."""
    import os

    if not entry.is_covering:
        return None
    constraints = extract_constraints(condition, entry.indexed_columns)
    # This sketch stores min/max only: a require_null constraint cannot
    # prune here — file_may_match treats None min/max (an all-null file)
    # as non-matching, which is exactly the file holding the NULL rows.
    # And a require_non_null-ONLY constraint (the ubiquitous join
    # null-guard) could only drop fully-all-null index files, which
    # never repays the listing + sketch reads — same actionability
    # trade as DataSkippingFilterRule.  Keep value/range constraints.
    constraints = {c: k for c, k in constraints.items()
                   if not k.require_null
                   and (k.values is not None or k.lo is not None
                        or k.hi is not None)}
    if not constraints:
        return None
    files = [f.name for f in entry.content.file_infos()]
    by_dir: Dict[str, List[str]] = {}
    for f in files:
        by_dir.setdefault(os.path.dirname(f), []).append(f)
    surviving: List[str] = []
    any_sketch = False
    for d, fs in by_dir.items():
        sketch_path = os.path.join(d, "_sketch.parquet")
        if not os.path.isfile(sketch_path):
            surviving.extend(fs)
            continue
        try:
            sketch_rows = _load_index_sketch(sketch_path)
        except Exception:  # noqa: BLE001 — a corrupt/unreadable sketch
            # (torn write, erroring store) must never fail the query;
            # pruning is an optimization, keeping every file is always
            # sound.  InjectedCrash (BaseException) still propagates.
            surviving.extend(fs)
            continue
        any_sketch = True
        by_name = {r[SKETCH_FILE_NAME]: r for r in sketch_rows}
        for f in fs:
            row = by_name.get(f)
            if row is None:
                surviving.append(f)
                continue
            ok = all(
                c.file_may_match(row.get(_min_col(col)),
                                 row.get(_max_col(col)))
                for col, c in constraints.items())
            if ok:
                surviving.append(f)
    if not any_sketch or len(surviving) >= len(files):
        return None
    if not surviving:
        surviving = [files[0]]  # keep schema; filter yields zero rows
    return surviving, len(files)
