"""The engine session: conf, readers, optimizer hook, and schema resolution.

Plays the role SparkSession plays for the reference: holds configuration
(HyperspaceConf), the source provider manager, and the optimizer-extension
switch ``enable_hyperspace()/disable_hyperspace()/is_hyperspace_enabled()``
(package.scala:47-79).  Datasets created from ``session.read`` carry the
session, and ``Dataset.collect()`` consults the switch to decide whether the
rewrite rules run.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan, ScanRelation
from hyperspace_tpu.sources.manager import FileBasedSourceProviderManager


class DataReader:
    """``session.read.parquet(path)`` etc., the DataFrameReader analog."""

    def __init__(self, session: "HyperspaceSession") -> None:
        self._session = session

    def _make(self, fmt: str, *paths: str, **options: str):
        from hyperspace_tpu.dataset import Dataset

        rel = ScanRelation(
            root_paths=tuple(paths),
            file_format=fmt,
            options=tuple(sorted(options.items())),
        )
        return Dataset(Scan(rel), self._session)

    def parquet(self, *paths: str, **options: str):
        return self._make("parquet", *paths, **options)

    def csv(self, *paths: str, **options: str):
        return self._make("csv", *paths, **options)

    def json(self, *paths: str, **options: str):
        return self._make("json", *paths, **options)

    def avro(self, *paths: str, **options: str):
        return self._make("avro", *paths, **options)

    def text(self, *paths: str, **options: str):
        return self._make("text", *paths, **options)

    def orc(self, *paths: str, **options: str):
        return self._make("orc", *paths, **options)

    def delta(self, path: str, **options: str):
        """Read a Delta table; ``versionAsOf``/``timestampAsOf`` options time
        travel (the df.read.format("delta") path of DeltaLakeIntegrationTest)."""
        return self._make("delta", path, **options)

    def iceberg(self, path: str, **options: str):
        """Read an Iceberg table; ``snapshot_id``/``as_of_timestamp`` options
        time travel (the df.read.format("iceberg") path of
        IcebergIntegrationTest; option names per IcebergRelation.scala:50-55)."""
        renamed = {k.replace("_", "-"): v for k, v in options.items()}
        return self._make("iceberg", path, **renamed)

    def format(self, fmt: str):
        reader = self

        class _FormatReader:
            def load(self, *paths: str, **options: str):
                return reader._make(fmt, *paths, **options)

        return _FormatReader()


class HyperspaceSession:
    def __init__(self, system_path: Optional[str] = None,
                 conf: Optional[HyperspaceConf] = None) -> None:
        self.conf = conf if conf is not None else HyperspaceConf()
        if system_path is not None:
            self.conf.system_path = system_path
        self._hyperspace_enabled = False
        if self.conf.event_logger:
            # The reflective eventLoggerClass conf
            # (HyperspaceEventLogging.scala:42-64).
            from hyperspace_tpu.telemetry.events import apply_conf_event_logger

            apply_conf_event_logger(self.conf.event_logger)
        if self.conf.fault_injection_enabled:
            # Deterministic fault injection (io/faults.py) armed via conf:
            # lets multi-process crash tests configure a child process
            # through its session conf alone.
            from hyperspace_tpu.io import faults

            faults.install_from_conf(self.conf)
        # Digest-on-write for index data files (io/integrity.py); actions
        # re-apply before each build so later conf.set() calls also win.
        from hyperspace_tpu.io import integrity

        integrity.configure_from_conf(self.conf)
        # Observability conf (telemetry/trace.py): span tracing + JSONL
        # sink.  Re-applied per query (Dataset.collect) so conf.set()
        # after construction also wins.
        from hyperspace_tpu.telemetry import trace

        trace.configure_from_conf(self.conf)
        self._schema_cache: Dict[object, Dict[str, str]] = {}
        # optimize() mutates shared state (the cached IndexLogEntry tags it
        # clears per pass), so concurrent queries — e.g. interop server
        # threads — serialize the OPTIMIZE step only; execution runs
        # outside the lock.
        import threading

        self._optimize_lock = threading.RLock()
        # Lake-schema memo, live only inside one optimize() pass: a query
        # sees one snapshot, so memoizing there is safe; across queries it
        # would go stale (overwrites can change the schema mid-session).
        # THREAD LOCAL: schema_map_of also runs outside the optimize lock
        # (executor mesh-join gates, hybrid-scan checks), so another
        # thread's in-flight pass must never see — or populate — this
        # thread's snapshot memo.
        self._lake_memo_tls = threading.local()
        # Physical stats of the most recent Dataset.collect() — THREAD
        # LOCAL so a server thread's query can never overwrite the stats a
        # local caller reads right after its own collect()
        # (see Executor.stats; the property pair below).
        self._exec_stats = threading.local()
        self.last_execution_stats = None
        # Run report of the most recent Dataset.collect() — THREAD LOCAL
        # for the same reason (telemetry/report.py; ds.last_run_report()).
        self._run_report = threading.local()
        # Build report of the most recent ACTION run through this session
        # (telemetry/build_report.py; Hyperspace.last_build_report()).
        # Session-wide, not thread-local: builds are rare, serialized by
        # the log protocol, and "what did the last build cost" is a
        # whole-session question (the interop build_report verb reads it
        # from a server thread).
        self.last_build_report_value = None
        # Fleet heartbeat publisher (telemetry/fleet.py): conf-gated off
        # by default; when hyperspace.fleet.telemetry.enabled is set at
        # construction the daemon thread starts here so every process
        # of a fleet shows up in fleet_status() without extra wiring
        # (conf set later goes through Hyperspace.start_fleet_telemetry).
        from hyperspace_tpu.telemetry import alerts, fleet

        fleet.maybe_start(self)
        # SLO alert engine (telemetry/alerts.py): same conf-gated,
        # never-raises pattern (hyperspace.alerts.enabled; conf set
        # later goes through Hyperspace.start_alerting).
        alerts.maybe_start(self)

    @property
    def _lake_schema_memo(self) -> Optional[Dict[object, Dict[str, str]]]:
        return getattr(self._lake_memo_tls, "memo", None)

    @_lake_schema_memo.setter
    def _lake_schema_memo(
            self, value: Optional[Dict[object, Dict[str, str]]]) -> None:
        self._lake_memo_tls.memo = value

    @property
    def last_execution_stats(self) -> Optional[Dict[str, list]]:
        return getattr(self._exec_stats, "value", None)

    @last_execution_stats.setter
    def last_execution_stats(self, value: Optional[Dict[str, list]]) -> None:
        self._exec_stats.value = value

    @property
    def last_run_report_value(self):
        return getattr(self._run_report, "value", None)

    @last_run_report_value.setter
    def last_run_report_value(self, value) -> None:
        self._run_report.value = value

    # -- plumbing -----------------------------------------------------------
    @property
    def read(self) -> DataReader:
        return DataReader(self)

    @property
    def source_provider_manager(self) -> FileBasedSourceProviderManager:
        # Rebuilt per access so conf changes take effect (CacheWithTransform
        # analog, util/CacheWithTransform.scala:31-45, without the cache —
        # construction is cheap here).
        return FileBasedSourceProviderManager(self.conf, session=self)

    def schema_of(self, scan: Scan) -> List[str]:
        return list(self.schema_map_of(scan).keys())

    def schema_map_of(self, scan: Scan) -> Dict[str, str]:
        # Keyed by the frozen relation value, not object identity: id() can
        # be recycled after GC, and equal relations share one listing.
        # Lake formats are NOT cached: the same relation value (path +
        # options) can point at a different snapshot after an overwrite that
        # changes the schema, so a value-keyed entry would go stale within a
        # session.  Their schema read is metadata-only (no file listing).
        from hyperspace_tpu.sources.interfaces import LAKE_DATA_FORMATS

        if scan.relation.hypothetical \
                and scan.relation.hypothetical_schema is not None:
            # What-if index scans have zero files; the schema rides the
            # relation itself (advisor/hypothetical.py).
            return dict(scan.relation.hypothetical_schema)
        if scan.relation.file_format.lower() in LAKE_DATA_FORMATS \
                and scan.relation.file_paths is None:
            memo = self._lake_schema_memo
            if memo is None:
                return self.source_provider_manager.get_relation(scan).schema()
            if scan.relation not in memo:
                memo[scan.relation] = \
                    self.source_provider_manager.get_relation(scan).schema()
            return memo[scan.relation]
        key = scan.relation
        if key not in self._schema_cache:
            if scan.relation.file_paths is not None:
                from hyperspace_tpu.io.parquet import read_schema

                from hyperspace_tpu.sources.interfaces import physical_read_format

                # The files of one relation share a schema, so any ONE
                # readable footer serves — and a corrupt first file
                # (bit-rot, torn put) must not kill PLANNING when a
                # healthy sibling can answer; the corrupt file itself
                # fails at execution, where quarantine containment
                # (dataset.collect) owns the recovery.
                schema = None
                for i, path in enumerate(scan.relation.file_paths):
                    try:
                        schema = read_schema(
                            path,
                            physical_read_format(scan.relation.file_format),
                            scan.relation.options_dict)
                        break
                    except Exception:  # noqa: BLE001 — unreadable file;
                        # re-raise only if NO file yields a schema
                        if i == len(scan.relation.file_paths) - 1:
                            raise
                if scan.relation.index_scan_of is None:
                    # Source-file subsets (hybrid scan) still carry hive
                    # partition columns parsed below the root paths.
                    from hyperspace_tpu.io.partitions import partition_spec_for_roots

                    for k, t in partition_spec_for_roots(
                            scan.relation.root_paths).items():
                        schema.setdefault(k, t)
                self._schema_cache[key] = schema
            else:
                rel = self.source_provider_manager.get_relation(scan)
                self._schema_cache[key] = rel.schema()
        return self._schema_cache[key]

    # -- the optimizer switch (package.scala:47-79) -------------------------
    def enable_hyperspace(self) -> "HyperspaceSession":
        self._hyperspace_enabled = True
        return self

    def disable_hyperspace(self) -> "HyperspaceSession":
        self._hyperspace_enabled = False
        return self

    def is_hyperspace_enabled(self) -> bool:
        return self._hyperspace_enabled

    @property
    def index_collection_manager(self):
        """TTL-cached manager (HyperspaceContext analog,
        Hyperspace.scala:168-204)."""
        from hyperspace_tpu.index.cache import CachingIndexCollectionManager

        return CachingIndexCollectionManager(self)

    def optimize(self, plan: LogicalPlan,
                 use_indexes: bool = True,
                 hypothetical=None) -> LogicalPlan:
        """Apply the rewrite rules if enabled — Join before Filter, the fixed
        order with the rationale in package.scala:25-35.  ACTIVE entries are
        loaded once and shared across both rules so per-scan signature
        memoization (tags) carries over (RuleUtils.scala:59-74).

        ``hypothetical`` is the advisor's what-if channel
        (advisor/hypothetical.py; docs/17-advisor.md): extra
        ``IndexLogEntry`` objects tagged hypothetical that this ONE pass
        considers alongside the persisted ACTIVE entries.  The resulting
        plan is for analysis only — its hypothetical scans refuse to
        execute — and entries without the tag are rejected so the channel
        cannot smuggle a real-looking index into planning.

        Column pruning always runs first — the reference's rules sit after
        Catalyst's ColumnPruning, so minimal per-side column requirements are
        a precondition the engine must establish itself (plan/pruning.py); it
        also enables scan-level column pushdown for the non-indexed path."""
        # Reused Dataset objects make the user's plan a DAG (one Scan
        # object under several branches).  Every rewrite below swaps
        # nodes BY IDENTITY, which on a DAG would install one branch's
        # pruning into its siblings — so first rebuild the plan as a
        # tree with a distinct node object per occurrence.
        plan = _uniquify(plan)
        # Subqueries rewrite OUTSIDE the lock: scalar folding and NOT IN
        # materialization EXECUTE whole subplans, and holding the
        # optimize lock for that would serialize every concurrent
        # query's optimize behind one slow subquery (the lock's contract
        # is "serialize the OPTIMIZE step only").  Nested optimize calls
        # for the subplans take the lock briefly themselves.
        from hyperspace_tpu.plan.subquery import rewrite_subqueries
        from hyperspace_tpu.telemetry.trace import span

        with span("optimize", use_indexes=use_indexes):
            plan = rewrite_subqueries(plan, self)
            with self._optimize_lock:
                return self._optimize_locked(plan, use_indexes, hypothetical)

    def _optimize_locked(self, plan: LogicalPlan,
                         use_indexes: bool = True,
                         hypothetical=None) -> LogicalPlan:
        from hyperspace_tpu.plan.pruning import prune_columns

        # Save/restore instead of set/None: subquery folding re-enters
        # optimize() from inside this pass (RLock), and the nested pass
        # must not clear the OUTER pass's snapshot memo on its way out.
        prev_memo = self._lake_schema_memo
        self._lake_schema_memo = {}
        try:
            # WHERE conjuncts sink to the side/scan they constrain
            # (Catalyst's PredicatePushdown role) — required for the SQL
            # front end's canonical filter-above-joins form to reach the
            # Filter-over-scan shapes every rule pattern-matches.
            from hyperspace_tpu.plan.pushdown import push_filters

            plan = push_filters(plan, self.schema_of)
            # THEN year(col)-style predicates over temporal scan columns
            # become raw ranges (plan/temporal.py): canonicalization needs
            # the filter sitting over its scan to see the column type, so
            # it must follow pushdown or SQL-shaped filters-above-joins
            # would keep their opaque Extracts.
            from hyperspace_tpu.plan.temporal import canonicalize_temporal

            plan = canonicalize_temporal(plan, self.schema_map_of)
            plan = prune_columns(plan, self.schema_of)
            # ``use_indexes=False`` is the degraded re-plan channel
            # (Dataset.collect's execution fallback): same normalization,
            # no index rewrites — WITHOUT flipping the session-global
            # enable switch under concurrent queries.
            if not self._hyperspace_enabled or not use_indexes:
                return plan
            from hyperspace_tpu.index.log_entry import States
            from hyperspace_tpu.rules.filter_rule import FilterIndexRule
            from hyperspace_tpu.rules.join_rule import JoinIndexRule

            entries = self.index_collection_manager.get_indexes([States.ACTIVE])
            # Belt-and-braces: the log managers refuse to persist
            # hypothetical entries, so none should ever come back from the
            # listing — but a real query must never plan against one even
            # if that guard regresses.
            entries = [e for e in entries if not e.is_hypothetical]
            if hypothetical:
                bad = [e.name for e in hypothetical if not e.is_hypothetical]
                if bad:
                    from hyperspace_tpu.exceptions import HyperspaceError

                    raise HyperspaceError(
                        f"optimize(hypothetical=...) entries must carry "
                        f"the hypothetical tag; got untagged {bad} — use "
                        f"advisor.hypothetical.hypothetical_entry()")
                entries = entries + list(hypothetical)
            # Cached entries outlive a query; tags memoize per-plan-node
            # state and id()s can be recycled across queries, so start each
            # pass clean.
            for e in entries:
                e._tags.clear()
            from hyperspace_tpu.telemetry import report

            report.record("indexes.considered",
                          names=[e.name for e in entries])
            plan = self._apply_rule_degradable(
                "JoinIndexRule", JoinIndexRule(self, entries).apply, plan)
            plan = self._apply_rule_degradable(
                "FilterIndexRule", FilterIndexRule(self, entries).apply, plan)
            # Filters above join-rewritten index scans still prune buckets
            # (rules/bucket_prune.py).
            from hyperspace_tpu.rules.bucket_prune import BucketPruneRule

            plan = self._apply_rule_degradable(
                "BucketPruneRule", BucketPruneRule(self, entries).apply, plan)
            # Data skipping last: a covering rewrite beats file pruning, and
            # the rule skips scans the other rules already rewrote.
            from hyperspace_tpu.rules.data_skipping import DataSkippingFilterRule

            plan = self._apply_rule_degradable(
                "DataSkippingFilterRule",
                DataSkippingFilterRule(self, entries).apply, plan)
            # The rules rebuild rewritten sides in Filter-above-Project
            # form; one more pushdown + prune reaches the same normal
            # form a second optimize() would — keeping optimize
            # idempotent (the plan-stability suites diff exact trees).
            plan = push_filters(plan, self.schema_of)
            plan = prune_columns(plan, self.schema_of)
            return plan
        finally:
            self._lake_schema_memo = prev_memo

    def _apply_rule_degradable(self, rule_name: str, apply_fn,
                               plan: LogicalPlan) -> LogicalPlan:
        """Degraded-mode boundary for one rewrite rule: a rule that dies
        reading index metadata/sketches (erroring store, corrupt files)
        must cost the query its acceleration, never its answer — the plan
        is returned un-rewritten and telemetry records the degradation
        (``hyperspace.system.degraded.fallbackToSource``; strict mode
        re-raises).  InjectedCrash is a BaseException and still
        propagates: a simulated process death is not a fallback.

        Observability boundary too: each rule gets a span and a run-report
        decision (applied / no match / skipped+reason) plus a
        ``rule.<slug>.applied`` counter — the one seam every rewrite rule
        passes through."""
        from hyperspace_tpu.telemetry import metrics, report
        from hyperspace_tpu.telemetry.trace import span

        slug = _rule_slug(rule_name)
        with span(f"optimize.rule.{slug}") as sp:
            try:
                new_plan = apply_fn(plan)
            except Exception as e:  # noqa: BLE001 — the contract is "any
                # index-side failure degrades"; source-side failures
                # surface again when the fallback plan executes the
                # source scan.
                if not self.conf.degraded_fallback_to_source:
                    raise
                from hyperspace_tpu.telemetry.events import (
                    IndexDegradedEvent,
                    emit_event,
                )

                sp.set(applied=False, skipped=repr(e))
                metrics.inc(f"rule.{slug}.skipped")
                report.record("rule", rule=rule_name, applied=False,
                              skipped_reason=f"{e!r}")
                emit_event(IndexDegradedEvent(
                    reason=f"{rule_name} failed: {e!r}",
                    message=f"{rule_name} skipped; query answers from the "
                            "source scan"))
                return plan
            applied = new_plan is not plan
            sp.set(applied=applied)
            if applied:
                metrics.inc(f"rule.{slug}.applied")
            report.record("rule", rule=rule_name, applied=applied)
            return new_plan


def _rule_slug(rule_name: str) -> str:
    """``FilterIndexRule`` → ``filter``, ``BucketPruneRule`` →
    ``bucket_prune`` — the metric-catalog naming of a rule class."""
    name = rule_name
    for suffix in ("Rule", "Index", "Filter"):
        if name.endswith(suffix) and name != suffix:
            name = name[:-len(suffix)]
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


def _uniquify(plan: LogicalPlan) -> LogicalPlan:
    """A structurally identical plan in which no node object appears twice
    (frozen ScanRelation values stay shared — only plan NODES are remade)."""
    new_children = tuple(_uniquify(c) for c in plan.children)
    if isinstance(plan, Scan):
        return Scan(plan.relation)
    return plan.with_children(new_children)
