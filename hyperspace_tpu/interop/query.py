"""JSON query spec -> Dataset: the wire form of the engine's plan verbs.

A spec is one JSON object:

    {"source": {"format": "parquet", "path": "/data/lineitem"},
     "filter": {"op": ">=", "col": "l_orderkey", "value": 100},
     "select": ["l_orderkey", "l_quantity"],
     "join":   {"source": {...}, "on": {"op": "==", "col": "a",
                                        "right_col": "b"}},
     "group_by": ["l_orderkey"],
     "aggs":   {"total": ["l_quantity", "sum"]}}

Verbs compose in the engine's canonical order: source -> filter -> join
-> group_by/aggs -> sort -> limit -> select (a select before grouping is
expressed by the pruning pass anyway).  Expressions use the same operator
names as the plan IR (==, <, <=, >, >=, and, or, not, in, is_null).
"""

from __future__ import annotations

from typing import Any, Dict

from hyperspace_tpu.plan.expr import (
    And,
    Arith,
    BinOp,
    Case,
    Cast,
    Col,
    Expr,
    Extract,
    InSubquery,
    IsIn,
    IsNull,
    Lit,
    Neg,
    Not,
    Or,
    OuterRef,
    ScalarSubquery,
    StringMatch,
)

# Session in scope while a spec decodes — subquery specs need it to build
# their Dataset trees (thread-local: the interop server decodes
# concurrently on worker threads).
import os
import re
import threading

_SPEC_TLS = threading.local()

# -- wire trace context ------------------------------------------------------
# A request spec may carry ``trace_id`` / ``request_id``: 16 lowercase hex
# chars (8 random bytes), minted by the client so a failure is
# correlatable from EITHER side of the wire.  The server adopts a valid
# id and MINTS its own for a missing/malformed one — a bad trace id must
# never reject a request (observability is advisory, the query is not).
TRACE_ID_HEX_CHARS = 16
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{16}$")


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace/request id (8 random bytes)."""
    return os.urandom(TRACE_ID_HEX_CHARS // 2).hex()


def valid_trace_id(value) -> bool:
    """Exactly 16 lowercase hex chars (uppercase normalizes on adopt)."""
    return isinstance(value, str) and \
        _TRACE_ID_RE.match(value.lower()) is not None


def pop_trace_context(spec):
    """Extract (and remove) the trace context from a decoded request
    spec: ``(trace_id, request_id, adopted)``.  ``adopted`` is True when
    the client's trace_id was usable; malformed/missing ids — wrong
    length, non-hex, non-string — fall back to server-minted ones.
    Never raises: the spec keys are popped even when unusable, so they
    cannot leak into query decoding."""
    raw_trace = spec.pop("trace_id", None)
    raw_request = spec.pop("request_id", None)
    adopted = valid_trace_id(raw_trace)
    trace_id = raw_trace.lower() if adopted else mint_trace_id()
    request_id = raw_request.lower() if valid_trace_id(raw_request) \
        else mint_trace_id()
    return trace_id, request_id, adopted


def _subquery_plan(spec: Dict[str, Any]):
    session = getattr(_SPEC_TLS, "session", None)
    if session is None:
        raise ValueError("Subquery specs are only valid inside a full "
                         "query spec (dataset_from_spec)")
    return dataset_from_spec(session, spec).plan

_CMP_OPS = ("==", "<", "<=", ">", ">=")
_ARITH_OPS = ("+", "-", "*", "/")


def value_expr_from_json(obj: Any) -> Expr:
    """A VALUE expression: bare JSON literal, {"col": name},
    {"value": v}, arithmetic {"op": "+", "left": ..., "right": ...},
    or {"op": "neg", "child": ...}."""
    if not isinstance(obj, dict):
        return Lit(obj)
    op = obj.get("op")
    if op in _ARITH_OPS:
        return Arith(op, value_expr_from_json(obj["left"]),
                     value_expr_from_json(obj["right"]))
    if op == "neg":
        return Neg(value_expr_from_json(obj["child"]))
    if op == "cast":
        return Cast(value_expr_from_json(obj["child"]), obj["type"])
    if op == "extract":
        # {"op": "extract", "field": "year", "child": {"col": "d"}}
        return Extract(obj["field"], value_expr_from_json(obj["child"]))
    if op == "scalar_subquery":
        # {"op": "scalar_subquery", "query": {full query spec}} — the
        # session resolves via the _SPEC_TLS thread-local that
        # dataset_from_spec sets while decoding.
        return ScalarSubquery(_subquery_plan(obj["query"]))
    if op == "outer_ref":
        return OuterRef(obj["name"])
    if op == "case":
        # {"op": "case", "branches": [[cond, value], ...],
        #  "otherwise": value?}  Conditions are BOOLEAN expressions.
        branches = [(expr_from_json(c), value_expr_from_json(v))
                    for c, v in obj["branches"]]
        otherwise = value_expr_from_json(obj["otherwise"]) \
            if "otherwise" in obj else Lit(None)
        return Case(branches, otherwise)
    if op is None and "col" in obj:
        return Col(obj["col"])
    if op is None and "value" in obj:
        return Lit(obj["value"])
    raise ValueError(f"Unknown value expression: {obj!r}")


def expr_from_json(obj: Dict[str, Any]) -> Expr:
    op = obj.get("op")
    if op in _CMP_OPS:
        if "left" in obj:
            # Structured form: both sides are value expressions
            # (arithmetic comparisons like l_ep * l_d > 100).
            return BinOp(op, value_expr_from_json(obj["left"]),
                         value_expr_from_json(obj["right"]))
        left = Col(obj["col"])
        if "right_col" in obj:
            return BinOp(op, left, Col(obj["right_col"]))
        return BinOp(op, left, Lit(obj["value"]))
    if op == "and":
        return And(expr_from_json(obj["left"]), expr_from_json(obj["right"]))
    if op == "or":
        return Or(expr_from_json(obj["left"]), expr_from_json(obj["right"]))
    if op == "not":
        return Not(expr_from_json(obj["child"]))
    if op == "in":
        return IsIn(Col(obj["col"]), list(obj["values"]))
    if op == "is_null":
        return IsNull(Col(obj["col"]))
    if op == "in_subquery":
        # {"op": "in_subquery", "col": "k", "query": {full query spec}};
        # wrap in {"op": "not", ...} for SQL's null-aware NOT IN.
        return InSubquery(Col(obj["col"]), _subquery_plan(obj["query"]))
    if op in StringMatch.KINDS:
        return StringMatch(op, Col(obj["col"]), obj["pattern"])
    raise ValueError(f"Unknown expression op: {op!r}")


# Wire input never reaches arbitrary attributes: explicit reader allowlist.
_SOURCE_FORMATS = ("parquet", "csv", "json", "orc", "avro", "text",
                   "delta", "iceberg")


def _read_source(session, source: Dict[str, Any]):
    fmt = source.get("format", "parquet")
    if fmt not in _SOURCE_FORMATS:
        raise ValueError(f"Unknown source format: {fmt!r}")
    path = source["path"]
    options = source.get("options", {})
    reader = getattr(session.read, fmt)
    return reader(path, **options) if options else reader(path)


def dataset_from_spec(session, spec: Dict[str, Any]):
    """Build a Dataset from ``spec`` against ``session`` (whose hyperspace
    enablement and indexes govern rewrites, exactly as for local use)."""
    prev = getattr(_SPEC_TLS, "session", None)
    _SPEC_TLS.session = session
    try:
        return _dataset_from_spec(session, spec)
    finally:
        _SPEC_TLS.session = prev


def _dataset_from_spec(session, spec: Dict[str, Any]):
    ds = _read_source(session, spec["source"])
    if "filter" in spec:
        ds = ds.filter(expr_from_json(spec["filter"]))
    if "join" in spec:
        j = spec["join"]
        other = _read_source(session, j["source"])
        if "filter" in j:
            other = other.filter(expr_from_json(j["filter"]))
        ds = ds.join(other, expr_from_json(j["on"]), j.get("how", "inner"))
    if "union" in spec:
        # UNION ALL with another full spec (query.py composes recursively).
        ds = ds.union(dataset_from_spec(session, spec["union"]))
    if "aggs" in spec or "group_by" in spec:
        grouped = ds.group_by(*spec.get("group_by", []))
        # {out: [col_or_value_expr, func]}; expression inputs arrive as
        # structured objects (value_expr_from_json).
        aggs = {out: (value_expr_from_json(src) if isinstance(src, dict)
                      else src, func)
                for out, (src, func) in spec.get("aggs", {}).items()}
        ds = grouped.agg(**aggs) if aggs else grouped.count()
    if "window" in spec:
        # [{"name": out, "func": "rank", "partition_by": [...],
        #   "order_by": ["c" | ["c", false], ...], "value": "v"?}, ...]
        for w in spec["window"]:
            keys = [k if isinstance(k, str) else tuple(k)
                    for k in w.get("order_by", [])]
            ds = ds.with_window(w["name"], w["func"],
                                partition_by=w.get("partition_by", ()),
                                order_by=keys, value=w.get("value"))
    if "qualify" in spec:
        # SQL QUALIFY: a filter over window outputs ("filter" runs
        # before windows, like WHERE).
        ds = ds.filter(expr_from_json(spec["qualify"]))
    if "sort" in spec:
        # ["col", ...] or [["col", false], ...] for descending; malformed
        # entries fail Dataset.sort's validation with a clear message.
        keys = [k if isinstance(k, str) else tuple(k) for k in spec["sort"]]
        ds = ds.sort(*keys)
    if "limit" in spec:
        ds = ds.limit(int(spec["limit"]))
    if "select" in spec:
        # Entries are column names, or {"name": out, "expr": value-expr}
        # for computed projections.  When any computed entry is present the
        # Compute node is built directly in spec order — Dataset.select's
        # names-then-keywords signature would move computed columns after
        # all plain names, losing the caller's interleaving.
        entries = spec["select"]
        if any(isinstance(c, dict) for c in entries):
            from hyperspace_tpu.dataset import Dataset
            from hyperspace_tpu.plan.nodes import Compute

            exprs = [(c, Col(c)) if isinstance(c, str)
                     else (c["name"], value_expr_from_json(c["expr"]))
                     for c in entries]
            ds = Dataset(Compute(exprs, ds.plan), ds.session)
        else:
            ds = ds.select(*entries)
    return ds
