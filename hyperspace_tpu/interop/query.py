"""JSON query spec -> Dataset: the wire form of the engine's plan verbs.

A spec is one JSON object:

    {"source": {"format": "parquet", "path": "/data/lineitem"},
     "filter": {"op": ">=", "col": "l_orderkey", "value": 100},
     "select": ["l_orderkey", "l_quantity"],
     "join":   {"source": {...}, "on": {"op": "==", "col": "a",
                                        "right_col": "b"}},
     "group_by": ["l_orderkey"],
     "aggs":   {"total": ["l_quantity", "sum"]}}

Verbs compose in the engine's canonical order: source -> filter -> join
-> group_by/aggs -> sort -> limit -> select (a select before grouping is
expressed by the pruning pass anyway).  Expressions use the same operator
names as the plan IR (==, <, <=, >, >=, and, or, not, in, is_null).
"""

from __future__ import annotations

from typing import Any, Dict

from hyperspace_tpu.plan.expr import (
    And,
    BinOp,
    Col,
    Expr,
    IsIn,
    IsNull,
    Lit,
    Not,
    Or,
)

_CMP_OPS = ("==", "<", "<=", ">", ">=")


def expr_from_json(obj: Dict[str, Any]) -> Expr:
    op = obj.get("op")
    if op in _CMP_OPS:
        left = Col(obj["col"])
        if "right_col" in obj:
            return BinOp(op, left, Col(obj["right_col"]))
        return BinOp(op, left, Lit(obj["value"]))
    if op == "and":
        return And(expr_from_json(obj["left"]), expr_from_json(obj["right"]))
    if op == "or":
        return Or(expr_from_json(obj["left"]), expr_from_json(obj["right"]))
    if op == "not":
        return Not(expr_from_json(obj["child"]))
    if op == "in":
        return IsIn(Col(obj["col"]), list(obj["values"]))
    if op == "is_null":
        return IsNull(Col(obj["col"]))
    raise ValueError(f"Unknown expression op: {op!r}")


# Wire input never reaches arbitrary attributes: explicit reader allowlist.
_SOURCE_FORMATS = ("parquet", "csv", "json", "orc", "avro", "text",
                   "delta", "iceberg")


def _read_source(session, source: Dict[str, Any]):
    fmt = source.get("format", "parquet")
    if fmt not in _SOURCE_FORMATS:
        raise ValueError(f"Unknown source format: {fmt!r}")
    path = source["path"]
    options = source.get("options", {})
    reader = getattr(session.read, fmt)
    return reader(path, **options) if options else reader(path)


def dataset_from_spec(session, spec: Dict[str, Any]):
    """Build a Dataset from ``spec`` against ``session`` (whose hyperspace
    enablement and indexes govern rewrites, exactly as for local use)."""
    ds = _read_source(session, spec["source"])
    if "filter" in spec:
        ds = ds.filter(expr_from_json(spec["filter"]))
    if "join" in spec:
        j = spec["join"]
        other = _read_source(session, j["source"])
        if "filter" in j:
            other = other.filter(expr_from_json(j["filter"]))
        ds = ds.join(other, expr_from_json(j["on"]), j.get("how", "inner"))
    if "aggs" in spec or "group_by" in spec:
        grouped = ds.group_by(*spec.get("group_by", []))
        aggs = spec.get("aggs", {})  # {out: [col, func]} unpacks in agg()
        ds = grouped.agg(**aggs) if aggs else grouped.count()
    if "sort" in spec:
        # ["col", ...] or [["col", false], ...] for descending; malformed
        # entries fail Dataset.sort's validation with a clear message.
        keys = [k if isinstance(k, str) else tuple(k) for k in spec["sort"]]
        ds = ds.sort(*keys)
    if "limit" in spec:
        ds = ds.limit(int(spec["limit"]))
    if "select" in spec:
        ds = ds.select(*spec["select"])
    return ds
