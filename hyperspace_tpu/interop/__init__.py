"""Language-neutral interop surface.

The reference exposes its API to non-JVM hosts via py4j bindings
(python/hyperspace/hyperspace.py:9) and ships a .NET sample
(examples/csharp/HyperspaceApp/Program.cs).  This package is the
equivalent for a Python-native engine: queries arrive as a JSON spec
(interop/query.py) over a socket and results return as an Arrow IPC
stream (interop/server.py) — consumable from Java/C#/Go/Rust/JS through
any Arrow implementation, no Python required on the client.
"""

from hyperspace_tpu.interop.query import (
    dataset_from_spec,
    expr_from_json,
    mint_trace_id,
    pop_trace_context,
    valid_trace_id,
)
from hyperspace_tpu.interop.server import (
    FleetQueryClient,
    QueryClient,
    QueryFailedError,
    QueryServer,
    ServerBusyError,
    parse_wire_error,
    request_query,
)

__all__ = ["dataset_from_spec", "expr_from_json", "mint_trace_id",
           "pop_trace_context", "valid_trace_id", "FleetQueryClient",
           "QueryClient", "QueryFailedError", "QueryServer",
           "ServerBusyError", "parse_wire_error", "request_query"]
